"""Dependency-free Avro: schema parser + binary codec + GenericRecord analog.

Parity: the reference carries Avro values end-to-end — records read from
Kafka hold GenericRecords, the agents-commons transforms mutate them, and the
gRPC agent protocol interns schemas per stream
(`langstream-agents/langstream-agent-grpc/.../agent.proto:37-48`,
`langstream-agents-commons/.../AvroUtil.java`). This module supplies the
codec those layers need, implemented from the Avro 1.11 specification
(binary encoding + canonical-form fingerprinting); no avro library ships in
the image.

Supported: null, boolean, int, long, float, double, bytes, string, record,
enum, array, map, union, fixed; logical types pass through untouched (the
encoding is that of the underlying type).
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from typing import Any, Optional

PRIMITIVES = {
    "null", "boolean", "int", "long", "float", "double", "bytes", "string"
}


class AvroError(ValueError):
    pass


@dataclass(frozen=True)
class Schema:
    """A parsed Avro schema node. ``source`` keeps the normalized dict/str
    form for re-serialization; complex types pre-resolve their children."""

    type: str
    source: Any
    name: Optional[str] = None
    fields: tuple[tuple[str, "Schema", Any], ...] = ()  # (name, schema, default)
    items: Optional["Schema"] = None  # array
    values: Optional["Schema"] = None  # map
    symbols: tuple[str, ...] = ()  # enum
    size: int = 0  # fixed
    branches: tuple["Schema", ...] = ()  # union

    def canonical(self) -> str:
        """Parsing-canonical-form JSON (stable intern/fingerprint key).
        Cached on the instance — schemas are immutable and this runs per
        record on the broker produce path."""
        cached = self.__dict__.get("_canonical_cache")
        if cached is None:
            cached = json.dumps(
                _canonical(self.source), separators=(",", ":"), sort_keys=False
            )
            object.__setattr__(self, "_canonical_cache", cached)
        return cached

    def fingerprint(self) -> int:
        """CRC-64-AVRO of the canonical form (Avro spec fingerprinting)."""
        cached = self.__dict__.get("_fingerprint_cache")
        if cached is None:
            cached = _crc64(self.canonical().encode())
            object.__setattr__(self, "_fingerprint_cache", cached)
        return cached


@dataclass
class AvroValue:
    """A datum + its schema — the GenericRecord analog carried as a record
    key/value through the platform."""

    schema: Schema
    data: Any

    def encode(self) -> bytes:
        return encode(self.schema, self.data)

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, AvroValue)
            and other.data == self.data
            and other.schema.canonical() == self.schema.canonical()
        )


# ---------------------------------------------------------------------------
# Schema parsing
# ---------------------------------------------------------------------------


def parse_schema(source: Any) -> Schema:
    if isinstance(source, (str, bytes)):
        text = source.decode() if isinstance(source, bytes) else source
        stripped = text.strip()
        if stripped.startswith(("{", "[")) or stripped.startswith('"'):
            source = json.loads(stripped)
        else:
            source = stripped  # bare primitive name
    return _parse(source, {}, namespace=None)


def _fullname(name: str, namespace: Optional[str]) -> str:
    if "." in name or not namespace:
        return name
    return f"{namespace}.{name}"


def _parse(node: Any, named: dict[str, Schema], namespace: Optional[str]) -> Schema:
    if isinstance(node, str):
        if node in PRIMITIVES:
            return Schema(type=node, source=node)
        ref = named.get(_fullname(node, namespace)) or named.get(node)
        if ref is None:
            raise AvroError(f"unknown schema reference {node!r}")
        return ref
    if isinstance(node, list):
        branches = tuple(_parse(b, named, namespace) for b in node)
        return Schema(type="union", source=node, branches=branches)
    if not isinstance(node, dict):
        raise AvroError(f"invalid schema node {node!r}")

    t = node.get("type")
    if t in PRIMITIVES:
        return Schema(type=t, source=node if len(node) > 1 else t)
    if t == "array":
        return Schema(
            type="array", source=node, items=_parse(node["items"], named, namespace)
        )
    if t == "map":
        return Schema(
            type="map", source=node, values=_parse(node["values"], named, namespace)
        )
    if t == "enum":
        name = _fullname(node["name"], node["namespace"] if "namespace" in node else namespace)
        schema = Schema(
            type="enum", source=node, name=name, symbols=tuple(node["symbols"])
        )
        named[name] = schema
        return schema
    if t == "fixed":
        name = _fullname(node["name"], node["namespace"] if "namespace" in node else namespace)
        schema = Schema(type="fixed", source=node, name=name, size=int(node["size"]))
        named[name] = schema
        return schema
    if t == "record" or t == "error":
        ns = node["namespace"] if "namespace" in node else namespace
        name = _fullname(node["name"], ns)
        # two-phase: register a placeholder so recursive references resolve
        fields: list[tuple[str, Schema, Any]] = []
        schema = Schema(type="record", source=node, name=name)
        named[name] = schema
        for f in node.get("fields", []):
            fields.append(
                (f["name"], _parse(f["type"], named, ns), f.get("default", _NO_DEFAULT))
            )
        object.__setattr__(schema, "fields", tuple(fields))
        return schema
    if isinstance(t, (list, dict)):
        return _parse(t, named, namespace)
    raise AvroError(f"unsupported schema type {t!r}")


_NO_DEFAULT = object()


def _canonical(node: Any, namespace: Optional[str] = None) -> Any:
    """Strip non-structural attributes, order keys per the spec's
    parsing-canonical-form field order, and apply the FULLNAMES step:
    every name (and name reference) is resolved to namespace.name before
    the namespace attribute is dropped — so two schemas differing only by
    namespace get DIFFERENT fingerprints, matching spec CRC-64-AVRO."""
    if isinstance(node, str):
        if (
            node in PRIMITIVES
            or node in ("record", "error", "enum", "fixed", "array", "map")
            or "." in node
            or not namespace
        ):
            return node
        return f"{namespace}.{node}"  # named-type reference → fullname
    if isinstance(node, list):
        return [_canonical(b, namespace) for b in node]
    if isinstance(node, dict):
        t = node.get("type")
        if t in PRIMITIVES and len(node) >= 1 and "name" not in node:
            return t
        ns = node["namespace"] if "namespace" in node else namespace
        out: dict[str, Any] = {}
        for key in ("name", "type", "fields", "symbols", "items", "values", "size"):
            if key not in node:
                continue
            v = node[key]
            if key == "name":
                out[key] = v if "." in v else (f"{ns}.{v}" if ns else v)
            elif key == "fields":
                out[key] = [
                    {"name": f["name"], "type": _canonical(f["type"], ns)} for f in v
                ]
            elif key in ("items", "values", "type") and not isinstance(v, (int,)):
                out[key] = _canonical(v, ns)
            else:
                out[key] = v
        return out
    return node


_CRC64_POLY = 0xC15D213AA4D7A795


def _crc64_table() -> list[int]:
    table = []
    for i in range(256):
        fp = i
        for _ in range(8):
            fp = (fp >> 1) ^ (_CRC64_POLY & -(fp & 1))
        table.append(fp)
    return table


_CRC64_TABLE = _crc64_table()
_CRC64_EMPTY = 0xC15D213AA4D7A795


def _crc64(data: bytes) -> int:
    fp = _CRC64_EMPTY
    for b in data:
        fp = (fp >> 8) ^ _CRC64_TABLE[(fp ^ b) & 0xFF]
    return fp


# ---------------------------------------------------------------------------
# Binary encoding
# ---------------------------------------------------------------------------


def _zigzag_encode(out: bytearray, v: int) -> None:
    v = (v << 1) ^ (v >> 63)
    v &= 0xFFFFFFFFFFFFFFFF
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def encode(schema: Schema, datum: Any) -> bytes:
    out = bytearray()
    _encode(out, schema, datum)
    return bytes(out)


def _encode(out: bytearray, schema: Schema, datum: Any) -> None:
    t = schema.type
    if t == "null":
        if datum is not None:
            raise AvroError(f"non-null datum for null schema: {datum!r}")
    elif t == "boolean":
        out.append(1 if datum else 0)
    elif t in ("int", "long"):
        _zigzag_encode(out, int(datum))
    elif t == "float":
        out.extend(struct.pack("<f", float(datum)))
    elif t == "double":
        out.extend(struct.pack("<d", float(datum)))
    elif t == "bytes":
        b = bytes(datum)
        _zigzag_encode(out, len(b))
        out.extend(b)
    elif t == "string":
        b = str(datum).encode()
        _zigzag_encode(out, len(b))
        out.extend(b)
    elif t == "fixed":
        b = bytes(datum)
        if len(b) != schema.size:
            raise AvroError(f"fixed {schema.name} needs {schema.size} bytes")
        out.extend(b)
    elif t == "enum":
        try:
            _zigzag_encode(out, schema.symbols.index(datum))
        except ValueError:
            raise AvroError(f"{datum!r} not in enum {schema.name}") from None
    elif t == "array":
        assert schema.items is not None
        items = list(datum)
        if items:
            _zigzag_encode(out, len(items))
            for item in items:
                _encode(out, schema.items, item)
        _zigzag_encode(out, 0)
    elif t == "map":
        assert schema.values is not None
        entries = dict(datum)
        if entries:
            _zigzag_encode(out, len(entries))
            for k, v in entries.items():
                b = str(k).encode()
                _zigzag_encode(out, len(b))
                out.extend(b)
                _encode(out, schema.values, v)
        _zigzag_encode(out, 0)
    elif t == "union":
        idx = _union_branch(schema, datum)
        _zigzag_encode(out, idx)
        _encode(out, schema.branches[idx], datum)
    elif t == "record":
        if not isinstance(datum, dict):
            raise AvroError(f"record {schema.name} needs a dict, got {type(datum)}")
        for name, fschema, default in schema.fields:
            if name in datum:
                _encode(out, fschema, datum[name])
            elif default is not _NO_DEFAULT:
                _encode(out, fschema, _default_value(fschema, default))
            else:
                raise AvroError(f"missing field {name!r} of record {schema.name}")
    else:
        raise AvroError(f"cannot encode type {t!r}")


def _default_value(schema: Schema, default: Any) -> Any:
    # union defaults apply to the FIRST branch; "null" default is None already
    if schema.type == "bytes" and isinstance(default, str):
        return default.encode("latin-1")
    return default


def _union_branch(schema: Schema, datum: Any) -> int:
    def matches(branch: Schema, d: Any) -> bool:
        t = branch.type
        if t == "null":
            return d is None
        if t == "boolean":
            return isinstance(d, bool)
        if t in ("int", "long"):
            return isinstance(d, int) and not isinstance(d, bool)
        if t in ("float", "double"):
            return isinstance(d, float)
        if t == "string":
            return isinstance(d, str)
        if t in ("bytes", "fixed"):
            return isinstance(d, (bytes, bytearray))
        if t == "enum":
            return isinstance(d, str) and d in branch.symbols
        if t == "array":
            return isinstance(d, (list, tuple))
        if t in ("map", "record"):
            return isinstance(d, dict)
        return False

    for i, branch in enumerate(schema.branches):
        if matches(branch, datum):
            return i
    # second pass: int→float promotion
    for i, branch in enumerate(schema.branches):
        if branch.type in ("float", "double") and isinstance(datum, int):
            return i
    raise AvroError(f"datum {datum!r} matches no union branch")


# ---------------------------------------------------------------------------
# Binary decoding
# ---------------------------------------------------------------------------


class _Decoder:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def raw(self, n: int) -> bytes:
        out = self.data[self.pos : self.pos + n]
        if len(out) != n:
            raise AvroError(f"truncated avro data at {self.pos}")
        self.pos += n
        return out

    def zigzag(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.raw(1)[0]
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)


def decode(schema: Schema, data: bytes) -> Any:
    d = _Decoder(data)
    out = _decode(d, schema)
    return out


def _decode(d: _Decoder, schema: Schema) -> Any:
    t = schema.type
    if t == "null":
        return None
    if t == "boolean":
        return d.raw(1)[0] != 0
    if t in ("int", "long"):
        return d.zigzag()
    if t == "float":
        return struct.unpack("<f", d.raw(4))[0]
    if t == "double":
        return struct.unpack("<d", d.raw(8))[0]
    if t == "bytes":
        return d.raw(d.zigzag())
    if t == "string":
        return d.raw(d.zigzag()).decode()
    if t == "fixed":
        return d.raw(schema.size)
    if t == "enum":
        return schema.symbols[d.zigzag()]
    if t == "array":
        assert schema.items is not None
        out = []
        while True:
            n = d.zigzag()
            if n == 0:
                return out
            if n < 0:  # block with byte-size prefix
                n = -n
                d.zigzag()
            for _ in range(n):
                out.append(_decode(d, schema.items))
    if t == "map":
        assert schema.values is not None
        out_map: dict[str, Any] = {}
        while True:
            n = d.zigzag()
            if n == 0:
                return out_map
            if n < 0:
                n = -n
                d.zigzag()
            for _ in range(n):
                key = d.raw(d.zigzag()).decode()
                out_map[key] = _decode(d, schema.values)
    if t == "union":
        return _decode(d, schema.branches[d.zigzag()])
    if t == "record":
        rec = {}
        for name, fschema, _default in schema.fields:
            rec[name] = _decode(d, fschema)
        return rec
    raise AvroError(f"cannot decode type {t!r}")


# ---------------------------------------------------------------------------
# JSON ↔ Avro datum helpers (agents-commons AvroUtil analog)
# ---------------------------------------------------------------------------


def datum_to_json(datum: Any) -> Any:
    """Avro datum → JSON-compatible object (bytes become latin-1 strings,
    the Avro JSON-encoding convention for bytes/fixed)."""
    if isinstance(datum, (bytes, bytearray)):
        return bytes(datum).decode("latin-1")
    if isinstance(datum, dict):
        return {k: datum_to_json(v) for k, v in datum.items()}
    if isinstance(datum, (list, tuple)):
        return [datum_to_json(v) for v in datum]
    return datum


def json_to_datum(schema: Schema, obj: Any, strict: bool = False) -> Any:
    """JSON object → datum shaped for ``schema`` (inverse of datum_to_json).

    ``strict``: raise AvroError when a record object carries keys the schema
    has no field for — the signal callers use to fall back to JSON instead
    of silently dropping mutated-in fields."""
    t = schema.type
    if t in ("bytes", "fixed") and isinstance(obj, str):
        return obj.encode("latin-1")
    if t == "record" and isinstance(obj, dict):
        out = {}
        known = {name for name, _f, _d in schema.fields}
        if strict:
            extra = set(obj) - known
            if extra:
                raise AvroError(
                    f"record {schema.name} has no fields for {sorted(extra)}"
                )
        for name, fschema, default in schema.fields:
            if name in obj:
                out[name] = json_to_datum(fschema, obj[name], strict)
            elif default is not _NO_DEFAULT:
                out[name] = _default_value(fschema, default)
        return out
    if t == "array" and isinstance(obj, (list, tuple)):
        assert schema.items is not None
        return [json_to_datum(schema.items, v, strict) for v in obj]
    if t == "map" and isinstance(obj, dict):
        assert schema.values is not None
        return {k: json_to_datum(schema.values, v, strict) for k, v in obj.items()}
    if t == "union":
        for branch in schema.branches:
            try:
                datum = json_to_datum(branch, obj, strict)
                _union_branch(schema, datum)  # validates
                return datum
            except AvroError:
                continue
        if strict:
            raise AvroError(f"no union branch of {schema.source} fits {obj!r}")
        return obj
    return obj
