"""Topic SPI — broker-agnostic consume / produce / admin.

Parity: reference `api/runner/topics/` (TopicConsumer, TopicProducer,
TopicAdmin, TopicReader, TopicOffsetPosition, OffsetPerPartition) and the
registry `TopicConnectionsRuntimeRegistry`.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Optional

from langstream_tpu.api.record import Record


@dataclass(frozen=True)
class TopicOffsetPosition:
    """Where a reader starts (reference TopicOffsetPosition).

    position ∈ {latest, earliest, absolute}; ``offsets`` is an opaque
    per-partition offset map serialized by the broker runtime.
    """

    position: str = "latest"
    offsets: dict[int, int] = field(default_factory=dict)

    LATEST = "latest"
    EARLIEST = "earliest"

    @staticmethod
    def absolute(offsets: dict[int, int]) -> "TopicOffsetPosition":
        return TopicOffsetPosition(position="absolute", offsets=dict(offsets))


class TopicConsumer(abc.ABC):
    """Group-based consumer with explicit, possibly out-of-order ack.

    Implementations must commit only contiguous prefixes per partition
    (reference KafkaConsumerWrapper.java:41-115 manual offset bookkeeping).
    """

    async def start(self) -> None:  # noqa: B027
        pass

    async def close(self) -> None:  # noqa: B027
        pass

    @abc.abstractmethod
    async def read(self) -> list[Record]: ...

    @abc.abstractmethod
    async def commit(self, records: list[Record]) -> None: ...

    def get_native_consumer(self) -> Any:
        return None

    def get_info(self) -> dict[str, Any]:
        return {}

    @property
    def total_out(self) -> int:
        return 0


class TopicProducer(abc.ABC):
    async def start(self) -> None:  # noqa: B027
        pass

    async def close(self) -> None:  # noqa: B027
        pass

    @abc.abstractmethod
    async def write(self, record: Record) -> None: ...

    @property
    def total_in(self) -> int:
        return 0


class TopicReader(abc.ABC):
    """Offset-addressed reader for gateway consume (no consumer group)."""

    async def start(self) -> None:  # noqa: B027
        pass

    async def close(self) -> None:  # noqa: B027
        pass

    @abc.abstractmethod
    async def read(self) -> "TopicReadResult": ...


@dataclass
class TopicReadResult:
    records: list[Record]
    offset: dict[int, int]
    # per-record resume positions: record_offsets[i] is the offset map to
    # restart AFTER records[i]; resuming from the batch-level ``offset`` for a
    # mid-batch record would skip the rest of the batch
    record_offsets: Optional[list[dict[int, int]]] = None


class TopicAdmin(abc.ABC):
    async def start(self) -> None:  # noqa: B027
        pass

    async def close(self) -> None:  # noqa: B027
        pass

    @abc.abstractmethod
    async def create_topic(self, name: str, partitions: int = 1, options: Optional[dict] = None) -> None: ...

    @abc.abstractmethod
    async def delete_topic(self, name: str) -> None: ...

    @abc.abstractmethod
    async def topic_exists(self, name: str) -> bool: ...


class TopicConnectionsRuntime(abc.ABC):
    """Factory for consumers/producers/readers/admin on one streaming cluster
    (reference TopicConnectionsRuntime / KafkaTopicConnectionsRuntime)."""

    async def init(self, streaming_cluster_config: dict[str, Any]) -> None:  # noqa: B027
        pass

    async def close(self) -> None:  # noqa: B027
        pass

    @abc.abstractmethod
    def create_consumer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicConsumer: ...

    @abc.abstractmethod
    def create_producer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicProducer: ...

    @abc.abstractmethod
    def create_reader(
        self,
        topic: str,
        initial_position: TopicOffsetPosition = TopicOffsetPosition(),
        config: Optional[dict[str, Any]] = None,
    ) -> TopicReader: ...

    @abc.abstractmethod
    def create_topic_admin(self) -> TopicAdmin: ...
