"""Agent SPI — the contract every agent implements. asyncio-native.

Parity: reference `api/runner/code/AgentCode.java:25` (init/start/close/
setContext), `AgentSource.java:22` (read/commit/permanentFailure),
`AgentProcessor.java:23` (async process → per-source-record results),
`AgentSink.java:22` (write → future), `AgentService.java:21` (join).

Design shift vs the reference: the Java SPI is callback-based
(`process(List<Record>, RecordSink)`); here ``process`` is a coroutine
returning ``list[ProcessorResult]`` — one per source record, each carrying
either output records or an error. Streaming side-effects (chunk records
emitted before the final result, e.g. completion token chunks) go through
``AgentContext.get_topic_producer`` exactly like the reference's
``StreamingChunksConsumer`` path (ChatCompletionsStep.java:137).
"""

from __future__ import annotations

import abc
import enum
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Optional, TYPE_CHECKING

from langstream_tpu.api.record import Record

if TYPE_CHECKING:
    from langstream_tpu.api.metrics import MetricsReporter
    from langstream_tpu.api.topics import TopicAdmin, TopicConsumer, TopicProducer


class ComponentType(enum.Enum):
    SOURCE = "source"
    PROCESSOR = "processor"
    SINK = "sink"
    SERVICE = "service"


@dataclass
class ProcessorResult:
    """Outcome of processing one source record (reference SourceRecordAndResult:42)."""

    source_record: Record
    records: list[Record] = field(default_factory=list)
    error: Optional[BaseException] = None

    @staticmethod
    def ok(source: Record, records: list[Record]) -> "ProcessorResult":
        return ProcessorResult(source_record=source, records=records)

    @staticmethod
    def failed(source: Record, error: BaseException) -> "ProcessorResult":
        return ProcessorResult(source_record=source, error=error)


# Callback used by push-style processors (streaming emit before completion).
RecordSink = Callable[[ProcessorResult], None]


class BadRecordError(Exception):
    """Non-retryable record failure — routes straight to the errors policy."""


class AgentContext(abc.ABC):
    """Runtime services available to an agent (reference AgentContext)."""

    @abc.abstractmethod
    def get_global_agent_id(self) -> str: ...

    @abc.abstractmethod
    def get_tenant(self) -> str: ...

    @abc.abstractmethod
    def get_persistent_state_directory(self) -> Optional[Path]:
        """Per-agent durable dir backed by resources.disk (AgentRunner.java:1130)."""

    @abc.abstractmethod
    def get_topic_producer(self, topic: str) -> "TopicProducer":
        """Producer for side-channel topics (streaming chunks, signals)."""

    @abc.abstractmethod
    def get_topic_consumer(self, topic: str) -> "TopicConsumer": ...

    @abc.abstractmethod
    def get_topic_admin(self) -> "TopicAdmin": ...

    @abc.abstractmethod
    def get_metrics_reporter(self) -> "MetricsReporter": ...

    @abc.abstractmethod
    def get_service_provider_registry(self) -> Any:
        """AI ServiceProvider registry (completions/embeddings backends)."""

    def get_code_directory(self) -> Optional[str]:
        """Source-package directory when known; ``<dir>/python`` goes on the
        path of python-agent subprocesses (reference PYTHONPATH injection)."""
        return None

    @abc.abstractmethod
    def critical_failure(self, error: BaseException) -> None:
        """Crash-only escape hatch (reference SimpleAgentContext.criticalFailure:1115)."""


class AgentCode(abc.ABC):
    """Base lifecycle (reference AgentCode.java:25)."""

    agent_id: str = ""
    agent_type: str = ""

    def __init__(self) -> None:
        self.context: Optional[AgentContext] = None
        self._processed = 0
        self._last_processed_at = 0.0

    @abc.abstractmethod
    def component_type(self) -> ComponentType: ...

    async def init(self, configuration: dict[str, Any]) -> None:  # noqa: B027
        pass

    async def start(self) -> None:  # noqa: B027
        pass

    async def close(self) -> None:  # noqa: B027
        pass

    def set_context(self, context: AgentContext) -> None:
        self.context = context

    def processed(self, n: int) -> None:
        import time

        self._processed += n
        self._last_processed_at = time.time()

    def agent_info(self) -> dict[str, Any]:
        """Status for /info (reference AbstractAgentCode.buildAdditionalInfo)."""
        return {
            "agent-id": self.agent_id,
            "agent-type": self.agent_type,
            "component-type": self.component_type().value,
            "metrics": {
                "total-in": self._processed,
                "last-processed-at": self._last_processed_at,
            },
        }


class AgentSource(AgentCode):
    """Pulls records in (reference AgentSource.java:22)."""

    def component_type(self) -> ComponentType:
        return ComponentType.SOURCE

    @abc.abstractmethod
    async def read(self) -> list[Record]:
        """Return next batch; may be empty. Must not block the loop forever."""

    async def commit(self, records: list[Record]) -> None:  # noqa: B027
        """Called when every downstream write for these records has landed."""

    async def permanent_failure(self, record: Record, error: BaseException) -> None:
        """Dead-letter hook; default re-raises to crash (reference behavior)."""
        raise error


class AgentProcessor(AgentCode):
    """Transforms records (reference AgentProcessor.java:23)."""

    def component_type(self) -> ComponentType:
        return ComponentType.PROCESSOR

    @abc.abstractmethod
    async def process(self, records: list[Record]) -> list[ProcessorResult]:
        """One ProcessorResult per input record, order-preserving."""


class SingleRecordProcessor(AgentProcessor):
    """Convenience base: per-record transform (reference SingleRecordAgentProcessor)."""

    @abc.abstractmethod
    async def process_record(self, record: Record) -> list[Record]: ...

    async def process(self, records: list[Record]) -> list[ProcessorResult]:
        out: list[ProcessorResult] = []
        for r in records:
            try:
                out.append(ProcessorResult.ok(r, await self.process_record(r)))
            except BaseException as e:  # noqa: BLE001 — routed to errors policy
                out.append(ProcessorResult.failed(r, e))
        return out


class AgentSink(AgentCode):
    """Writes records out (reference AgentSink.java:22)."""

    def component_type(self) -> ComponentType:
        return ComponentType.SINK

    @abc.abstractmethod
    async def write(self, record: Record) -> None:
        """Completes when durably written. Raise to trigger errors policy."""

    def handles_commit(self) -> bool:
        """True if the sink owns source offset commits (Kafka Connect parity)."""
        return False

    def set_commit_callback(self, cb: Callable[[list[Record]], None]) -> None:  # noqa: B027
        pass


class AgentService(AgentCode):
    """Long-running service bypassing the record loop (reference AgentService.java:21)."""

    def component_type(self) -> ComponentType:
        return ComponentType.SERVICE

    @abc.abstractmethod
    async def join(self) -> None:
        """Run until shutdown; the runner awaits this instead of the poll loop."""
