"""Typed application model: modules → pipelines → agents, topics, gateways.

Parity: reference `langstream-api/src/main/java/ai/langstream/api/model/`
(Application.java, Module.java, Pipeline.java, AgentConfiguration.java,
TopicDefinition.java, Gateway.java:31-160, ResourcesSpec.java:22,
ErrorsSpec.java:26-44, DiskSpec.java:22). TPU-native addition: ``TpuSpec`` on
``ResourcesSpec`` — the reference has no device topology concept (SURVEY §2.11).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional


# ---------------------------------------------------------------------------
# Resource / error specs (cascading defaults: agent → pipeline → app)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DiskSpec:
    """Persistent state disk for an agent (reference DiskSpec.java:22)."""

    enabled: bool = False
    type: str = "default"
    size: str = "256M"

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["DiskSpec"]:
        if d is None:
            return None
        if isinstance(d, bool):
            return DiskSpec(enabled=d)
        return DiskSpec(
            enabled=bool(d.get("enabled", True)),
            type=str(d.get("type", "default")),
            size=str(d.get("size", "256M")),
        )


@dataclass(frozen=True)
class TpuSpec:
    """TPU topology request for an agent replica — new, no reference counterpart.

    One agent replica maps to one JAX process group over ``topology`` (e.g.
    "v5e-8"); ``mesh`` names logical axes and sizes, e.g. {"data":1,"model":8}.
    The planner validates that the mesh factorises the topology's chip count.

    ``hosts > 1`` declares a MULTI-HOST slice: the replica is still ONE
    logical broker consumer, but it spans ``hosts`` pods that form a single
    ``jax.distributed`` process group (replica-vs-shard distinction, SURVEY
    §7 — shard parallelism spans pods; replica parallelism multiplies
    consumers). The k8s factory emits hosts×parallelism StatefulSet pods and
    the entrypoint derives process_index/coordinator from the pod ordinal.
    """

    type: str = "v5e"
    topology: str = "1"  # chips per replica, e.g. "8" or "2x4"
    mesh: dict[str, int] = field(default_factory=dict)
    hosts: int = 1  # pods (JAX processes) forming one logical replica

    @staticmethod
    def normalized_topology(topology: str) -> str:
        """Strip a generation prefix: "8", "2x4", "v5e-8", "v5p-2x2" → bare
        "8" / "2x4" form (the single accept-forms contract — GKE label values
        and chip counting both derive from this)."""
        import re

        return re.sub(r"^[a-z0-9]*?-", "", str(topology).lower().strip())

    @property
    def chips(self) -> int:
        topo = self.normalized_topology(self.topology)
        n = 1
        for part in topo.split("x"):
            if part.strip().isdigit():
                n *= int(part)
        return max(n, 1)

    @property
    def chips_per_host(self) -> int:
        return self.chips // max(self.hosts, 1)

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["TpuSpec"]:
        if d is None:
            return None
        return TpuSpec(
            type=str(d.get("type", "v5e")),
            topology=str(d.get("topology", "1")),
            mesh=dict(d.get("mesh", {})),
            hosts=int(d.get("hosts", 1)),
        )


@dataclass(frozen=True)
class ResourcesSpec:
    """Scaling spec (reference ResourcesSpec.java:22) + TPU topology.

    parallelism → replica count (consumer-group data parallelism);
    size → cpu/mem units; tpu → per-replica device mesh (shard parallelism).
    """

    parallelism: Optional[int] = None
    size: Optional[int] = None
    disk: Optional[DiskSpec] = None
    tpu: Optional[TpuSpec] = None

    DEFAULT_PARALLELISM = 1
    DEFAULT_SIZE = 1

    def with_defaults_from(self, higher: Optional["ResourcesSpec"]) -> "ResourcesSpec":
        """Cascade (reference ResourcesSpec.withDefaultsFrom:30)."""
        if higher is None:
            return self
        return ResourcesSpec(
            parallelism=self.parallelism if self.parallelism is not None else higher.parallelism,
            size=self.size if self.size is not None else higher.size,
            disk=self.disk if self.disk is not None else higher.disk,
            tpu=self.tpu if self.tpu is not None else higher.tpu,
        )

    def resolved_parallelism(self) -> int:
        return self.parallelism if self.parallelism is not None else self.DEFAULT_PARALLELISM

    def resolved_size(self) -> int:
        return self.size if self.size is not None else self.DEFAULT_SIZE

    @staticmethod
    def from_dict(d: Optional[dict]) -> "ResourcesSpec":
        if not d:
            return ResourcesSpec()
        return ResourcesSpec(
            parallelism=d.get("parallelism"),
            size=d.get("size"),
            disk=DiskSpec.from_dict(d.get("disk")),
            tpu=TpuSpec.from_dict(d.get("tpu")),
        )


VALID_ON_FAILURE = ("fail", "skip", "dead-letter")


@dataclass(frozen=True)
class ErrorsSpec:
    """Record-level error policy (reference ErrorsSpec.java:26-44)."""

    retries: Optional[int] = None
    on_failure: Optional[str] = None  # fail | skip | dead-letter

    DEFAULT_RETRIES = 0
    DEFAULT_ON_FAILURE = "fail"

    def with_defaults_from(self, higher: Optional["ErrorsSpec"]) -> "ErrorsSpec":
        if higher is None:
            return self
        return ErrorsSpec(
            retries=self.retries if self.retries is not None else higher.retries,
            on_failure=self.on_failure if self.on_failure is not None else higher.on_failure,
        )

    def resolved_retries(self) -> int:
        return self.retries if self.retries is not None else self.DEFAULT_RETRIES

    def resolved_on_failure(self) -> str:
        return self.on_failure if self.on_failure is not None else self.DEFAULT_ON_FAILURE

    def validate(self) -> None:
        if self.on_failure is not None and self.on_failure not in VALID_ON_FAILURE:
            raise ValueError(
                f"errors.on-failure must be one of {VALID_ON_FAILURE}, got {self.on_failure!r}"
            )
        if self.retries is not None and self.retries < 0:
            raise ValueError(f"errors.retries must be >= 0, got {self.retries}")

    @staticmethod
    def from_dict(d: Optional[dict]) -> "ErrorsSpec":
        if not d:
            return ErrorsSpec()
        spec = ErrorsSpec(retries=d.get("retries"), on_failure=d.get("on-failure"))
        spec.validate()
        return spec


# ---------------------------------------------------------------------------
# Topics
# ---------------------------------------------------------------------------


@dataclass
class SchemaDefinition:
    type: str = "string"  # string | bytes | json | avro
    schema: Optional[str] = None
    name: Optional[str] = None

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["SchemaDefinition"]:
        if d is None:
            return None
        return SchemaDefinition(
            type=str(d.get("type", "string")),
            schema=d.get("schema"),
            name=d.get("name"),
        )


CREATE_MODE_NONE = "none"
CREATE_MODE_CREATE_IF_NOT_EXISTS = "create-if-not-exists"
DELETE_MODE_NONE = "none"
DELETE_MODE_DELETE = "delete"


@dataclass
class TopicDefinition:
    """Reference TopicDefinition.java. ``implicit`` marks planner-created topics."""

    name: str
    creation_mode: str = CREATE_MODE_NONE
    deletion_mode: str = DELETE_MODE_NONE
    partitions: int = 0
    implicit: bool = False
    key_schema: Optional[SchemaDefinition] = None
    value_schema: Optional[SchemaDefinition] = None
    options: dict[str, Any] = field(default_factory=dict)
    config: dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: dict) -> "TopicDefinition":
        name = d.get("name")
        if not name:
            raise ValueError("topic definition requires a 'name'")
        creation_mode = d.get("creation-mode", CREATE_MODE_NONE)
        if creation_mode not in (CREATE_MODE_NONE, CREATE_MODE_CREATE_IF_NOT_EXISTS):
            raise ValueError(f"unknown topic creation-mode {creation_mode!r}")
        deletion_mode = d.get("deletion-mode", DELETE_MODE_NONE)
        if deletion_mode not in (DELETE_MODE_NONE, DELETE_MODE_DELETE):
            raise ValueError(f"unknown topic deletion-mode {deletion_mode!r}")
        return TopicDefinition(
            name=name,
            creation_mode=creation_mode,
            deletion_mode=deletion_mode,
            partitions=int(d.get("partitions", 0)),
            key_schema=SchemaDefinition.from_dict(d.get("keySchema") or d.get("key-schema")),
            value_schema=SchemaDefinition.from_dict(d.get("schema") or d.get("value-schema")),
            options=dict(d.get("options", {})),
            config=dict(d.get("config", {})),
        )

    def copy(self) -> "TopicDefinition":
        return dataclasses.replace(self)


# ---------------------------------------------------------------------------
# Agents / pipelines / modules
# ---------------------------------------------------------------------------


@dataclass
class AgentConfiguration:
    """One agent step in a pipeline (reference AgentConfiguration.java)."""

    type: str
    id: Optional[str] = None
    name: Optional[str] = None
    input: Optional[str] = None  # topic name or implicit connection to previous
    output: Optional[str] = None
    configuration: dict[str, Any] = field(default_factory=dict)
    resources: ResourcesSpec = field(default_factory=ResourcesSpec)
    errors: ErrorsSpec = field(default_factory=ErrorsSpec)
    signals_from: Optional[str] = None
    deletion_mode: str = "none"


@dataclass
class Pipeline:
    id: str
    module: str
    name: Optional[str] = None
    resources: ResourcesSpec = field(default_factory=ResourcesSpec)
    errors: ErrorsSpec = field(default_factory=ErrorsSpec)
    agents: list[AgentConfiguration] = field(default_factory=list)


@dataclass
class Module:
    DEFAULT_MODULE = "default"

    id: str = DEFAULT_MODULE
    pipelines: dict[str, Pipeline] = field(default_factory=dict)
    topics: dict[str, TopicDefinition] = field(default_factory=dict)

    def add_topic(self, topic: TopicDefinition) -> TopicDefinition:
        existing = self.topics.get(topic.name)
        if existing is not None:
            return existing
        self.topics[topic.name] = topic
        return topic


# ---------------------------------------------------------------------------
# Gateways
# ---------------------------------------------------------------------------


@dataclass
class GatewayAuth:
    provider: str = ""
    configuration: dict[str, Any] = field(default_factory=dict)
    allow_test_mode: bool = True

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["GatewayAuth"]:
        if d is None:
            return None
        return GatewayAuth(
            provider=str(d.get("provider", "")),
            configuration=dict(d.get("configuration", {})),
            allow_test_mode=bool(d.get("allow-test-mode", True)),
        )


@dataclass
class ProduceOptions:
    headers: list[dict[str, Any]] = field(default_factory=list)


@dataclass
class ConsumeOptions:
    filters: dict[str, Any] = field(default_factory=dict)


@dataclass
class ChatOptions:
    """Reference Gateway.ChatOptions:135 — one socket, produce + filtered consume."""

    questions_topic: Optional[str] = None
    answers_topic: Optional[str] = None
    headers: list[dict[str, Any]] = field(default_factory=list)


@dataclass
class ServiceOptions:
    """Reference Gateway.ServiceOptions:149 — request/reply or agent proxy."""

    input_topic: Optional[str] = None
    output_topic: Optional[str] = None
    agent_id: Optional[str] = None
    headers: list[dict[str, Any]] = field(default_factory=list)


GATEWAY_TYPES = ("produce", "consume", "chat", "service")


@dataclass
class Gateway:
    """Reference Gateway.java:31-160; types :54-58."""

    id: str
    type: str
    topic: Optional[str] = None
    authentication: Optional[GatewayAuth] = None
    parameters: list[str] = field(default_factory=list)
    produce_options: Optional[ProduceOptions] = None
    consume_options: Optional[ConsumeOptions] = None
    chat_options: Optional[ChatOptions] = None
    service_options: Optional[ServiceOptions] = None
    events_topic: Optional[str] = None

    def __post_init__(self) -> None:
        if self.type not in GATEWAY_TYPES:
            raise ValueError(f"gateway type must be one of {GATEWAY_TYPES}, got {self.type!r}")


# ---------------------------------------------------------------------------
# Instance / resources / secrets
# ---------------------------------------------------------------------------


@dataclass
class StreamingCluster:
    type: str = "memory"  # memory | kafka | pulsar | pravega
    configuration: dict[str, Any] = field(default_factory=dict)


@dataclass
class ComputeCluster:
    type: str = "local"  # local | kubernetes
    configuration: dict[str, Any] = field(default_factory=dict)


@dataclass
class Instance:
    streaming_cluster: StreamingCluster = field(default_factory=StreamingCluster)
    compute_cluster: ComputeCluster = field(default_factory=ComputeCluster)
    globals_: dict[str, Any] = field(default_factory=dict)


@dataclass
class Resource:
    """configuration.resources entry — AI providers, datasources."""

    id: str
    type: str
    name: Optional[str] = None
    configuration: dict[str, Any] = field(default_factory=dict)


@dataclass
class AssetDefinition:
    id: str
    name: Optional[str] = None
    asset_type: str = ""
    creation_mode: str = CREATE_MODE_NONE
    deletion_mode: str = DELETE_MODE_NONE
    config: dict[str, Any] = field(default_factory=dict)


@dataclass
class Secret:
    id: str
    name: Optional[str] = None
    data: dict[str, Any] = field(default_factory=dict)


@dataclass
class Secrets:
    secrets: dict[str, Secret] = field(default_factory=dict)


@dataclass
class Dependency:
    """configuration.dependencies entry (jar/nar download in the reference)."""

    name: str
    url: str
    sha512sum: str = ""
    type: str = "java-library"


# ---------------------------------------------------------------------------
# Application root
# ---------------------------------------------------------------------------


@dataclass
class Application:
    """Root of the model (reference Application.java)."""

    modules: dict[str, Module] = field(default_factory=dict)
    resources: dict[str, Resource] = field(default_factory=dict)
    assets: list[AssetDefinition] = field(default_factory=list)
    dependencies: list[Dependency] = field(default_factory=list)
    gateways: list[Gateway] = field(default_factory=list)
    instance: Instance = field(default_factory=Instance)
    secrets: Secrets = field(default_factory=Secrets)
    # where the source package lives on disk (when known); the runtime adds
    # <code_directory>/python to python-agent subprocess paths (reference
    # PythonGrpcServer.java:61-76 PYTHONPATH injection)
    code_directory: Optional[str] = None

    def get_module(self, module_id: str) -> Module:
        mod = self.modules.get(module_id)
        if mod is None:
            mod = Module(id=module_id)
            self.modules[module_id] = mod
        return mod

    @property
    def default_module(self) -> Module:
        return self.get_module(Module.DEFAULT_MODULE)

    def all_agents(self) -> list[AgentConfiguration]:
        out: list[AgentConfiguration] = []
        for mod in self.modules.values():
            for pipe in mod.pipelines.values():
                out.extend(pipe.agents)
        return out
