"""Self-describing config schema model — drives validation errors and docs.

Parity: reference `api/doc/AgentConfigurationModel.java`, `ConfigProperty.java`
plus the reflection-driven `ClassConfigValidator` (565 LoC). Here the schema is
declared as ``ConfigProperty`` descriptors on agent/resource config classes;
`core.validator` consumes them for unknown-field rejection and type checks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class ConfigProperty:
    name: str
    description: str = ""
    type: str = "string"  # string|integer|number|boolean|object|array
    required: bool = False
    default: Any = None
    extended_validation: Optional[str] = None


@dataclass
class ConfigModel:
    """Schema for one agent/resource/asset type."""

    type: str
    description: str = ""
    properties: dict[str, ConfigProperty] = field(default_factory=dict)
    allow_unknown: bool = False

    def prop(self, name: str) -> Optional[ConfigProperty]:
        return self.properties.get(name)


def props(*items: ConfigProperty) -> dict[str, ConfigProperty]:
    return {p.name: p for p in items}
