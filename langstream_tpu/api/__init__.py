"""L0 — application model and SPIs. Everything else depends on this layer only.

Parity target: reference `langstream-api/` (see SURVEY.md §2.1). Pure data +
abstract contracts; no IO, no broker, no JAX imports here.
"""

from langstream_tpu.api.model import (
    AgentConfiguration,
    Application,
    ComputeCluster,
    DiskSpec,
    ErrorsSpec,
    Gateway,
    Instance,
    Module,
    Pipeline,
    Resource,
    ResourcesSpec,
    Secret,
    Secrets,
    StreamingCluster,
    TopicDefinition,
    TpuSpec,
)
from langstream_tpu.api.record import Header, Record, SimpleRecord
from langstream_tpu.api.agent import (
    AgentCode,
    AgentContext,
    AgentProcessor,
    AgentService,
    AgentSink,
    AgentSource,
    ComponentType,
    ProcessorResult,
    RecordSink,
)
from langstream_tpu.api.topics import (
    TopicAdmin,
    TopicConsumer,
    TopicOffsetPosition,
    TopicProducer,
    TopicReader,
)
from langstream_tpu.api.planner import (
    AgentNode,
    Connection,
    ExecutionPlan,
    ExecutionPlanOptimiser,
)

__all__ = [
    "AgentCode",
    "AgentConfiguration",
    "AgentContext",
    "AgentNode",
    "AgentProcessor",
    "AgentService",
    "AgentSink",
    "AgentSource",
    "Application",
    "ComponentType",
    "ComputeCluster",
    "Connection",
    "DiskSpec",
    "ErrorsSpec",
    "ExecutionPlan",
    "ExecutionPlanOptimiser",
    "Gateway",
    "Header",
    "Instance",
    "Module",
    "Pipeline",
    "ProcessorResult",
    "Record",
    "RecordSink",
    "Resource",
    "ResourcesSpec",
    "Secret",
    "Secrets",
    "SimpleRecord",
    "StreamingCluster",
    "TopicAdmin",
    "TopicConsumer",
    "TopicDefinition",
    "TopicOffsetPosition",
    "TopicProducer",
    "TopicReader",
    "TpuSpec",
]
