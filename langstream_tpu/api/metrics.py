"""Metrics SPI (reference api/runner/code/MetricsReporter.java:18).

Hierarchical reporters: ``with_prefix`` returns a child whose counters are
namespaced; the runtime installs a Prometheus-text implementation, tests use
the in-memory default. TPU additions: gauges for tokens/sec, TTFT, batch
occupancy, HBM use (SURVEY §5 observability note), and fixed-bucket
``Histogram``s for streaming latency distributions (TTFT, inter-token,
queue wait — the tail telemetry averages-and-counters cannot carry;
docs/SERVING.md §12).
"""

from __future__ import annotations

import bisect
import threading
from typing import Optional, Sequence


def _render_labels(labels: dict) -> str:
    """Canonical ``{k="v",...}`` rendering (sorted keys) — used both as
    the registry-key suffix and in the exposition line, so one (name,
    labels) pair is always one series."""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    __slots__ = ("name", "help", "labels", "_value", "_lock")

    def __init__(
        self, name: str, help_: str = "", labels: Optional[dict] = None,
    ) -> None:
        self.name = name
        self.help = help_
        # optional Prometheus labels: one Counter object IS one labeled
        # series (``name{k="v"}``); unlabeled stays the common case
        self.labels = dict(labels) if labels else None
        self._value = 0.0
        self._lock = threading.Lock()

    def count(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    __slots__ = ("name", "help", "labels", "_value")

    def __init__(
        self, name: str, help_: str = "", labels: Optional[dict] = None,
    ) -> None:
        self.name = name
        self.help = help_
        self.labels = dict(labels) if labels else None
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = v

    @property
    def value(self) -> float:
        return self._value


def log_buckets(lo: float, hi: float, per_decade: int = 4) -> tuple[float, ...]:
    """Log-spaced histogram bucket upper bounds from ``lo`` to (at least)
    ``hi``, ``per_decade`` buckets per decade. Fixed at construction — a
    streaming histogram must never reshape under load."""
    if lo <= 0 or hi <= lo:
        raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
    step = 10.0 ** (1.0 / max(1, int(per_decade)))
    out: list[float] = []
    v = lo
    while v < hi * (1.0 + 1e-9):
        # round to 4 significant digits so exposition `le` labels are stable
        out.append(float(f"{v:.4g}"))
        v *= step
    if out[-1] < hi:
        out.append(float(f"{hi:.4g}"))
    return tuple(dict.fromkeys(out))


class Histogram:
    """Fixed-bucket streaming histogram (Prometheus semantics: cumulative
    ``_bucket{le=...}`` counts plus ``_sum``/``_count``). ``record`` is the
    hot-loop call: one bisect + three int/float updates under a lock —
    cheap enough for per-token instrumentation (the engine's overhead
    bound test measures it against the decode step)."""

    __slots__ = ("name", "help", "_bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(
        self, name: str, help_: str = "", buckets: Optional[Sequence[float]] = None
    ) -> None:
        self.name = name
        self.help = help_
        bounds = tuple(sorted(buckets)) if buckets else log_buckets(1e-3, 60.0)
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1 = the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def record(self, value: float) -> None:
        # LOCK-FREE on purpose: every engine histogram has exactly ONE
        # writer thread (engine thread or fetch thread), so there are no
        # lost updates to guard against; readers (snapshot/percentile,
        # metrics thread) tolerate a value landing between their reads of
        # counts and sum. load()/reset() swap whole objects atomically
        # (GIL), so the worst interleaving is one dropped sample. This is
        # the hot-loop call the ≤1%-of-decode-step bound is measured on.
        i = bisect.bisect_left(self._bounds, value)
        self._counts[i] += 1
        self._sum += value
        self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def reset(self) -> None:
        """Zero all state (bounds keep). Benches reset after their warmup
        request so compile-time TTFT outliers don't own the tail."""
        with self._lock:
            self._counts = [0] * (len(self._bounds) + 1)
            self._sum = 0.0
            self._count = 0

    def _pct_from(self, counts: list, total: int, p: float) -> float:
        """p-quantile over ONE captured counts list: linear interpolation
        inside the winning bucket, the standard `histogram_quantile`
        estimator. 0.0 when empty; values past the last finite bound clamp
        to it (the +Inf bucket has no width)."""
        if total == 0:
            return 0.0
        rank = p * total
        seen = 0.0
        for i, c in enumerate(counts):
            seen += c
            if seen >= rank:
                if i >= len(self._bounds):
                    return self._bounds[-1]
                hi = self._bounds[i]
                lo = self._bounds[i - 1] if i > 0 else 0.0
                if c == 0:
                    return hi
                frac = (rank - (seen - c)) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        return self._bounds[-1]

    def percentile(self, p: float) -> float:
        with self._lock:
            counts = list(self._counts)
            total = self._count
        return self._pct_from(counts, total, p)

    def snapshot(self) -> dict:
        """Plain-dict snapshot, safe to serialize: cumulative bucket counts
        keyed by upper bound, plus sum/count and derived percentiles — all
        computed from ONE captured copy, so the percentiles can never
        disagree with the buckets they ship next to."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        cum: list[list[float]] = []
        acc = 0
        for bound, c in zip(self._bounds, counts):
            acc += c
            cum.append([bound, acc])
        return {
            "buckets": cum,
            "sum": round(s, 6),
            "count": total,
            "p50": round(self._pct_from(counts, total, 0.50), 6),
            "p90": round(self._pct_from(counts, total, 0.90), 6),
            "p99": round(self._pct_from(counts, total, 0.99), 6),
        }

    def load(self, snapshot: dict) -> None:
        """Overwrite this histogram's state from a ``snapshot()`` dict with
        the SAME bucket bounds — the exporter mirror path: the engine owns
        the live histogram, the metrics reporter re-exposes it."""
        cum = snapshot.get("buckets") or []
        if len(cum) != len(self._bounds):
            raise ValueError(
                f"snapshot has {len(cum)} buckets, histogram {self.name} "
                f"has {len(self._bounds)}"
            )
        counts = []
        prev = 0
        for _, acc in cum:
            counts.append(int(acc) - prev)
            prev = int(acc)
        total = int(snapshot.get("count", prev))
        counts.append(max(0, total - prev))  # +Inf bucket
        with self._lock:
            self._counts = counts
            self._count = total
            self._sum = float(snapshot.get("sum", 0.0))

    def exposition(self, safe_name: str) -> list[str]:
        """Prometheus text lines (TYPE/HELP emitted by the reporter)."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
        lines = []
        acc = 0
        for bound, c in zip(self._bounds, counts):
            acc += c
            le = f"{bound:g}"
            lines.append(f'{safe_name}_bucket{{le="{le}"}} {acc}')
        lines.append(f'{safe_name}_bucket{{le="+Inf"}} {total}')
        lines.append(f"{safe_name}_sum {s}")
        lines.append(f"{safe_name}_count {total}")
        return lines


class MetricsReporter:
    """In-memory reporter; also the base class for exporters."""

    def __init__(self, prefix: str = "", registry: Optional[dict] = None) -> None:
        self._prefix = prefix
        self._registry: dict[str, Counter | Gauge | Histogram] = (
            registry if registry is not None else {}
        )

    def with_prefix(self, prefix: str) -> "MetricsReporter":
        joined = f"{self._prefix}_{prefix}" if self._prefix else prefix
        return MetricsReporter(joined, self._registry)

    def _full(self, name: str) -> str:
        return f"{self._prefix}_{name}" if self._prefix else name

    def counter(
        self, name: str, help_: str = "", labels: Optional[dict] = None,
    ) -> Counter:
        full = self._full(name)
        key = full + _render_labels(labels) if labels else full
        c = self._registry.get(key)
        if not isinstance(c, Counter):
            c = Counter(full, help_, labels)
            self._registry[key] = c
        return c

    def gauge(
        self, name: str, help_: str = "", labels: Optional[dict] = None,
    ) -> Gauge:
        full = self._full(name)
        key = full + _render_labels(labels) if labels else full
        g = self._registry.get(key)
        if not isinstance(g, Gauge):
            g = Gauge(full, help_, labels)
            self._registry[key] = g
        return g

    def histogram(
        self, name: str, help_: str = "", buckets: Optional[Sequence[float]] = None
    ) -> Histogram:
        full = self._full(name)
        h = self._registry.get(full)
        if not isinstance(h, Histogram):
            h = Histogram(full, help_, buckets)
            self._registry[full] = h
        return h

    def prometheus_text(self) -> str:
        """Render all metrics in Prometheus text exposition format.
        Labeled series of one metric share a single HELP/TYPE block (the
        ``seen`` set dedupes by base name — registry keys carry the
        rendered labels, metric ``name`` attributes do not)."""
        lines: list[str] = []
        seen: set[str] = set()
        for _key, m in sorted(self._registry.items()):
            safe = m.name.replace("-", "_").replace(".", "_")
            if safe not in seen:
                seen.add(safe)
                if m.help:
                    lines.append(f"# HELP {safe} {m.help}")
                if isinstance(m, Histogram):
                    lines.append(f"# TYPE {safe} histogram")
                else:
                    kind = "counter" if isinstance(m, Counter) else "gauge"
                    lines.append(f"# TYPE {safe} {kind}")
            if isinstance(m, Histogram):
                lines.extend(m.exposition(safe))
                continue
            labels = m.labels
            if labels:
                lines.append(f"{safe}{_render_labels(labels)} {m.value}")
            else:
                lines.append(f"{safe} {m.value}")
        return "\n".join(lines) + "\n"


DISABLED = MetricsReporter()
