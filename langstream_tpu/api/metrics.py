"""Metrics SPI (reference api/runner/code/MetricsReporter.java:18).

Hierarchical reporters: ``with_prefix`` returns a child whose counters are
namespaced; the runtime installs a Prometheus-text implementation, tests use
the in-memory default. TPU additions: gauges for tokens/sec, TTFT, batch
occupancy, HBM use (SURVEY §5 observability note).
"""

from __future__ import annotations

import threading
from typing import Optional


class Counter:
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help_: str = "") -> None:
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()

    def count(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help_: str = "") -> None:
        self.name = name
        self.help = help_
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = v

    @property
    def value(self) -> float:
        return self._value


class MetricsReporter:
    """In-memory reporter; also the base class for exporters."""

    def __init__(self, prefix: str = "", registry: Optional[dict] = None) -> None:
        self._prefix = prefix
        self._registry: dict[str, Counter | Gauge] = registry if registry is not None else {}

    def with_prefix(self, prefix: str) -> "MetricsReporter":
        joined = f"{self._prefix}_{prefix}" if self._prefix else prefix
        return MetricsReporter(joined, self._registry)

    def _full(self, name: str) -> str:
        return f"{self._prefix}_{name}" if self._prefix else name

    def counter(self, name: str, help_: str = "") -> Counter:
        full = self._full(name)
        c = self._registry.get(full)
        if not isinstance(c, Counter):
            c = Counter(full, help_)
            self._registry[full] = c
        return c

    def gauge(self, name: str, help_: str = "") -> Gauge:
        full = self._full(name)
        g = self._registry.get(full)
        if not isinstance(g, Gauge):
            g = Gauge(full, help_)
            self._registry[full] = g
        return g

    def prometheus_text(self) -> str:
        """Render all metrics in Prometheus text exposition format."""
        lines: list[str] = []
        for name, m in sorted(self._registry.items()):
            safe = name.replace("-", "_").replace(".", "_")
            kind = "counter" if isinstance(m, Counter) else "gauge"
            if m.help:
                lines.append(f"# HELP {safe} {m.help}")
            lines.append(f"# TYPE {safe} {kind}")
            lines.append(f"{safe} {m.value}")
        return "\n".join(lines) + "\n"


DISABLED = MetricsReporter()
