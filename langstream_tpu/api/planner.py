"""Planner SPI: logical app → physical execution plan.

Parity: reference `api/runtime/` (ExecutionPlan.java:32-160, AgentNode,
ConnectionImplementation, ExecutionPlanOptimiser.java:22, ComputeClusterRuntime,
StreamingClusterRuntime). TPU-native addition: each AgentNode carries a
resolved ``TpuSpec`` so deployers can schedule device meshes (SURVEY §2.11).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Optional

from langstream_tpu.api.model import (
    AgentConfiguration,
    Application,
    AssetDefinition,
    ErrorsSpec,
    ResourcesSpec,
    TopicDefinition,
)


@dataclass
class Connection:
    """Physical endpoint of an agent: a topic or an in-process link after fusion."""

    TOPIC = "topic"
    INTERNAL = "internal"

    kind: str
    topic: Optional[str] = None

    @staticmethod
    def to_topic(name: str) -> "Connection":
        return Connection(kind=Connection.TOPIC, topic=name)

    @staticmethod
    def internal() -> "Connection":
        return Connection(kind=Connection.INTERNAL)


@dataclass
class AgentNode:
    """Physical agent (reference DefaultAgentNode). After fusion one node may
    host several logical agents (composite), mirroring
    ComposableAgentExecutionPlanOptimiser.mergeAgents:76."""

    id: str
    agent_type: str
    component_type: str  # source|processor|sink|service
    module_id: str
    pipeline_id: str
    configuration: dict[str, Any] = field(default_factory=dict)
    resources: ResourcesSpec = field(default_factory=ResourcesSpec)
    errors: ErrorsSpec = field(default_factory=ErrorsSpec)
    input: Optional[Connection] = None
    output: Optional[Connection] = None
    composite: list["AgentNode"] = field(default_factory=list)
    disk: bool = False
    signals_from: Optional[str] = None

    @property
    def is_composite(self) -> bool:
        return bool(self.composite)

    def logical_agents(self) -> list["AgentNode"]:
        return self.composite if self.composite else [self]


@dataclass
class ExecutionPlan:
    """Physical plan (reference ExecutionPlan.java:32-160)."""

    application_id: str
    topics: dict[str, TopicDefinition] = field(default_factory=dict)
    agents: dict[str, AgentNode] = field(default_factory=dict)
    assets: list[AssetDefinition] = field(default_factory=list)
    application: Optional[Application] = None

    def register_topic(self, topic: TopicDefinition) -> TopicDefinition:
        existing = self.topics.get(topic.name)
        if existing is not None:
            return existing
        self.topics[topic.name] = topic
        return topic

    def add_agent(self, node: AgentNode) -> None:
        if node.id in self.agents:
            raise ValueError(f"duplicate physical agent id {node.id!r}")
        self.agents[node.id] = node

    def agent_sequence(self) -> list[AgentNode]:
        return list(self.agents.values())


class ExecutionPlanOptimiser(abc.ABC):
    """Reference ExecutionPlanOptimiser.java:22."""

    @abc.abstractmethod
    def can_merge(self, previous: AgentNode, agent: AgentNode) -> bool: ...

    @abc.abstractmethod
    def merge(self, previous: AgentNode, agent: AgentNode, plan: ExecutionPlan) -> AgentNode: ...


@dataclass
class AgentNodeMetadata:
    """Deployer-specific placement metadata (k8s namespace, TPU node pool…)."""

    data: dict[str, Any] = field(default_factory=dict)


class ComputeClusterRuntime(abc.ABC):
    """Builds and deploys execution plans (reference ComputeClusterRuntime)."""

    @abc.abstractmethod
    def build_execution_plan(
        self, application_id: str, application: Application
    ) -> ExecutionPlan: ...

    async def deploy(self, plan: ExecutionPlan) -> None:  # noqa: B027
        pass

    async def delete(self, plan: ExecutionPlan) -> None:  # noqa: B027
        pass


class StreamingClusterRuntime(abc.ABC):
    """Topic naming/creation policy side (reference StreamingClusterRuntime)."""

    def pick_topic_name(self, topic: TopicDefinition) -> str:
        return topic.name

    async def deploy_topics(self, plan: ExecutionPlan) -> None:  # noqa: B027
        pass

    async def delete_topics(self, plan: ExecutionPlan) -> None:  # noqa: B027
        pass
