"""Storage SPIs: application store, global metadata, code storage, assets.

Parity: reference `api/storage/ApplicationStore.java`, `GlobalMetadataStore.java`,
`api/codestorage/CodeStorage.java`, `api/runner/assets/AssetManager.java`,
`api/database/VectorDatabaseWriterProvider.java`.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Optional

from langstream_tpu.api.model import Application, AssetDefinition, Secrets


@dataclass
class StoredApplication:
    application_id: str
    application: Application
    code_archive_id: Optional[str] = None
    status: dict[str, Any] = field(default_factory=dict)


class ApplicationStore(abc.ABC):
    @abc.abstractmethod
    def put(
        self,
        tenant: str,
        application_id: str,
        application: Application,
        code_archive_id: Optional[str],
    ) -> None: ...

    @abc.abstractmethod
    def get(self, tenant: str, application_id: str) -> Optional[StoredApplication]: ...

    @abc.abstractmethod
    def delete(self, tenant: str, application_id: str) -> None: ...

    @abc.abstractmethod
    def list(self, tenant: str) -> dict[str, StoredApplication]: ...

    def get_secrets(self, tenant: str, application_id: str) -> Optional[Secrets]:
        return None


class GlobalMetadataStore(abc.ABC):
    @abc.abstractmethod
    def put(self, key: str, value: str) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[str]: ...

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    @abc.abstractmethod
    def list(self) -> dict[str, str]: ...


@dataclass
class CodeArchiveMetadata:
    tenant: str
    code_store_id: str
    application_id: str
    digests: dict[str, str] = field(default_factory=dict)


class CodeStorage(abc.ABC):
    """App code archives (reference CodeStorage.java; S3CodeStorage impl)."""

    @abc.abstractmethod
    def store(self, tenant: str, application_id: str, archive_bytes: bytes) -> CodeArchiveMetadata: ...

    @abc.abstractmethod
    def download(self, tenant: str, code_store_id: str) -> bytes: ...

    @abc.abstractmethod
    def delete(self, tenant: str, code_store_id: str) -> None: ...


class AssetManager(abc.ABC):
    """Declarative infra asset lifecycle (reference AssetManager.java)."""

    async def initialize(self, asset: AssetDefinition) -> None:  # noqa: B027
        pass

    @abc.abstractmethod
    async def asset_exists(self) -> bool: ...

    @abc.abstractmethod
    async def deploy_asset(self) -> None: ...

    @abc.abstractmethod
    async def delete_asset(self) -> None: ...

    async def close(self) -> None:  # noqa: B027
        pass


class VectorDatabaseWriter(abc.ABC):
    """Reference api/database/VectorDatabaseWriter — used by vector-db-sink."""

    async def init(self, config: dict[str, Any]) -> None:  # noqa: B027
        pass

    @abc.abstractmethod
    async def upsert(self, record: Any, context: dict[str, Any]) -> None: ...

    async def close(self) -> None:  # noqa: B027
        pass


class DataSource(abc.ABC):
    """Queryable datasource (vector or SQL) resolved from a
    `configuration.resources` datasource entry.

    Reference: `ai/agents/datasource/DataSourceProvider` and the per-DB
    QueryStepDataSource implementations used by the `query` /
    `query-vector-db` agents.
    """

    async def init(self, config: dict[str, Any]) -> None:  # noqa: B027
        pass

    @abc.abstractmethod
    async def fetch_data(self, query: str, params: list[Any]) -> list[dict[str, Any]]: ...

    async def execute_statement(self, query: str, params: list[Any]) -> dict[str, Any]:
        """DML path (`mode: execute`); returns e.g. generated keys."""
        raise NotImplementedError("this datasource is read-only")

    async def close(self) -> None:  # noqa: B027
        pass
