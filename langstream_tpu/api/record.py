"""Record model: key / value / headers / origin / timestamp.

Parity: reference `api/runner/code/Record.java:20`, `SimpleRecord`, `Header`.
Records are immutable value objects; agents produce new records rather than
mutating inputs (the transform context in agents/genai mutates a scratch copy).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional, Protocol, runtime_checkable


@dataclass(frozen=True)
class Header:
    key: str
    value: Any

    def value_as_string(self) -> Optional[str]:
        if self.value is None:
            return None
        if isinstance(self.value, bytes):
            return self.value.decode("utf-8", errors="replace")
        return str(self.value)


@runtime_checkable
class Record(Protocol):
    """Structural record contract (reference Record.java:20)."""

    @property
    def key(self) -> Any: ...

    @property
    def value(self) -> Any: ...

    @property
    def origin(self) -> Optional[str]: ...

    @property
    def timestamp(self) -> Optional[float]: ...

    @property
    def headers(self) -> tuple[Header, ...]: ...


def get_header(record: "Record", key: str) -> Optional[Header]:
    for h in record.headers:
        if h.key == key:
            return h
    return None


def header_value(record: "Record", key: str, default: Any = None) -> Any:
    h = get_header(record, key)
    return h.value if h is not None else default


@dataclass(frozen=True)
class SimpleRecord:
    """Default Record implementation (reference SimpleRecord)."""

    value: Any
    key: Any = None
    headers: tuple[Header, ...] = field(default_factory=tuple)
    origin: Optional[str] = None
    timestamp: Optional[float] = None

    @staticmethod
    def of(
        value: Any,
        key: Any = None,
        headers: Optional[Iterable[Header | tuple[str, Any]]] = None,
        origin: Optional[str] = None,
        timestamp: Optional[float] = None,
    ) -> "SimpleRecord":
        hs: list[Header] = []
        for h in headers or ():
            hs.append(h if isinstance(h, Header) else Header(h[0], h[1]))
        return SimpleRecord(
            value=value,
            key=key,
            headers=tuple(hs),
            origin=origin,
            timestamp=timestamp if timestamp is not None else time.time(),
        )

    @staticmethod
    def copy_from(record: "Record", **overrides: Any) -> "SimpleRecord":
        base = dict(
            value=record.value,
            key=record.key,
            headers=tuple(record.headers),
            origin=record.origin,
            timestamp=record.timestamp,
        )
        base.update(overrides)
        return SimpleRecord(**base)

    def with_headers(self, extra: Iterable[Header | tuple[str, Any]]) -> "SimpleRecord":
        hs = list(self.headers)
        for h in extra:
            hs.append(h if isinstance(h, Header) else Header(h[0], h[1]))
        return SimpleRecord.copy_from(self, headers=tuple(hs))
