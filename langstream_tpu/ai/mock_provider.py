"""Mock AI provider for tests — the WireMock-stub tier of the reference's
test strategy (SURVEY §4: remote services tested against HTTP stubs, never
live APIs). Resource type ``mock-ai-configuration``.

Configuration:
  response: static completion text (default echoes the last user message)
  chunk-size: streaming chunk size in characters (default 4)
  embedding-dim: dimension of deterministic hash embeddings (default 8)
"""

from __future__ import annotations

import hashlib
import uuid
from typing import Any, Optional

import numpy as np

from langstream_tpu.ai.provider import (
    ChatChunk,
    ChatCompletionsResult,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
    StreamingChunksConsumer,
)


class MockCompletionsService(CompletionsService):
    def __init__(self, resource_config: dict[str, Any]) -> None:
        self.response: Optional[str] = resource_config.get("response")
        self.chunk_size = int(resource_config.get("chunk-size", 4))
        self.calls: list[dict[str, Any]] = []

    async def get_chat_completions(
        self,
        messages: list[ChatMessage],
        options: dict[str, Any],
        chunks_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionsResult:
        self.calls.append({"messages": messages, "options": options})
        content = (
            self.response
            if self.response is not None
            else f"echo: {messages[-1].content if messages else ''}"
        )
        if chunks_consumer is not None:
            answer_id = str(uuid.uuid4())
            pieces = [
                content[i : i + self.chunk_size]
                for i in range(0, len(content), self.chunk_size)
            ] or [""]
            for i, piece in enumerate(pieces):
                chunks_consumer(
                    ChatChunk(
                        content=piece,
                        index=i,
                        last=i == len(pieces) - 1,
                        answer_id=answer_id,
                    )
                )
        return ChatCompletionsResult(
            content=content,
            prompt_tokens=sum(len(m.content.split()) for m in messages),
            completion_tokens=len(content.split()),
        )


class MockEmbeddingsService(EmbeddingsService):
    def __init__(self, resource_config: dict[str, Any]) -> None:
        self.dim = int(resource_config.get("embedding-dim", 8))
        self.calls: list[list[str]] = []

    async def compute_embeddings(self, texts: list[str]) -> list[list[float]]:
        self.calls.append(list(texts))
        out = []
        for t in texts:
            seed = int.from_bytes(hashlib.sha256(t.encode()).digest()[:8], "big")
            v = np.random.default_rng(seed).normal(size=self.dim)
            out.append((v / np.linalg.norm(v)).tolist())
        return out


class MockAIProvider(ServiceProvider):
    def __init__(self, resource_config: dict[str, Any]) -> None:
        self.resource_config = resource_config
        self.completions = MockCompletionsService(resource_config)
        self.embeddings = MockEmbeddingsService(resource_config)

    def get_completions_service(self, config: dict[str, Any]) -> CompletionsService:
        return self.completions

    def get_embeddings_service(self, config: dict[str, Any]) -> EmbeddingsService:
        return self.embeddings


def register() -> None:
    from langstream_tpu.api.doc import ConfigModel
    from langstream_tpu.core.registry import REGISTRY, ResourceTypeInfo

    REGISTRY.register_resource(
        ResourceTypeInfo(
            type="mock-ai-configuration",
            description="Canned-response AI provider for tests.",
            config_model=ConfigModel(type="mock-ai-configuration", allow_unknown=True),
            factory=MockAIProvider,
        )
    )
