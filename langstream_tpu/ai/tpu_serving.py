"""TPU-native AI service provider: local JAX serving instead of remote APIs.

This is the component the whole rebuild exists for (BASELINE.md north star):
it implements the reference's ServiceProvider/CompletionsService/
EmbeddingsService SPI surface (`services/ServiceProvider.java:24`,
`completions/CompletionsService.java:22-33`, `embeddings/EmbeddingsService.java:24-36`)
with a local continuous-batching engine on the chip, replacing
`OpenAICompletionService.java` et al. Registered as resource type
``tpu-serving`` in `configuration.resources`.

Resource configuration:
  model: preset name (models.configs.MODEL_PRESETS) — gemma-2b, llama-3-8b, …
  tokenizer: "byte" (default) | "hf:<local path>"
  weights: "random" (default) | path to HF safetensors dir (models.loader)
  weight-streaming: auto (default) | off → streamed sharded weight load
    (models/streamload.py, docs/SERVING.md §22): safetensors shards are
    header-indexed and mmapped, a parallel reader pool assembles one
    LAYER at a time into staging, and per-layer device uploads overlap
    the next layer's host work — host RAM peaks at the readahead window
    instead of the eager path's ~2× weights, and the transfer TAIL
    overlaps engine compile-warmup (the load returns with uploads still
    in flight). Bit-exact vs the eager path on every architecture ×
    dtype; "off" is the escape hatch (and the bench_cold_start baseline)
  weight-load-workers: parallel shard-reader threads (default 4) — also
    sizes the readahead window (workers + 1 layers) the host-RAM staging
    peak is bounded by
  quantize-on-load: auto (default) | off → with `quantization: int8`,
    quantize each layer ON DEVICE as it streams in, so an int8
    deployment never materializes the full-precision tree anywhere —
    host holds one staging window, device holds the int8 tree plus one
    full-precision layer. "off" loads full-precision first, then runs
    the eager quantize_params pass (big models fall back to the
    host-staged eager path). Identical numerics either way
  max-batch / max-seq-len / prefill-buckets / decode-chunk: engine knobs
  kv-layout: paged (default) | dense → KV memory layout. "paged" is the
    unified page-table-indexed device pool (serving/pagepool.py): decode,
    chunked prefill and speculative verify all attend through per-slot
    page tables (ONE compiled program each — the kv_bound compile ladder
    is gone), and prefix reuse aliases pages zero-copy. Legal under
    multi-host SPMD (allocator events ride the leader→follower wire,
    docs/SERVING.md §14) and sharded meshes (the pool shards kv heads on
    "model"). "dense" is the per-slot big-cache layout, kept ONE release
    as the escape hatch (it also carries the ring long-prefill path,
    which paged does not speak yet). `page-size` (default 64 tokens)
    sizes a page;
    `kv-pages` overrides the pool's page count (default: dense-parity
    capacity + `prefix-cache-fraction` alias headroom — see
    docs/SERVING.md §11 for the memory-plan math and migration notes)
  overlap: true (default) → fused prefill–decode iterations (every device
    dispatch carries a token-budgeted slice of pending prefill work plus
    the decode chunk — the gateway-TTFT lever, PERF.md round 6)
  prefill-token-budget: prefill tokens per fused iteration (default: the
    chunked-prefill segment width = the largest prefill bucket)
  max-prefill-streams: concurrent chunked-prefill local caches (default 2
    with overlap, 1 without; each costs one long-prefill cache of HBM)
  prefix-cache: auto | off (default off) → automatic cross-request prefix
    KV reuse (serving/prefix_cache.py): shared prompt preambles prefill
    once, later admissions gather the cached KV and prefill only the
    suffix. `prefix-cache-fraction` (default 0.25) sizes the device pool
    relative to the decode cache; `prefix-cache-entries` overrides the
    row count directly (0 disables the pool entirely). The memory plan
    accounts the pool before warmup.
  host-kv-fraction: tiered KV (docs/SERVING.md §16; paged layout +
    prefix-cache only) — sizes a pinned host-RAM page arena relative to
    the device pool (e.g. 8.0 = 8× the pool in host RAM; 0, the default,
    disables the tier). Idle published prefixes spill into it off the hot
    loop (`spill-idle-s`, default 0 = as soon as published) and under HBM
    pressure LRU eviction DEMOTES to the host copy instead of dropping —
    a hibernated session's next turn restores its KV at DMA speed instead
    of re-prefilling. `spill: auto|off` (default auto) is the escape
    hatch; a restore blocking an admission past `restore-stall-dump-s`
    (default 1.0) produces a `spill-stall` flight dump. Leader-side host
    state: construction-disabled under SPMD (an explicit warning, like
    adapters in round 14).
  speculation: auto | off (default off) → self-speculative decoding
    (serving/speculation.py + engine._verify_chunk): host-side n-gram
    prompt-lookup drafts verified k+1-at-a-time in one device dispatch —
    one weight read emits up to k+1 tokens per slot on repetitive text.
    `speculation-tokens` (default 4) is k, fixed engine-wide (one compiled
    verify ladder). Runs under SPMD too (drafts ride the wire, §14);
    composes with overlap, prefix-cache, and both KV dtypes
    (docs/SERVING.md §10).
  adapters: list of LoRA adapters to register at startup — each entry
    {name, rank (8), scale (1.0), path (HF/peft safetensors dir) | seed
    (random init)}. One engine then serves base + every adapter MIXED in
    the same decode dispatch (serving/adapters.py; per-request selection
    via the completion option `adapter: <name>`). `adapter-pool-fraction`
    (default 0.1) sizes the hot device pool as a fraction of weight HBM —
    adapters beyond it stay registered and hot-swap in LRU (watch
    engine_adapter_swaps_total); `adapter-rank` pads all adapters to one
    pool rank; `adapter-pool-rows` overrides the row count directly.
    Not yet on the SPMD wire (single-host engines only); docs §15
  constrained-decoding: auto (default) | off → grammar-constrained
    decoding (serving/constrain.py): a request carrying
    `response-format: {type: json_schema|regex, ...}` compiles to a
    token-level DFA and the sampler masks illegal tokens every step, so
    structured output is guaranteed valid — including through the
    speculative verify path. `grammar-slots` (default 64 — the packed
    bitmask pool made rows ~32× cheaper than the old dense table, so
    hundreds of resident grammars are affordable; 0 disables constrained
    decoding), `grammar-states` (default 128) and `grammar-exceptions`
    (default 65536 — per-row capacity for non-default transitions) size
    the device pool; the memory plan logs the cost (≈0.3GiB at a 256k
    vocab with 64 slots — docs §15 has the sizing table)
  queue-depth / shed-policy: bounded admission queue; "block" (default)
    backpressures the broker poll loop, "reject" sheds with a retry-after
    (ShedError) so front doors degrade to fast 429s under overload
  tenants: multi-tenant overload control (serving/tenancy.py, docs
    §19) — list of {name, weight (1.0), max-slots, queue-share,
    token-rate, burst-s} blocks. Admission becomes per-tenant weighted
    deficit round-robin (the fused iteration's prefill-token budget and
    the free-slot pool divide by weight, work-conserving), per-tenant
    queue shares shed the burster instead of backpressuring everyone,
    and token-rate quotas make over-quota tenants shed FIRST under
    pressure. Unknown tenants get weight 1.0 and no caps; requests
    without a tenant land in "default".
  brownout: auto (default) | off — the graceful-degradation ladder
    (docs §19): under sustained load (engine load_score ≥
    `brownout-enter-load`, default 2.0, held for `brownout-dwell-s`,
    default 0.5) the engine walks spec-shrink → spec-off → reject-low →
    reject-quota one hysteresis-gated step at a time, and walks back
    down once load holds ≤ `brownout-exit-load` (default 1.0). Every
    transition is counted, logged and flight-dumped (`brownout` reason);
    decode of admitted work is never degraded in correctness.
  engine-restart-backoff / engine-max-restarts: loop-crash recovery —
    quarantine in-flight slots, rebuild device state, restart under
    bounded exponential backoff (single-host only; SPMD stays crash-only)
  drain-grace-s: close() drains (finish in-flight, reject new) this many
    seconds before the hard stop
  fault-injection / fault-seed / fault-stall-s: deterministic fault drills
    (serving/faultinject.py; also via LSTPU_FAULTS env)
  observability: true (default) → streaming latency histograms (TTFT,
    inter-token, queue wait, dispatch/fetch times → stats()["histograms"],
    /metrics exposition and the Grafana heatmap), per-request lifecycle
    spans on /traces, the derived load score, and the flight recorder.
    `flight-recorder-iterations` (default 256) sizes the ring of engine
    iterations dumped on NaN/page quarantines, restarts and shed bursts;
    `flight-dir` (or LSTPU_FLIGHT_DIR) writes dump JSON files there
    (docs/SERVING.md §12)
  fleet: auto | off (default off) → resolve each completion through the
    fleet router (serving/fleet.py): prefix-affinity-first, load-second
    dispatch across this engine plus the peer replicas in
    `fleet-replicas` (list of beacon base-URLs or {id,url} dicts).
    `fleet-lambda` (default 256) trades warm-prefix tokens against load;
    `fleet-policy` (affinity | round-robin | least-loaded) exists for
    benches; `fleet-replica-id`/`fleet-self-url` identify THIS replica in
    beacons; `fleet-beacon-ttl-s`/`fleet-refresh-interval-s`/
    `fleet-sticky-ttl-s` tune health and session stickiness
    (docs/SERVING.md §13). The /state beacon and /fleet/generate endpoint
    are served regardless of this knob — fleet: off only means THIS
    process routes nothing.
  fleet-role: prefill | decode | mixed (default mixed) → disaggregated
    prefill/decode (docs/SERVING.md §18): the role rides this replica's
    beacon; routers steer prefill-heavy admissions (estimated prefill ≥
    `fleet-prefill-threshold`, default 2048 tokens) at prefill-tagged
    replicas, run prefill + the first token there, MIGRATE the KV pages
    (`POST /fleet/migrate`, lstpu-kvmig-v1, per-page blake2b checksums)
    to a decode replica, and finish the stream where the steady decode
    pool lives. `fleet-migrate: auto|off` disables only the transfer
    (roles still steer; streams decode in place);
    `fleet-migrate-timeout-s` (default 30) bounds each transfer — on ANY
    migration failure the stream decodes in place on the prefill
    replica, token-exact, and the fallback is counted + flight-dumped.
  spmd-parity-echo: false (default) → on multi-host replicas, re-broadcast
    every processed decode/verify chunk's tokens so followers verify them
    against their own device results (one extra broadcast per chunk; a
    mismatch attempts ONE coordinated resync, then crashes the replica
    with a flight dump — docs/SERVING.md §14/§20 divergence semantics)
  spmd-watchdog-s: 30 (0 = off) → slice resilience bound (docs/SERVING.md
    §20): follower recv deadline (2×), leader idle-heartbeat cadence
    (¼×) and the leader's per-iteration fetch bound (1×) — a wedged or
    dead leader is detected within 2× instead of parking every pod in a
    collective forever; must exceed the worst single warmup family's
    compile stall (warm the compile cache, or raise it)
  spmd-resync-window-s: 60 → a second divergence within this window of a
    granted resync stays fatal (transient wire loss does not repeat)
  compile-cache-dir: persistent XLA compile cache directory — a scale-up
    replica pointed at a warm (shared) cache dir skips the warmup
    ladder's compile wall and serves in seconds (fleet cold-start lever)
  mesh: {model: N, data: M, expert: K} → shard weights over the local mesh
  quantization: "int8" → weight-only int8 (halves weight HBM traffic; big
    models stage on the host so the bf16 tree never needs device HBM)
  kv-cache-quantization: "int8" → int8 KV cache with per-token per-head
    scales (int8×int8 MXU attention; ~halves decode cache bandwidth —
    the lever that matters for GQA models like llama, see PERF.md)
  hbm-bytes: device HBM budget for that staging decision (default 16GiB)

Streaming follows the reference's growth batching (OpenAICompletionService:
"start from 1 chunk, then double the size until min-chunks-per-message"), so
the first token becomes the first chunk — TTFT is one decode step.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import uuid
from typing import Any, Optional

import numpy as np

from langstream_tpu.ai.provider import (
    ChatChunk,
    ChatCompletionsResult,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
    StreamingChunksConsumer,
)
from langstream_tpu.models.configs import MODEL_PRESETS, GenerationOptions, ModelConfig

log = logging.getLogger(__name__)


class _EngineHolder:
    """Lazy, thread-safe singleton build of tokenizer/params/engine —
    engine construction compiles XLA programs, so it must happen once."""

    def __init__(self, config: dict[str, Any]) -> None:
        self.config = config
        self._lock = threading.Lock()
        self._engine = None
        self._tokenizer = None
        self._model_config: Optional[ModelConfig] = None
        self._params = None
        self._embed_fn = None
        self._mesh = None
        self._fleet_router = None
        self._fleet_replica_id: Optional[str] = None
        # checkpoint→device load accounting (filled by params(), read by
        # build_engine → ServingEngine stats()/memory plan); {} for
        # random-init weights
        self._weight_load_report: dict[str, Any] = {}
        # ONE injector instance for the whole holder: the streamed loader
        # consults the `weight-load` site during params() and the engine
        # consults every other site — sharing the instance keeps the
        # seeded schedule and call counters coherent across both
        self._injector: Any = None
        self._injector_built = False

    def mesh(self):
        """Device mesh for TP/EP sharding when `mesh` is configured."""
        if self._mesh is None and self.config.get("mesh"):
            from langstream_tpu.parallel.mesh import build_mesh

            self._mesh = build_mesh(dict(self.config["mesh"]))
        return self._mesh

    def model_config(self) -> ModelConfig:
        if self._model_config is None:
            name = self.config.get("model", "tiny-test")
            if name not in MODEL_PRESETS:
                raise ValueError(
                    f"unknown model preset {name!r}; known: {sorted(MODEL_PRESETS)}"
                )
            mc = MODEL_PRESETS[name]
            kv_mode = str(self.config.get("kv-cache-quantization", "") or "").lower()
            if kv_mode not in ("", "none", "int8"):
                raise ValueError(
                    f"unknown kv-cache-quantization {kv_mode!r}; "
                    "supported: int8, none"
                )
            if kv_mode == "int8":
                import dataclasses

                mc = dataclasses.replace(mc, kv_cache_dtype="int8")
            self._model_config = mc
        return self._model_config

    def tokenizer(self):
        if self._tokenizer is None:
            from langstream_tpu.serving.tokenizer import get_tokenizer

            self._tokenizer = get_tokenizer(self.config.get("tokenizer", "byte"))
        return self._tokenizer

    def params(self):
        import jax

        if self._params is None:
            import contextlib
            import time

            from langstream_tpu.models.transformer import init_params

            weights = self.config.get("weights", "random")
            mc = self.model_config()
            quant_mode = str(self.config.get("quantization", "") or "").lower()
            if quant_mode not in ("", "none", "int8", "w8"):
                raise ValueError(
                    f"unknown quantization {quant_mode!r}; supported: int8"
                )
            quantize = quant_mode in ("int8", "w8")
            streaming = self.config.get("weight-streaming", "auto")
            if not isinstance(streaming, bool) and str(streaming).lower() not in (
                "auto", "off",
            ):
                raise ValueError(
                    f"unknown weight-streaming {streaming!r}; "
                    "supported: auto, off"
                )
            stream_on = (
                streaming is True
                or (not isinstance(streaming, bool)
                    and str(streaming).lower() == "auto")
            )
            workers = int(self.config.get("weight-load-workers", 4))
            if workers < 1:
                raise ValueError(
                    f"weight-load-workers must be >= 1, got {workers}"
                )
            qol = self.config.get("quantize-on-load", "auto")
            if not isinstance(qol, bool) and str(qol).lower() not in (
                "auto", "off",
            ):
                raise ValueError(
                    f"unknown quantize-on-load {qol!r}; supported: auto, off"
                )
            qol_on = quantize and (
                qol is True
                or (not isinstance(qol, bool) and str(qol).lower() == "auto")
            )
            # models whose full-precision tree would not fit device HBM are
            # built + quantized on the HOST and shipped int8 (host init is
            # slower, so small models stay on-device). The STREAMED int8
            # path never materializes the full-precision tree, so it skips
            # the host stage entirely — unless quantize-on-load is forced
            # off, which reinstates the eager host-staged economics.
            hbm_budget = int(self.config.get("hbm-bytes", 16 * 1024**3))
            needs_host = quantize and mc.approx_params * 2 > hbm_budget // 2
            if stream_on and needs_host and quantize and not qol_on:
                stream_on = False
            report: dict[str, Any] = {}
            if weights not in (None, "random") and stream_on:
                from langstream_tpu.models.streamload import (
                    load_params_streamed,
                )

                # block=False: the transfer tail rides JAX async dispatch,
                # so engine construction + compile-warmup below overlap the
                # last layers' uploads (the §22 cold-start lever)
                params, rep = load_params_streamed(
                    weights,
                    mc,
                    workers=workers,
                    quantize=qol_on,
                    fault_injector=self._fault_injector(),
                    block=False,
                )
                if quantize and not qol_on:
                    from langstream_tpu.models.quant import quantize_params

                    params = quantize_params(params, mc)
                report = rep.as_dict()
            else:
                t0 = time.perf_counter()
                scope = (
                    jax.default_device(jax.devices("cpu")[0])
                    if needs_host
                    else contextlib.nullcontext()
                )
                with scope:
                    if weights in (None, "random"):
                        params = init_params(mc, jax.random.PRNGKey(0))
                    else:
                        from langstream_tpu.models.loader import load_params

                        params = load_params(weights, mc)
                    if quantize:
                        from langstream_tpu.models.quant import quantize_params

                        params = quantize_params(params, mc)
                if needs_host and self.mesh() is None:
                    # no mesh: move the int8 tree onto the accelerator
                    # ourselves (with a mesh, shard_params below owns
                    # placement)
                    params = jax.device_put(params, jax.devices()[0])
                if weights not in (None, "random"):
                    # the eager baseline's ledger, so streamed-vs-eager is
                    # comparable in stats()/bench without a code path probe
                    jax.block_until_ready(params)
                    report = {
                        "streamed": False,
                        "workers": 1,
                        "quantize-on-load": False,
                        "total-s": round(time.perf_counter() - t0, 4),
                        "bytes-read": sum(
                            leaf.size * leaf.dtype.itemsize
                            for leaf in jax.tree.leaves(params)
                        ),
                    }
            mesh = self.mesh()
            if mesh is not None:
                from langstream_tpu.parallel.sharding import shard_params

                params = shard_params(params, mesh, mc)
            self._weight_load_report = report
            self._params = params
        return self._params

    def build_engine(self, start: bool = True):
        """Construct the (possibly SPMD) engine. ``start=False`` is the
        multi-host follower path: the caller runs follower_loop over the
        channel instead of the leader's device loop."""
        from langstream_tpu.parallel.multihost import DistributedConfig
        from langstream_tpu.serving.engine import ServingEngine

        # persistent XLA compile cache (fleet fast cold start): a scale-up
        # replica pointed at a warm shared cache dir deserializes every
        # warmup program instead of recompiling — seconds instead of the
        # compile wall. Must be set BEFORE any jit below runs.
        cache_dir = self.config.get("compile-cache-dir")
        if cache_dir:
            from langstream_tpu.serving.engine import (
                enable_persistent_compile_cache,
            )

            enable_persistent_compile_cache(str(cache_dir))
        mc = self.model_config()
        layout = str(self.config.get("kv-layout", "paged")).lower()
        if layout not in ("paged", "dense"):
            raise ValueError(
                f"unknown kv-layout {layout!r}; supported: paged, dense"
            )
        page_size = int(self.config.get("page-size", 64))
        if page_size < 1:
            raise ValueError(f"page-size must be >= 1, got {page_size}")
        px = self.config.get("prefix-cache", "off")
        if not isinstance(px, bool) and str(px).lower() not in ("auto", "off"):
            raise ValueError(
                f"unknown prefix-cache {px!r}; supported: auto, off"
            )
        spill = self.config.get("spill", "auto")
        if not isinstance(spill, bool) and str(spill).lower() not in (
            "auto", "off",
        ):
            raise ValueError(f"unknown spill {spill!r}; supported: auto, off")
        host_kv_fraction = float(self.config.get("host-kv-fraction", 0.0))
        if host_kv_fraction < 0:
            raise ValueError(
                f"host-kv-fraction must be >= 0, got {host_kv_fraction}"
            )
        spill_idle_s = float(self.config.get("spill-idle-s", 0.0))
        spec = self.config.get("speculation", "off")
        if not isinstance(spec, bool) and str(spec).lower() not in ("auto", "off"):
            raise ValueError(
                f"unknown speculation {spec!r}; supported: auto, off"
            )
        spec_tokens = int(self.config.get("speculation-tokens", 4))
        if spec_tokens < 1:
            raise ValueError(
                f"speculation-tokens must be >= 1, got {spec_tokens}"
            )
        constrained = self.config.get("constrained-decoding", "auto")
        if not isinstance(constrained, bool) and str(constrained).lower() not in (
            "auto", "off",
        ):
            raise ValueError(
                f"unknown constrained-decoding {constrained!r}; "
                "supported: auto, off"
            )
        buckets = tuple(
            self.config.get("prefill-buckets", (32, 64, 128, 256, 512, 1024, 2048))
        )
        max_batch = int(self.config.get("max-batch", 8))
        prefill_batch = self.config.get("prefill-batch")
        max_seq = int(self.config.get("max-seq-len", min(2048, mc.max_seq_len)))
        spmd = None
        dist = DistributedConfig.from_env()
        if dist.is_multihost:
            # every process of the replica builds an IDENTICAL channel
            # (page/draft buffer sizes derive from the shared config); the
            # leader announces, followers replay (parallel/spmd_serving.py,
            # docs/SERVING.md §14 — prefix reuse, speculation and the
            # paged allocator all ride the wire since round 13)
            from langstream_tpu.parallel.spmd_serving import SpmdChannel
            from langstream_tpu.serving.pagepool import table_len_for

            spmd = SpmdChannel(
                prefill_batch=int(prefill_batch or ServingEngine.PREFILL_BATCH),
                max_width=max(buckets),
                max_batch=max_batch,
                table_len=(
                    table_len_for(max_seq, page_size)
                    if layout == "paged"
                    else 0
                ),
                spec_tokens=spec_tokens,
                echo=bool(self.config.get("spmd-parity-echo", False)),
                decode_chunk=int(self.config.get("decode-chunk", 16)),
                # slice resilience (docs/SERVING.md §20): the watchdog
                # bound arms idle heartbeats, the follower recv deadline
                # AND the leader's per-iteration fetch bound; 0 disables
                # all three (the pre-round-19 park-in-the-collective
                # behavior). Default 30s: it must exceed the worst single
                # warmup family's compile stall (seconds against a warm
                # persistent compile cache; set higher — or 0 — for cold
                # caches through a slow tunnel). The resync window is the
                # follower's repeat-divergence fatality rule.
                watchdog_s=float(self.config.get("spmd-watchdog-s", 30.0)),
                resync_window_s=float(
                    self.config.get("spmd-resync-window-s", 60.0)
                ),
            )
        # disaggregated serving (docs/SERVING.md §18): the replica's role —
        # validated HERE so a bad knob fails before the engine builds, and
        # passed down so role-tagged replicas budget migration staging RAM
        fleet_role = str(self.config.get("fleet-role") or "mixed")
        if fleet_role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"unknown fleet-role {fleet_role!r}; supported: prefill, "
                "decode, mixed"
            )
        engine = ServingEngine(
            mc,
            self.params(),
            max_batch=max_batch,
            max_seq_len=max_seq,
            eos_token_id=self.tokenizer().eos_token_id,
            prefill_buckets=buckets,
            mesh=self.mesh(),
            decode_chunk=int(self.config.get("decode-chunk", 16)),
            prefill_batch=prefill_batch,
            spmd=spmd,
            pipeline_depth=int(self.config.get("pipeline-depth", 1)),
            ttft_chunk_floor=int(self.config.get("ttft-chunk-floor", 4)),
            # default (None): precompile the decode ladder on TPU backends
            # so no XLA compile ever lands mid-traffic (PERF.md round 5b)
            precompile=self.config.get("precompile"),
            overlap=bool(self.config.get("overlap", True)),
            prefill_token_budget=(
                int(self.config["prefill-token-budget"])
                if self.config.get("prefill-token-budget") is not None
                else None
            ),
            max_prefill_streams=(
                int(self.config["max-prefill-streams"])
                if self.config.get("max-prefill-streams") is not None
                else None
            ),
            kv_layout=layout,  # validated at the top of this method
            page_size=page_size,
            kv_pages=(
                int(self.config["kv-pages"])
                if self.config.get("kv-pages") is not None
                else None
            ),
            # tiered KV (docs/SERVING.md §16): host-RAM spill + hibernation
            host_kv_fraction=host_kv_fraction,
            spill=spill,
            spill_idle_s=spill_idle_s,
            restore_stall_dump_s=float(
                self.config.get("restore-stall-dump-s", 1.0)
            ),
            # durable session tier (docs/SERVING.md §23): crash-safe disk
            # checkpoints — `durable: auto` turns on iff `durable-dir` is
            # set, so the block is one knob in the common case
            durable=self.config.get("durable", "auto"),
            durable_dir=(
                str(self.config["durable-dir"])
                if self.config.get("durable-dir")
                else None
            ),
            durable_max_bytes=int(self.config.get("durable-max-bytes", 0)),
            durable_timeout_s=float(
                self.config.get("durable-timeout-s", 5.0)
            ),
            prefix_cache=px,  # validated at the top of this method
            prefix_cache_fraction=float(
                self.config.get("prefix-cache-fraction", 0.25)
            ),
            prefix_cache_entries=(
                int(self.config["prefix-cache-entries"])
                if self.config.get("prefix-cache-entries") is not None
                else None
            ),
            speculation=spec,  # validated at the top of this method
            speculation_tokens=spec_tokens,
            # the agentic tier (docs/SERVING.md §15): multi-LoRA adapters +
            # grammar-constrained decoding
            adapters=list(self.config.get("adapters") or []),
            adapter_pool_fraction=float(
                self.config.get("adapter-pool-fraction", 0.1)
            ),
            adapter_rank=(
                int(self.config["adapter-rank"])
                if self.config.get("adapter-rank") is not None
                else None
            ),
            adapter_pool_rows=(
                int(self.config["adapter-pool-rows"])
                if self.config.get("adapter-pool-rows") is not None
                else None
            ),
            constrained_decoding=constrained,
            grammar_slots=int(self.config.get("grammar-slots", 64)),
            grammar_states=int(self.config.get("grammar-states", 128)),
            grammar_exceptions=int(
                self.config.get("grammar-exceptions", 65536)
            ),
            grammar_tokenizer=self.tokenizer(),
            # request lifecycle / fault recovery (docs/SERVING.md §9)
            queue_depth=(
                int(self.config["queue-depth"])
                if self.config.get("queue-depth") is not None
                else None
            ),
            shed_policy=str(self.config.get("shed-policy", "block")),
            # multi-tenant overload control + brownout (docs/SERVING.md
            # §19): validated inside ServingEngine/TenantSpec so a bad
            # block fails the build, not the first burst
            tenants=list(self.config.get("tenants") or []),
            brownout=self.config.get("brownout", "auto"),
            brownout_enter_load=float(
                self.config.get("brownout-enter-load", 2.0)
            ),
            brownout_exit_load=float(
                self.config.get("brownout-exit-load", 1.0)
            ),
            brownout_dwell_s=float(
                self.config.get("brownout-dwell-s", 0.5)
            ),
            restart_backoff_s=float(
                self.config.get("engine-restart-backoff", 0.1)
            ),
            max_restarts=int(self.config.get("engine-max-restarts", 5)),
            fault_injector=self._fault_injector(),
            migrate_staging=fleet_role != "mixed",
            # per-phase load timings + staging peak from params() (§22);
            # params is an earlier argument, so the report is populated by
            # the time this kwarg is evaluated
            weight_load_report=self._weight_load_report,
            # observability layer (docs/SERVING.md §12): histograms +
            # request spans + flight recorder; off is the escape hatch for
            # the measured (<1%) hot-loop overhead
            observability=bool(self.config.get("observability", True)),
            flight_iterations=int(
                self.config.get("flight-recorder-iterations", 256)
            ),
            flight_dir=(
                str(self.config["flight-dir"])
                if self.config.get("flight-dir")
                else None
            ),
        )
        if start:
            engine.start()
            # publish this engine's state beacon + fleet dispatch endpoint
            # on the runtime HTTP server (serving/fleet.py registry): GET
            # /state and POST /fleet/generate work in every topology, not
            # just fleet-mode ones (the router on ANOTHER pod reads them)
            from langstream_tpu.serving import fleet as fleet_mod

            rid = str(self.config.get("fleet-replica-id") or "local")
            url = str(self.config.get("fleet-self-url") or "")
            role = fleet_role  # validated before the engine build above
            self._fleet_replica_id = rid
            fleet_mod.register_local(
                rid,
                beacon_fn=lambda: fleet_mod.beacon_from_engine(
                    rid, engine, url=url, role=role
                ),
                generate_fn=lambda payload: fleet_mod.engine_generate(
                    engine, payload
                ),
                # streaming remote dispatch (docs/SERVING.md §17): frames
                # flow to the dispatching router as the engine delivers
                # tokens, so a remote route keeps local TTFT semantics
                generate_stream_fn=(
                    lambda payload: fleet_mod.engine_generate_stream(
                        engine, payload
                    )
                ),
                # KV-page migration (docs/SERVING.md §18): inbound binds
                # and outbound pushes for disaggregated prefill/decode —
                # served regardless of the fleet knob, like /state
                migrate_bind_fn=(
                    lambda frames, timeout_s=30.0:
                    fleet_mod.engine_migrate_bind(
                        engine, frames, timeout_s
                    )
                ),
                migrate_out_fn=lambda payload: fleet_mod.engine_migrate_out(
                    engine, payload
                ),
                # peer-to-peer page fetch (docs/SERVING.md §21): serve
                # pages to a radix-missing peer (copy, never release) and
                # pull from an owner on command; the limits probe bounds
                # what /fleet/migrate will read off the wire
                migrate_pages_fn=(
                    lambda payload: fleet_mod.engine_migrate_pages(
                        engine, payload
                    )
                ),
                p2p_fetch_fn=lambda payload: fleet_mod.engine_p2p_fetch(
                    engine, payload
                ),
                migrate_limits_fn=engine.migrate_limits,
                reset_fn=engine.reset_histograms,
                # one attribute read (never stats()) — /healthz surfaces
                # the crash→rebuild→backoff window for readiness probes
                recovering_fn=lambda: engine.recovering,
                # same discipline for the durable tier (§23): True while
                # a disk restore is serving an admission, so readiness
                # can tell resurrection-in-progress from wedged
                restoring_fn=lambda: getattr(engine, "restoring", False),
            )
        return engine

    def _fault_injector(self):
        """Config-driven fault injection (staging drills): `fault-injection`
        is the spec string (serving/faultinject.py grammar), `fault-seed`
        pins the schedule. Built ONCE and cached: the streamed weight loader
        consults the `weight-load` site during params() and the engine
        consults every other site — a single instance keeps the seeded
        per-site schedule coherent across both consumers. With no config
        spec we fall back to LSTPU_FAULTS env activation here (instead of
        leaving it to the engine) for the same sharing reason."""
        if not self._injector_built:
            from langstream_tpu.serving.faultinject import FaultInjector

            spec = str(self.config.get("fault-injection", "") or "").strip()
            if spec:
                self._injector = FaultInjector(
                    spec,
                    seed=int(self.config.get("fault-seed", 0)),
                    stall_s=float(self.config.get("fault-stall-s", 0.05)),
                )
            else:
                self._injector = FaultInjector.from_env()
            self._injector_built = True
        return self._injector

    def engine(self):
        with self._lock:
            if self._engine is None:
                self._engine = self.build_engine(start=True)
            return self._engine

    def fleet_router(self):
        """The fleet router when `fleet: auto` is configured, else None.
        The router fronts THIS engine (InProcessReplica — local requests
        never pay an HTTP hop) plus every peer URL in `fleet-replicas`;
        its beacon refresher starts with it (docs/SERVING.md §13)."""
        mode = self.config.get("fleet", "off")
        mode_s = str(mode).lower()
        if mode is False or mode_s in ("off", "false", "none", ""):
            return None
        if mode is not True and mode_s != "auto":
            raise ValueError(f"unknown fleet mode {mode!r}; supported: auto, off")
        engine = self.engine()  # outside the lock: engine() takes it
        with self._lock:
            if self._fleet_router is None:
                from langstream_tpu.serving.fleet import (
                    FleetRouter,
                    HttpReplica,
                    InProcessReplica,
                    register_local_router,
                )

                rid = self._fleet_replica_id or "local"
                replicas: list[Any] = [
                    InProcessReplica(
                        rid, engine,
                        url=str(self.config.get("fleet-self-url") or ""),
                        role=str(self.config.get("fleet-role") or "mixed"),
                    )
                ]
                for peer in self.config.get("fleet-replicas") or []:
                    if isinstance(peer, dict):
                        replicas.append(
                            HttpReplica(
                                str(peer.get("id") or peer["url"]),
                                str(peer["url"]),
                            )
                        )
                    else:
                        replicas.append(HttpReplica(str(peer), str(peer)))
                router = FleetRouter(
                    replicas,
                    lam=float(self.config.get("fleet-lambda", 256.0)),
                    policy=str(self.config.get("fleet-policy", "affinity")),
                    beacon_ttl_s=float(
                        self.config.get("fleet-beacon-ttl-s", 10.0)
                    ),
                    refresh_interval_s=float(
                        self.config.get("fleet-refresh-interval-s", 0.5)
                    ),
                    sticky_ttl_s=float(
                        self.config.get("fleet-sticky-ttl-s", 600.0)
                    ),
                    # disaggregated prefill/decode (docs/SERVING.md §18)
                    prefill_route_threshold=int(
                        self.config.get("fleet-prefill-threshold", 2048)
                    ),
                    migrate=str(
                        self.config.get("fleet-migrate", "auto")
                    ).lower() not in ("off", "false", "0", "none"),
                    migrate_timeout_s=float(
                        self.config.get("fleet-migrate-timeout-s", 30.0)
                    ),
                    # peer-to-peer page fetch on radix miss (§21)
                    p2p=str(
                        self.config.get("fleet-p2p", "auto")
                    ).lower() not in ("off", "false", "0", "none"),
                    p2p_threshold=int(
                        self.config.get("fleet-p2p-threshold", 256)
                    ),
                    # fetch-vs-prefill cost model floor (§23): below this
                    # token gap a hint never fetches, estimates or not
                    p2p_min_gap=int(
                        self.config.get("fleet-p2p-min-gap", 0)
                    ),
                )
                router.start()
                # the HTTP prefetch surface (§23): POST /fleet/prefetch
                # reaches this router through the process registry
                register_local_router(router)
                self._fleet_router = router
            return self._fleet_router

    def embed_fn(self):
        with self._lock:
            if self._embed_fn is None:
                import functools

                import jax

                from langstream_tpu.models.transformer import encode
                from langstream_tpu.parallel.multihost import DistributedConfig

                if DistributedConfig.from_env().is_multihost:
                    # followers only replay the serving engine's dispatches
                    # (spmd_serving); an embed jit over the global mesh would
                    # hang in its first collective waiting for peers. Fail
                    # fast until embed ops join the SPMD channel.
                    raise RuntimeError(
                        "embeddings are not yet supported on a multi-host "
                        "(tpu.hosts > 1) replica — run the embedding model "
                        "on a single-host agent"
                    )

                self._embed_fn = functools.partial(
                    jax.jit(encode, static_argnames=("config",)),
                    config=self.model_config(),
                )
            return self._embed_fn

    def begin_drain(self) -> None:
        """The graceful HALF of teardown, callable while the runtime HTTP
        server is still up: stop routing, unregister the fleet beacon
        (peers see /state go empty within one refresh instead of racing
        new remote routes into the drain window — routes that would die
        as hop failures and charge the WRONG replica's breaker), then
        drain the engine so in-flight remote streams finish over the
        still-open wire. Idempotent; close() finishes with the hard
        stop."""
        with self._lock:
            if getattr(self, "_drain_begun", False):
                # idempotent for real: _serve() drains before its server
                # stops, then close() runs — a second drain() here would
                # wait the full grace period AGAIN on a wedged stream,
                # doubling worst-case shutdown
                return
            self._drain_begun = True
            router, self._fleet_router = self._fleet_router, None
            rid, self._fleet_replica_id = self._fleet_replica_id, None
            engine = self._engine
        if router is not None:
            from langstream_tpu.serving.fleet import unregister_local_router

            unregister_local_router()
            router.stop()
        if rid is not None:
            from langstream_tpu.serving import fleet as fleet_mod

            fleet_mod.unregister_local(rid)
        if engine is not None:
            # graceful: finish in-flight, reject new (ShedError) for a
            # bounded grace period — stop() alone _fail_alls work that
            # only needed a few more chunks
            engine.drain(float(self.config.get("drain-grace-s", 10.0)))
            # replica hibernation (§23): with the durable tier on,
            # checkpoint every live session to disk AFTER the drain
            # (streams finished; entries quiesced) and BEFORE close()'s
            # engine.stop() kills the command loop. No-op ({}) with the
            # tier off; failure degrades to whatever already checkpointed
            # — the drain itself never blocks on a wedged disk.
            if hasattr(engine, "hibernate"):
                ledger = engine.hibernate(rid or "")
                if ledger:
                    log.info(
                        "replica %s hibernated: %s session prefix(es), "
                        "%s bytes, %s failure(s)",
                        rid or "local", ledger.get("entries"),
                        ledger.get("bytes"), ledger.get("failures"),
                    )

    def close(self) -> None:
        self.begin_drain()
        with self._lock:
            engine, self._engine = self._engine, None
        if engine is not None:
            engine.stop()


class _StreamState:
    """Growth batching: flush after 1 raw token, then 2, 4, … capped at
    min_chunks — the reference provider's schedule."""

    def __init__(self, tokenizer, consumer: StreamingChunksConsumer, min_chunks: int):
        self.tokenizer = tokenizer
        self.consumer = consumer
        self.min_chunks = max(1, min_chunks)
        self.threshold = 1
        self.pending = 0
        self.tokens: list[int] = []
        self.emitted_text = ""
        self.index = 0
        self.answer_id = str(uuid.uuid4())

    def on_token(self, token: int) -> None:
        self.tokens.append(token)
        self.pending += 1
        if self.pending >= self.threshold:
            self._flush(last=False)
            self.threshold = min(self.threshold * 2, self.min_chunks)

    def _flush(self, last: bool) -> None:
        if last:
            text = self.tokenizer.decode(self.tokens)
        else:
            # a token boundary may split a multibyte char: hold back the
            # undecodable tail so the next flush re-emits it whole
            text = self.tokenizer.decode_stream_prefix(self.tokens)
            if not text.startswith(self.emitted_text):
                # decode prefix not stable yet (mid-grapheme) — wait
                self.pending = 0
                return
        delta = text[len(self.emitted_text) :]
        if delta or last:
            self.consumer(
                ChatChunk(content=delta, index=self.index, last=last, answer_id=self.answer_id)
            )
            self.index += 1
            self.emitted_text = text
        self.pending = 0

    def finish(self) -> None:
        self._flush(last=True)


class TpuCompletionsService(CompletionsService):
    def __init__(self, holder: _EngineHolder, step_config: dict[str, Any]) -> None:
        self.holder = holder
        self.step_config = step_config

    def engine_stats(self) -> dict[str, Any]:
        """Batch occupancy etc. for the serving gauges (only meaningful once
        the engine exists — never force a build just to report zeros)."""
        engine = self.holder._engine
        return engine.stats() if engine is not None else {}

    def fleet_stats(self) -> dict[str, Any]:
        """Router counters for the fleet gauges (empty when fleet: off or
        the router was never built — never force a build to report zeros)."""
        router = self.holder._fleet_router
        return router.stats() if router is not None else {}

    def _render_prompt(self, messages: list[ChatMessage]) -> str:
        tok = self.holder.tokenizer()
        hf = getattr(tok, "_tok", None)
        if hf is not None and getattr(hf, "chat_template", None):
            return hf.apply_chat_template(
                [{"role": m.role, "content": m.content} for m in messages],
                tokenize=False,
                add_generation_prompt=True,
            )
        lines = [f"{m.role}: {m.content}" for m in messages]
        lines.append("assistant:")
        return "\n".join(lines)

    async def get_chat_completions(
        self,
        messages: list[ChatMessage],
        options: dict[str, Any],
        chunks_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionsResult:
        return await self._generate(self._render_prompt(messages), options, chunks_consumer)

    async def get_text_completions(
        self,
        prompt: list[str],
        options: dict[str, Any],
        chunks_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionsResult:
        return await self._generate("\n".join(prompt), options, chunks_consumer)

    def _finish_result(
        self,
        tokens: list[int],
        finish_reason: str,
        prompt_tokens: int,
        ttft_s: float,
        total_s: float,
        options: dict[str, Any],
        stream_state: Optional["_StreamState"],
    ) -> ChatCompletionsResult:
        if stream_state is not None:
            stream_state.finish()
        content = self.holder.tokenizer().decode(tokens)
        # string-level stop sequences (token-level stops handled in-engine)
        for stop in options.get("stop") or []:
            cut = content.find(stop)
            if cut >= 0:
                content = content[:cut]
        return ChatCompletionsResult(
            content=content,
            finish_reason=finish_reason,
            prompt_tokens=prompt_tokens,
            completion_tokens=len(tokens),
            ttft_ms=ttft_s * 1000.0,
            total_ms=total_s * 1000.0,
        )

    async def _fleet_dispatch(
        self,
        router: Any,
        prompt_tokens: list[int],
        options: dict[str, Any],
        chunks_consumer: Optional[StreamingChunksConsumer],
    ) -> Optional[ChatCompletionsResult]:
        """Resolve one request through the fleet router. Returns None when
        the FIRST route lands on THIS replica (the caller runs the native
        zero-hop streaming path) and the completed result when it was
        dispatched over the wire.

        The hop STREAMS (docs/SERVING.md §17): router.stream_generate
        frames pipe straight into the gateway chunk writers as the peer
        delivers tokens, so a remote route keeps local TTFT semantics —
        the first chunk reaches the client long before the completion
        finishes. A peer dying mid-stream fails over WARM inside the
        router (prompt + delivered tokens re-dispatched to a survivor;
        prefix reuse makes the resume cheap) — this layer only keeps the
        cross-process cancel registration pointed at whichever replica
        currently owns the stream. The hop budget derives from the
        request's own deadline, never the flat default. Fleet sheds
        surface as the engine's ShedError so the pipeline's 429 handling
        is one code path."""
        import asyncio

        from langstream_tpu.serving import lifecycle
        from langstream_tpu.serving.engine import ShedError
        from langstream_tpu.serving.fleet import (
            FleetShedError,
            ReplicaError,
            close_frames,
            hop_timeout_s,
        )

        session_id = str(options.get("cancel-key") or "") or None
        # cross-process cancel (ROADMAP 3b): the cancel-key RIDES to the
        # peer — engine_generate_stream registers the request in the
        # peer's process-local lifecycle registry — and the owning replica
        # is recorded here per hop, so lifecycle.cancel() on a client
        # disconnect forwards POST /fleet/cancel and the remote decode
        # dies at the next chunk boundary instead of at its deadline
        remote_options = dict(options)
        loop = asyncio.get_running_loop()
        frames = router.stream_generate(
            prompt_tokens, remote_options, session_id=session_id,
            timeout_s=hop_timeout_s(options),
        )

        def _next():
            try:
                return next(frames)
            except StopIteration:
                return None

        delivered: list[int] = []
        end: Optional[dict] = None
        owner_url: Optional[str] = None
        stream_state = None

        def _point_cancel_at(url: str, is_local: bool) -> None:
            # keep exactly one remote-owner registration live, following
            # the stream across failovers
            nonlocal owner_url
            if owner_url is not None and session_id:
                lifecycle.unregister_remote(session_id, owner_url)
            owner_url = None
            if (
                session_id and url and not is_local
                and not url.startswith("local:")
            ):
                lifecycle.register_remote(session_id, url)
                owner_url = url

        # ONE try/finally owns the stream from here: a cancellation at ANY
        # await below (including the first fetch) must close the router
        # generator so the serving replica cancels its in-flight request
        try:
            try:
                first = await loop.run_in_executor(None, _next)
            except FleetShedError as e:
                raise ShedError(str(e), retry_after_s=e.retry_after_s) from e
            if first is None:
                return None  # defensive: empty stream means nothing routed
            if (
                first.get("kind") == "route"
                and first.get("local")
                and not first.get("disagg")
            ):
                # the route landed HERE: hand back to the native streaming
                # path before any dispatch happened (the route decision and
                # its counters/stickiness stand — this replica serves it).
                # NOT for a disagg prefill-handoff route (§18): the router
                # owns that orchestration (prefill here, migrate, decode
                # elsewhere) — short-circuiting would decode in place and
                # silently disable disaggregation on the local replica
                return None
            if chunks_consumer is not None:
                stream_state = _StreamState(
                    self.holder.tokenizer(),
                    chunks_consumer,
                    int(options.get("min-chunks-per-message", 20)),
                )
            frame: Optional[dict] = first
            while frame is not None:
                kind = frame.get("kind")
                if kind == "route":
                    _point_cancel_at(
                        str(frame.get("url") or ""),
                        bool(frame.get("local")),
                    )
                elif kind == "tokens":
                    for t in frame.get("tokens") or []:
                        delivered.append(int(t))
                        if stream_state is not None:
                            stream_state.on_token(int(t))
                elif kind == "end":
                    end = frame
                    break
                try:
                    frame = await loop.run_in_executor(None, _next)
                except FleetShedError as e:
                    raise ShedError(
                        str(e), retry_after_s=e.retry_after_s
                    ) from e
        except ReplicaError:
            if delivered:
                raise  # tokens already streamed: a local restart would dup
            # every replica DIED before the first token (sheds raise
            # FleetShedError→ShedError above, never this): serve locally
            # (cold) rather than fail — the engine in this process may be
            # healthy even when the router has it quarantined
            return None
        finally:
            if owner_url is not None and session_id:
                lifecycle.unregister_remote(session_id, owner_url)
            # race-safe: an executor thread may still be inside next()
            # when this coroutine is cancelled
            close_frames(frames)
        if end is None:
            raise ReplicaError(
                "fleet stream ended without a terminal frame"
            )
        return self._finish_result(
            delivered,
            str(end.get("finish_reason", "stop")),
            int(end.get("prompt_tokens", len(prompt_tokens))),
            float(end.get("ttft_s", 0.0)),
            float(end.get("total_s", 0.0)),
            options,
            stream_state,
        )

    async def _generate(
        self,
        prompt: str,
        options: dict[str, Any],
        chunks_consumer: Optional[StreamingChunksConsumer],
    ) -> ChatCompletionsResult:
        from langstream_tpu.serving.engine import GenerationRequest

        engine = self.holder.engine()
        tokenizer = self.holder.tokenizer()
        gen_options = GenerationOptions.from_dict(options)
        prompt_tokens = tokenizer.encode(prompt)
        router = self.holder.fleet_router()
        if router is not None:
            remote = await self._fleet_dispatch(
                router, prompt_tokens, options, chunks_consumer
            )
            if remote is not None:
                return remote
        stream_state = None
        on_token = None
        if chunks_consumer is not None:
            stream_state = _StreamState(
                tokenizer,
                chunks_consumer,
                int(options.get("min-chunks-per-message", 20)),
            )
            on_token = stream_state.on_token

        loop = asyncio.get_running_loop()
        done: asyncio.Future = loop.create_future()

        def _on_done(res) -> None:  # engine thread → event loop
            loop.call_soon_threadsafe(
                lambda: done.done() or done.set_result(res)
            )

        # trace correlation: the record's propagated ls-trace-id (forwarded
        # by the completions step) wins; else join whatever agent span is
        # active so the engine's request spans stitch into the pipeline
        # trace on /traces either way
        from langstream_tpu.tracing import TRACER

        trace_id = str(options.get("trace-id") or "") or TRACER.current_trace_id()
        request = GenerationRequest(
            prompt_tokens=prompt_tokens,
            options=gen_options,
            on_token=on_token,
            on_done=_on_done,
            trace_id=trace_id,
        )
        # client-disconnect wiring: the gateway cancels every request
        # registered under the record's session header when the websocket
        # drops (serving/lifecycle.py), so an abandoned stream stops
        # consuming decode steps within one chunk
        from langstream_tpu.serving import lifecycle

        cancel_key = str(options.get("cancel-key") or "")
        if cancel_key:
            lifecycle.register(cancel_key, request)
        try:
            # submit may block on a full queue (backpressure) → executor; the
            # WAIT is a loop future resolved by on_done, so an in-flight
            # generation holds no thread and agent fan-out isn't capped by
            # the executor pool size. Under shed-policy=reject the engine
            # raises ShedError with a retry-after estimate — honor it here
            # with a few PACED retries, so pipeline-level error handling
            # doesn't hammer the overloaded engine with immediate
            # resubmits (the 429/Retry-After contract, in-process)
            from langstream_tpu.serving.engine import ShedError

            for attempt in range(3):
                try:
                    await loop.run_in_executor(None, engine.submit, request)
                    break
                except ShedError as shed:
                    if attempt == 2:
                        raise
                    await asyncio.sleep(min(max(shed.retry_after_s, 0.05), 5.0))
            try:
                result = await asyncio.wait_for(done, 600.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                # the awaiting task died (agent timeout / task cancellation):
                # without this the engine decodes the orphan to
                # max_new_tokens while its slot serves nobody
                request.cancel()
                raise
        finally:
            if cancel_key:
                lifecycle.unregister(cancel_key, request)
        if result.error is not None:
            raise result.error
        # finish_reason may be "cancelled"/"deadline": partial output flows
        # through normally (the record commits, the dead client's answer
        # goes unread) — raising here would only trigger pipeline retries
        # for work the client already abandoned
        return self._finish_result(
            result.tokens,
            result.finish_reason,
            result.prompt_tokens,
            result.ttft_s,
            result.total_s,
            options,
            stream_state,
        )


class TpuEmbeddingsService(EmbeddingsService):
    def __init__(self, holder: _EngineHolder, step_config: dict[str, Any]) -> None:
        self.holder = holder
        self.max_len = int(step_config.get("max-text-tokens", 512))

    async def compute_embeddings(self, texts: list[str]) -> list[list[float]]:
        import jax.numpy as jnp

        tokenizer = self.holder.tokenizer()
        params = self.holder.params()
        embed = self.holder.embed_fn()

        token_lists = [tokenizer.encode(t)[: self.max_len] for t in texts]
        # bucket the width to limit recompiles
        width = 16
        longest = max((len(t) for t in token_lists), default=1)
        while width < longest:
            width *= 2
        batch = np.zeros((len(texts), width), np.int32)
        lengths = np.zeros(len(texts), np.int32)
        for i, toks in enumerate(token_lists):
            batch[i, : len(toks)] = toks
            lengths[i] = max(1, len(toks))

        loop = asyncio.get_running_loop()

        def run():
            out = embed(params, jnp.asarray(batch), jnp.asarray(lengths))
            return np.asarray(out)

        vectors = await loop.run_in_executor(None, run)
        return [v.tolist() for v in vectors]


class TpuServingProvider(ServiceProvider):
    def __init__(self, resource_config: dict[str, Any]) -> None:
        self.holder = _EngineHolder(resource_config)

    def get_completions_service(self, config: dict[str, Any]) -> CompletionsService:
        return TpuCompletionsService(self.holder, config)

    def get_embeddings_service(self, config: dict[str, Any]) -> EmbeddingsService:
        return TpuEmbeddingsService(self.holder, config)

    async def close(self) -> None:
        # holder.close() drains synchronously for up to drain-grace-s —
        # run it off-loop so in-flight chunk-write coroutines (what the
        # draining generations are producing) keep running
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self.holder.close)


def register() -> None:
    from langstream_tpu.api.doc import ConfigModel
    from langstream_tpu.core.registry import REGISTRY, ResourceTypeInfo

    REGISTRY.register_resource(
        ResourceTypeInfo(
            type="tpu-serving",
            description="Local JAX/TPU completions+embeddings serving engine.",
            config_model=ConfigModel(type="tpu-serving", allow_unknown=True),
            factory=TpuServingProvider,
        )
    )
