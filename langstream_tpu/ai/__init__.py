"""AI service-provider layer: the slot the TPU serving engine plugs into.

Parity: reference `langstream-ai-agents` provider SPI
(`services/ServiceProvider.java:24`, `completions/CompletionsService.java:22-33`,
`embeddings/EmbeddingsService.java:24-36`). SURVEY §2.5: "The TPU serving
provider implements exactly this SPI surface."
"""

from langstream_tpu.ai.provider import (
    ChatChunk,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
    ServiceProviderRegistry,
    StreamingChunksConsumer,
)

__all__ = [
    "ChatChunk",
    "ChatMessage",
    "CompletionsService",
    "EmbeddingsService",
    "ServiceProvider",
    "ServiceProviderRegistry",
    "StreamingChunksConsumer",
]


def register_providers() -> None:
    """Register built-in AI resource types (called from agents bootstrap)."""
    from langstream_tpu.ai import mock_provider, openai_compat, remote_cloud, tpu_serving

    mock_provider.register()
    tpu_serving.register()
    openai_compat.register()
    remote_cloud.register()


register_providers()
