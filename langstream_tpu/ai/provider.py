"""Completions / embeddings provider SPI + registry.

Parity: reference `ai/agents/services/ServiceProvider.java:24`,
`completions/CompletionsService.java:22-33` (getChatCompletions with a
StreamingChunksConsumer), `embeddings/EmbeddingsService.java:24-36`, and the
provider registry resolved from `configuration.resources` entries
(AIProvidersResourceProvider). The TPU JAX provider registers as resource type
``tpu-serving`` (replacing `open-ai-configuration` et al. as the default).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from langstream_tpu.api.model import Application, Resource


@dataclass
class ChatMessage:
    role: str
    content: str

    @staticmethod
    def from_dict(d: dict) -> "ChatMessage":
        return ChatMessage(role=str(d.get("role", "user")), content=str(d.get("content", "")))


@dataclass
class ChatChunk:
    """One streamed delta (reference Chunk/StreamingChunksConsumer contract)."""

    content: str
    index: int
    last: bool
    answer_id: str = ""


# consume_chunk(chunk) — called for every streamed delta, including the last
StreamingChunksConsumer = Callable[[ChatChunk], None]


@dataclass
class ChatCompletionsResult:
    content: str
    role: str = "assistant"
    finish_reason: str = "stop"
    prompt_tokens: int = 0
    completion_tokens: int = 0
    ttft_ms: float = 0.0
    total_ms: float = 0.0
    extra: dict[str, Any] = field(default_factory=dict)


class CompletionsService(abc.ABC):
    """Reference CompletionsService.java:22-33."""

    @abc.abstractmethod
    async def get_chat_completions(
        self,
        messages: list[ChatMessage],
        options: dict[str, Any],
        chunks_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionsResult: ...

    async def get_text_completions(
        self,
        prompt: list[str],
        options: dict[str, Any],
        chunks_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionsResult:
        messages = [ChatMessage(role="user", content=p) for p in prompt]
        return await self.get_chat_completions(messages, options, chunks_consumer)


class EmbeddingsService(abc.ABC):
    """Reference EmbeddingsService.java:24-36."""

    @abc.abstractmethod
    async def compute_embeddings(self, texts: list[str]) -> list[list[float]]: ...


class ServiceProvider(abc.ABC):
    """Reference ServiceProvider.java:24."""

    @abc.abstractmethod
    def get_completions_service(self, config: dict[str, Any]) -> CompletionsService: ...

    @abc.abstractmethod
    def get_embeddings_service(self, config: dict[str, Any]) -> EmbeddingsService: ...

    async def close(self) -> None:  # noqa: B027
        pass


class ServiceProviderRegistry:
    """Resolves providers from the app's `configuration.resources` entries.

    Agents ask for completions/embeddings either by explicit resource id
    (configuration ``ai-service``) or by taking the first AI resource declared
    (the reference behaves the same with its single-provider lookup).
    """

    def __init__(self, application: Optional[Application] = None) -> None:
        self._providers: dict[str, ServiceProvider] = {}
        self._datasources: dict[str, Any] = {}
        self._resources: dict[str, Resource] = {}
        if application is not None:
            from langstream_tpu.api.storage import DataSource
            from langstream_tpu.core.registry import REGISTRY

            for rid, resource in application.resources.items():
                info = REGISTRY.resource(resource.type)
                if info is not None and info.factory is not None:
                    provider = info.factory(resource.configuration)
                    if isinstance(provider, ServiceProvider):
                        self._providers[rid] = provider
                        self._resources[rid] = resource
                    elif isinstance(provider, DataSource):
                        self._datasources[rid] = provider
                        self._resources[rid] = resource

    def register(self, resource_id: str, provider: ServiceProvider) -> None:
        self._providers[resource_id] = provider

    def register_datasource(self, resource_id: str, datasource: Any) -> None:
        self._datasources[resource_id] = datasource

    def get_datasource(self, resource_id: Optional[str] = None) -> Any:
        if resource_id is not None:
            if resource_id not in self._datasources:
                raise ValueError(
                    f"no datasource for resource {resource_id!r}; "
                    f"known: {sorted(self._datasources)}"
                )
            return self._datasources[resource_id]
        if not self._datasources:
            raise ValueError(
                "no datasource configured; declare a configuration.resources "
                "entry of type datasource/vector-database"
            )
        return next(iter(self._datasources.values()))

    def get_provider(self, resource_id: Optional[str] = None) -> ServiceProvider:
        if resource_id is not None:
            if resource_id not in self._providers:
                raise ValueError(
                    f"no AI service provider for resource {resource_id!r}; "
                    f"known: {sorted(self._providers)}"
                )
            return self._providers[resource_id]
        if not self._providers:
            raise ValueError(
                "no AI service provider configured; declare a configuration.resources "
                "entry (e.g. type tpu-serving)"
            )
        return next(iter(self._providers.values()))

    async def close(self) -> None:
        import logging

        for target in (*self._providers.values(), *self._datasources.values()):
            try:
                await target.close()
            except Exception:  # noqa: BLE001 — close the rest regardless
                logging.getLogger(__name__).exception(
                    "error closing AI provider/datasource"
                )
