"""Bedrock + Vertex AI remote providers (SDK-free HTTP).

Parity: reference `langstream-ai-agents/.../services/impl/BedrockService...`
(SigV4-signed `POST /model/{id}/invoke` on bedrock-runtime) and
`VertexAIProvider` (`POST .../publishers/google/models/{model}:predict` with
a bearer token). Rebuilt on the same stdlib SigV4 signer the s3-source agent
uses (`agents/storage/_sigv4_headers`, service="bedrock") and plain
aiohttp — no boto3, no google-cloud SDK.

These restore the reference's "mix remote models into the app" capability
class alongside the TPU-local provider and the OpenAI-compatible provider
(openai_compat.py): one app can route some steps to the local chip and
others to Bedrock/Vertex."""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Optional

from langstream_tpu.ai.provider import (
    ChatChunk,
    ChatCompletionsResult,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
    StreamingChunksConsumer,
)


def _consume_whole(
    content: str, chunks_consumer: Optional[StreamingChunksConsumer]
) -> None:
    """Non-streaming backends still honor the chunk contract: one content
    chunk + the last marker."""
    if chunks_consumer is None:
        return
    answer_id = uuid.uuid4().hex
    chunks_consumer(ChatChunk(content=content, index=0, last=False, answer_id=answer_id))
    chunks_consumer(ChatChunk(content="", index=1, last=True, answer_id=answer_id))


class BedrockCompletions(CompletionsService):
    def __init__(self, provider: "BedrockProvider", config: dict[str, Any]) -> None:
        self.provider = provider
        self.config = config

    async def get_chat_completions(
        self,
        messages: list[ChatMessage],
        options: dict[str, Any],
        chunks_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionsResult:
        model = options.get("model") or self.provider.model
        # anthropic-messages request shape (the common bedrock chat schema);
        # parameters-by-name pass through via options["parameters"]
        system = "\n".join(m.content for m in messages if m.role == "system")
        body: dict[str, Any] = {
            "anthropic_version": "bedrock-2023-05-31",
            "max_tokens": int(
                options.get("max-tokens") or options.get("max-new-tokens") or 256
            ),
            "messages": [
                {"role": m.role, "content": m.content}
                for m in messages
                if m.role != "system"
            ],
            **dict(options.get("parameters") or {}),
        }
        if system:
            body["system"] = system
        start = time.monotonic()
        payload = await self.provider.invoke(model, body)
        content = ""
        for block in payload.get("content", []):
            if block.get("type") == "text":
                content += block.get("text", "")
        if not content and "completion" in payload:  # titan/claude-v1 shapes
            content = payload["completion"]
        total_ms = (time.monotonic() - start) * 1e3
        _consume_whole(content, chunks_consumer)
        usage = payload.get("usage", {})
        return ChatCompletionsResult(
            content=content,
            finish_reason=payload.get("stop_reason") or "stop",
            prompt_tokens=int(usage.get("input_tokens", 0)),
            completion_tokens=int(usage.get("output_tokens", 0)),
            ttft_ms=total_ms,
            total_ms=total_ms,
        )


class BedrockEmbeddings(EmbeddingsService):
    def __init__(self, provider: "BedrockProvider", config: dict[str, Any]) -> None:
        self.provider = provider
        self.config = config

    async def compute_embeddings(self, texts: list[str]) -> list[list[float]]:
        model = self.config.get("model") or self.provider.embeddings_model
        out: list[list[float]] = []
        for text in texts:  # titan embeddings: one text per invoke
            payload = await self.provider.invoke(model, {"inputText": text})
            out.append([float(x) for x in payload.get("embedding", [])])
        return out


class BedrockProvider(ServiceProvider):
    """`bedrock-configuration` resource: ``region``, ``access-key``,
    ``secret-key``, default ``model`` / ``embeddings-model``; ``endpoint``
    overrides the bedrock-runtime URL for tests."""

    def __init__(self, config: dict[str, Any]) -> None:
        self.region = config.get("region", "us-east-1")
        self.endpoint = str(
            config.get("endpoint")
            or f"https://bedrock-runtime.{self.region}.amazonaws.com"
        ).rstrip("/")
        self.access_key = config.get("access-key", "")
        self.secret_key = config.get("secret-key", "")
        self.model = config.get("model", "")
        self.embeddings_model = config.get("embeddings-model", "")
        self._session: Any = None

    async def invoke(self, model: str, body: dict[str, Any]) -> dict[str, Any]:
        import aiohttp

        from langstream_tpu.agents.storage import _sigv4_headers

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        from urllib.parse import quote

        url = f"{self.endpoint}/model/{quote(model, safe='')}/invoke"
        payload = json.dumps(body).encode()
        headers = _sigv4_headers(
            "POST", url, self.region, self.access_key, self.secret_key,
            payload, service="bedrock",
        )
        headers["Content-Type"] = "application/json"
        async with self._session.post(url, data=payload, headers=headers) as resp:
            data = await resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"bedrock invoke {model} failed ({resp.status}): {data[:300]!r}"
                )
            return json.loads(data)

    def get_completions_service(self, config: dict[str, Any]) -> CompletionsService:
        return BedrockCompletions(self, config)

    def get_embeddings_service(self, config: dict[str, Any]) -> EmbeddingsService:
        return BedrockEmbeddings(self, config)

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


class VertexCompletions(CompletionsService):
    def __init__(self, provider: "VertexProvider", config: dict[str, Any]) -> None:
        self.provider = provider
        self.config = config

    async def get_chat_completions(
        self,
        messages: list[ChatMessage],
        options: dict[str, Any],
        chunks_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionsResult:
        model = options.get("model") or self.provider.model
        contents = []
        system: Optional[dict] = None
        for m in messages:
            if m.role == "system":
                system = {"parts": [{"text": m.content}]}
                continue
            role = "model" if m.role == "assistant" else "user"
            contents.append({"role": role, "parts": [{"text": m.content}]})
        body: dict[str, Any] = {"contents": contents}
        if system is not None:
            body["systemInstruction"] = system
        generation: dict[str, Any] = {}
        if options.get("max-tokens") or options.get("max-new-tokens"):
            generation["maxOutputTokens"] = int(
                options.get("max-tokens") or options["max-new-tokens"]
            )
        if options.get("temperature") is not None:
            generation["temperature"] = options["temperature"]
        if generation:
            body["generationConfig"] = generation
        start = time.monotonic()
        payload = await self.provider.post(f"{model}:generateContent", body)
        content = ""
        for candidate in payload.get("candidates", [])[:1]:
            for part in candidate.get("content", {}).get("parts", []):
                content += part.get("text", "")
        total_ms = (time.monotonic() - start) * 1e3
        _consume_whole(content, chunks_consumer)
        usage = payload.get("usageMetadata", {})
        return ChatCompletionsResult(
            content=content,
            prompt_tokens=int(usage.get("promptTokenCount", 0)),
            completion_tokens=int(usage.get("candidatesTokenCount", 0)),
            ttft_ms=total_ms,
            total_ms=total_ms,
        )


class VertexEmbeddings(EmbeddingsService):
    def __init__(self, provider: "VertexProvider", config: dict[str, Any]) -> None:
        self.provider = provider
        self.config = config

    async def compute_embeddings(self, texts: list[str]) -> list[list[float]]:
        model = self.config.get("model") or self.provider.embeddings_model
        payload = await self.provider.post(
            f"{model}:predict", {"instances": [{"content": t} for t in texts]}
        )
        return [
            [float(x) for x in p.get("embeddings", {}).get("values", [])]
            for p in payload.get("predictions", [])
        ]


class VertexProvider(ServiceProvider):
    """`vertex-configuration` resource: ``url`` (regional endpoint),
    ``project``, ``region``, ``token`` (bearer — the reference takes a
    service-account json OR a token; only the token path is SDK-free),
    default ``model`` / ``embeddings-model``."""

    def __init__(self, config: dict[str, Any]) -> None:
        self.region = config.get("region", "us-central1")
        self.project = config.get("project", "")
        base = config.get("url") or f"https://{self.region}-aiplatform.googleapis.com"
        self.base = str(base).rstrip("/")
        self.token = config.get("token", "")
        self.model = config.get("model", "")
        self.embeddings_model = config.get("embeddings-model", "")
        self._session: Any = None

    async def post(self, model_verb: str, body: dict[str, Any]) -> dict[str, Any]:
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        url = (
            f"{self.base}/v1/projects/{self.project}/locations/{self.region}"
            f"/publishers/google/models/{model_verb}"
        )
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        async with self._session.post(url, json=body, headers=headers) as resp:
            data = await resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"vertex {model_verb} failed ({resp.status}): {data[:300]!r}"
                )
            return json.loads(data)

    def get_completions_service(self, config: dict[str, Any]) -> CompletionsService:
        return VertexCompletions(self, config)

    def get_embeddings_service(self, config: dict[str, Any]) -> EmbeddingsService:
        return VertexEmbeddings(self, config)

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


def register() -> None:
    from langstream_tpu.api.doc import ConfigModel
    from langstream_tpu.core.registry import REGISTRY, ResourceTypeInfo

    REGISTRY.register_resource(
        ResourceTypeInfo(
            type="bedrock-configuration",
            description="AWS Bedrock remote models (SigV4, SDK-free).",
            config_model=ConfigModel(type="bedrock-configuration", allow_unknown=True),
            factory=BedrockProvider,
        )
    )
    REGISTRY.register_resource(
        ResourceTypeInfo(
            type="vertex-configuration",
            description="Google Vertex AI remote models (bearer token).",
            config_model=ConfigModel(type="vertex-configuration", allow_unknown=True),
            factory=VertexProvider,
        )
    )
