"""OpenAI-compatible remote AI provider (HTTP + SSE streaming).

Parity: reference `langstream-ai-agents/.../impl/OpenAICompletionService.java`
(+ the `open-ai-configuration` resource in AIProvidersResourceProvider) — the
capability it restores is MIXING models in one app: TPU-local serving for the
models you host, remote OpenAI-compatible endpoints (OpenAI, vLLM, Ollama,
llama.cpp server, text-generation-inference...) for the ones you don't.

The surface is the CompletionsService/EmbeddingsService SPI; streaming uses
the `/chat/completions` SSE protocol (`data: {...}` lines, `data: [DONE]`
terminator) and feeds the same StreamingChunksConsumer contract the TPU
provider does, so `ai-chat-completions`'s stream-to-topic path works
unchanged against either provider.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Optional

from langstream_tpu.ai.provider import (
    ChatChunk,
    ChatCompletionsResult,
    ChatMessage,
    CompletionsService,
    EmbeddingsService,
    ServiceProvider,
    StreamingChunksConsumer,
)


class OpenAICompatCompletions(CompletionsService):
    def __init__(self, provider: "OpenAICompatProvider", config: dict[str, Any]) -> None:
        self.provider = provider
        self.config = config

    async def get_chat_completions(
        self,
        messages: list[ChatMessage],
        options: dict[str, Any],
        chunks_consumer: Optional[StreamingChunksConsumer] = None,
    ) -> ChatCompletionsResult:
        body: dict[str, Any] = {
            "model": options.get("model") or self.provider.model,
            "messages": [{"role": m.role, "content": m.content} for m in messages],
        }
        for key, wire_key in (
            ("max-tokens", "max_tokens"),
            ("max-new-tokens", "max_tokens"),
            ("temperature", "temperature"),
            ("top-p", "top_p"),
            ("stop", "stop"),
        ):
            if options.get(key) is not None:
                body[wire_key] = options[key]
        start = time.monotonic()
        if chunks_consumer is not None:
            body["stream"] = True
            return await self._stream(body, chunks_consumer, start)
        status, payload = await self.provider.post("/chat/completions", body)
        if status != 200:
            raise RuntimeError(
                f"chat completions failed ({status}): {payload[:300]!r}"
            )
        data = json.loads(payload)
        choice = data["choices"][0]
        usage = data.get("usage", {})
        total_ms = (time.monotonic() - start) * 1e3
        return ChatCompletionsResult(
            content=choice["message"].get("content") or "",
            role=choice["message"].get("role", "assistant"),
            finish_reason=choice.get("finish_reason") or "stop",
            prompt_tokens=int(usage.get("prompt_tokens", 0)),
            completion_tokens=int(usage.get("completion_tokens", 0)),
            ttft_ms=total_ms,
            total_ms=total_ms,
        )

    async def _stream(
        self, body: dict, chunks_consumer: StreamingChunksConsumer, start: float
    ) -> ChatCompletionsResult:
        answer_id = uuid.uuid4().hex
        parts: list[str] = []
        finish_reason = "stop"
        ttft_ms = 0.0
        index = 0
        async for event in self.provider.post_sse("/chat/completions", body):
            if event == "[DONE]":
                break
            data = json.loads(event)
            choice = (data.get("choices") or [{}])[0]
            delta = choice.get("delta", {})
            content = delta.get("content") or ""
            if choice.get("finish_reason"):
                finish_reason = choice["finish_reason"]
            if not content:
                continue
            if not parts:
                ttft_ms = (time.monotonic() - start) * 1e3
            parts.append(content)
            chunks_consumer(
                ChatChunk(content=content, index=index, last=False, answer_id=answer_id)
            )
            index += 1
        chunks_consumer(
            ChatChunk(content="", index=index, last=True, answer_id=answer_id)
        )
        total_ms = (time.monotonic() - start) * 1e3
        return ChatCompletionsResult(
            content="".join(parts),
            finish_reason=finish_reason,
            completion_tokens=index,
            ttft_ms=ttft_ms,
            total_ms=total_ms,
        )


class OpenAICompatEmbeddings(EmbeddingsService):
    def __init__(self, provider: "OpenAICompatProvider", config: dict[str, Any]) -> None:
        self.provider = provider
        self.config = config

    async def compute_embeddings(self, texts: list[str]) -> list[list[float]]:
        body = {
            "model": self.config.get("model") or self.provider.embeddings_model,
            "input": texts,
        }
        status, payload = await self.provider.post("/embeddings", body)
        if status != 200:
            raise RuntimeError(f"embeddings failed ({status}): {payload[:300]!r}")
        data = json.loads(payload)
        rows = sorted(data["data"], key=lambda d: d.get("index", 0))
        return [list(map(float, row["embedding"])) for row in rows]


class OpenAICompatProvider(ServiceProvider):
    """`open-ai-configuration` resource → OpenAI-compatible HTTP backend.

    config keys: ``url`` (base, e.g. http://host:8000/v1), ``access-key``
    (bearer token, optional), ``model`` / ``embeddings-model`` defaults."""

    def __init__(self, config: dict[str, Any]) -> None:
        self.url = str(config.get("url", "https://api.openai.com/v1")).rstrip("/")
        self.access_key = config.get("access-key") or config.get("api-key") or ""
        self.model = config.get("model", "")
        self.embeddings_model = config.get("embeddings-model", self.model)
        self._session: Any = None

    def _headers(self) -> dict[str, str]:
        headers = {"Content-Type": "application/json"}
        if self.access_key:
            headers["Authorization"] = f"Bearer {self.access_key}"
        return headers

    async def session(self):
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def post(self, path: str, body: dict) -> tuple[int, bytes]:
        session = await self.session()
        async with session.post(
            f"{self.url}{path}", json=body, headers=self._headers()
        ) as resp:
            return resp.status, await resp.read()

    async def post_sse(self, path: str, body: dict):
        """POST and yield SSE `data:` payload strings."""
        session = await self.session()
        async with session.post(
            f"{self.url}{path}", json=body, headers=self._headers()
        ) as resp:
            if resp.status != 200:
                raise RuntimeError(
                    f"streaming request failed ({resp.status}): "
                    f"{(await resp.read())[:300]!r}"
                )
            buffer = b""
            async for chunk in resp.content.iter_any():
                buffer += chunk
                while b"\n" in buffer:
                    line, buffer = buffer.split(b"\n", 1)
                    line = line.strip()
                    if line.startswith(b"data:"):
                        payload = line[len(b"data:"):].strip()
                        if payload:
                            yield payload.decode("utf-8", "replace")

    def get_completions_service(self, config: dict[str, Any]) -> CompletionsService:
        return OpenAICompatCompletions(self, config)

    def get_embeddings_service(self, config: dict[str, Any]) -> EmbeddingsService:
        return OpenAICompatEmbeddings(self, config)

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


def register() -> None:
    from langstream_tpu.api.doc import ConfigModel
    from langstream_tpu.core.registry import REGISTRY, ResourceTypeInfo

    for type_ in ("open-ai-configuration", "openai-compatible"):
        REGISTRY.register_resource(
            ResourceTypeInfo(
                type=type_,
                description=(
                    "Remote OpenAI-compatible completions/embeddings endpoint "
                    "(OpenAI, vLLM, Ollama, TGI...)."
                ),
                config_model=ConfigModel(type=type_, allow_unknown=True),
                factory=OpenAICompatProvider,
            )
        )
