"""JWT verification shared by the gateway and the control plane.

Parity: reference ``langstream-auth-jwt`` (AuthenticationProviderToken +
JwksUriSigningKeyResolver.java) — HS256 via a shared secret, RS256 via a
configured PEM public key, or RS256 via a JWKS endpoint resolved by ``kid``
with caching. RSA signature verification uses the installed ``cryptography``
package.

Configuration keys (all providers pick the first that applies):
  secret-key        HS256 shared secret
  public-key        RS256 PEM public key (inline, ``-----BEGIN ...``)
  jwks-uri          RS256 JWKS endpoint; keys cached, refreshed on unknown kid
  audience / issuer optional claim checks (audience accepts list claims)
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Any, Optional


class JwtError(ValueError):
    pass


def _b64d(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def decode_unverified(token: str) -> tuple[dict, dict, bytes, bytes]:
    """(header, payload, signature, signed_bytes) — no verification."""
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
        header = json.loads(_b64d(header_b64))
        payload = json.loads(_b64d(payload_b64))
        signature = _b64d(sig_b64)
    except Exception as e:  # noqa: BLE001 — any malformation is the same error
        raise JwtError(f"malformed JWT: {e}") from e
    return header, payload, signature, f"{header_b64}.{payload_b64}".encode()


def _rsa_key_from_jwk(jwk: dict):
    from cryptography.hazmat.primitives.asymmetric.rsa import RSAPublicNumbers

    n = int.from_bytes(_b64d(jwk["n"]), "big")
    e = int.from_bytes(_b64d(jwk["e"]), "big")
    return RSAPublicNumbers(e, n).public_key()


def _rsa_key_from_pem(pem: str):
    from cryptography.hazmat.primitives.asymmetric.rsa import RSAPublicKey
    from cryptography.hazmat.primitives.serialization import load_pem_public_key

    key = load_pem_public_key(pem.encode())
    if not isinstance(key, RSAPublicKey):
        # fail fast at CONFIG time: an EC/Ed25519 key would otherwise raise
        # TypeError on every RS256 verify call
        raise ValueError(
            f"public-key must be an RSA public key, got {type(key).__name__}"
        )
    return key


def _verify_rs256(key, signature: bytes, signed: bytes) -> bool:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric import padding
    from cryptography.hazmat.primitives.hashes import SHA256

    try:
        key.verify(signature, signed, padding.PKCS1v15(), SHA256())
        return True
    except InvalidSignature:
        return False


class JwtVerifier:
    """Verifies bearer JWTs per the configuration (see module docstring)."""

    def __init__(self, configuration: dict[str, Any]) -> None:
        self._secret: Optional[str] = configuration.get("secret-key")
        self._public_key_pem: Optional[str] = configuration.get("public-key")
        self._jwks_uri: Optional[str] = configuration.get("jwks-uri")
        self._audience = configuration.get("audience")
        self._issuer = configuration.get("issuer")
        if not (self._secret or self._public_key_pem or self._jwks_uri):
            raise ValueError(
                "jwt verification requires one of secret-key / public-key / jwks-uri"
            )
        self._pem_key = (
            _rsa_key_from_pem(self._public_key_pem) if self._public_key_pem else None
        )
        self._jwks_keys: dict[str, Any] = {}  # kid → rsa public key

    async def _resolve_jwks_key(self, kid: Optional[str]):
        """kid → key, fetching/refreshing the JWKS on a miss
        (JwksUriSigningKeyResolver semantics)."""
        if kid in self._jwks_keys:
            return self._jwks_keys[kid]
        import asyncio

        import aiohttp

        assert self._jwks_uri is not None
        try:
            timeout = aiohttp.ClientTimeout(total=10)
            async with aiohttp.ClientSession(timeout=timeout) as session:
                async with session.get(self._jwks_uri) as resp:
                    if resp.status != 200:
                        raise JwtError(f"jwks fetch failed: HTTP {resp.status}")
                    doc = await resp.json(content_type=None)
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            # network faults must fail AUTH, not escape as raw exceptions
            raise JwtError(f"jwks fetch failed: {e}") from e
        for jwk in doc.get("keys", []):
            if jwk.get("kty") == "RSA":
                self._jwks_keys[jwk.get("kid")] = _rsa_key_from_jwk(jwk)
        if kid not in self._jwks_keys:
            if kid is None and len(self._jwks_keys) == 1:
                # kid-less issuer with a single key: cache under None so the
                # hot path stops refetching the document per verification
                self._jwks_keys[None] = next(iter(self._jwks_keys.values()))
                return self._jwks_keys[None]
            raise JwtError(f"no JWKS key for kid {kid!r}")
        return self._jwks_keys[kid]

    async def verify(self, token: str) -> dict[str, Any]:
        """Returns the validated claims; raises JwtError otherwise."""
        header, payload, signature, signed = decode_unverified(token)
        alg = header.get("alg")
        if alg == "HS256":
            if not self._secret:
                raise JwtError("HS256 token but no secret-key configured")
            expected = hmac.new(self._secret.encode(), signed, hashlib.sha256).digest()
            if not hmac.compare_digest(signature, expected):
                raise JwtError("bad signature")
        elif alg == "RS256":
            if self._pem_key is not None:
                key = self._pem_key
            elif self._jwks_uri:
                key = await self._resolve_jwks_key(header.get("kid"))
            else:
                raise JwtError("RS256 token but no public-key / jwks-uri configured")
            if not _verify_rs256(key, signature, signed):
                raise JwtError("bad signature")
        else:
            raise JwtError(f"unsupported alg {alg!r}")

        now = time.time()

        def numeric(claim: str) -> Optional[float]:
            if claim not in payload:
                return None
            try:
                return float(payload[claim])
            except (TypeError, ValueError) as e:
                raise JwtError(f"non-numeric {claim} claim") from e

        exp, nbf = numeric("exp"), numeric("nbf")
        if exp is not None and now > exp:
            raise JwtError("token expired")
        if nbf is not None and now < nbf:
            raise JwtError("token not yet valid")
        if self._audience is not None:
            aud = payload.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            accepted = (
                self._audience
                if isinstance(self._audience, list)
                else [self._audience]
            )
            # accept on any intersection (mirrors the issuer-list handling);
            # equality — not set() — so a malformed unhashable aud entry
            # still yields a clean JwtError → 401, not a TypeError
            if not any(a in auds for a in accepted):
                raise JwtError("bad audience")
        if self._issuer is not None:
            issuers = (
                self._issuer if isinstance(self._issuer, list) else [self._issuer]
            )
            if payload.get("iss") not in issuers:
                raise JwtError("bad issuer")
        return payload


def claims_to_principal(payload: dict[str, Any]) -> dict[str, str]:
    """Flatten string-ish claims into principal values for header mappings
    and consume filters (value-from-authentication)."""
    values = {
        k: str(v) for k, v in payload.items() if isinstance(v, (str, int, float))
    }
    if "sub" in payload:
        values.setdefault("subject", str(payload["sub"]))
    return values
