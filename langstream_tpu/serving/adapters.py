"""Multi-LoRA adapter registry: many logical models, one hot engine.

ROADMAP item 4's serving half (grounded in PAPERS.md "DeepServe" — the
multi-tenant win is multiplexing N logical models onto ONE engine — and
"Software-Defined Agentic Serving" — the adapter is a per-request POLICY
input, not a deployment): the engine keeps a FIXED-shape device pool of
stacked low-rank factors, and every decode/verify/prefill dispatch gathers
each slot's factors by row (`models/transformer.py _lora_delta`), so a
mixed batch of base + N adapters is still ONE compiled program.

This module is the HOST half:

- ``AdapterSpec``: one logical adapter — name, rank, scale (alpha/rank),
  and where its factors come from (a HF/peft safetensors dir via
  ``models/loader.load_lora_params``, or a seed for random init in tests
  and benches).
- ``AdapterRegistry``: the device pool (row 0 = the all-zero BASE row the
  public adapter id ``-1`` maps to; rows 1..R-1 hot-swapped) plus the
  host bookkeeping that makes residency a CACHE, not a deployment:
  refcounted rows (a row serving an active slot is pinned), LRU eviction
  under pool pressure, and a jitted traced-row upload program so a swap is
  ONE device dispatch that never recompiles (`adapter-load` is warmed with
  an out-of-bounds row at engine startup, like every other program).

Registration is a control-plane operation: ``register()`` loads/initializes
the factors host-side (no device work), ``acquire()`` makes them resident
on first use — so registering 100 adapters against an 8-row pool is legal,
and the pool behaves like the prefix cache does for KV: hot tenants stay,
cold tenants swap in on demand (``swaps_total`` is the gauge to watch).

Adapters smaller than the pool rank are zero-padded (zero columns
contribute exactly nothing to ``(x @ A) @ B``); adapters LARGER than the
pool rank are rejected at registration with the sizing arithmetic.
MoE configs carry attention-only adapters (expert FFN tensors are sharded
over "expert" and a per-slot gathered expert-FFN delta has no cheap
formulation); dense configs adapt all seven projections.
"""

from __future__ import annotations

import functools
import logging
import math
import threading
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from langstream_tpu.models.configs import ModelConfig

log = logging.getLogger(__name__)

# public id -1 (base / no adapter) maps to device pool row 0, the all-zero
# row — the zero/base row contract models/transformer.py documents
BASE_ROW = 0


class AdapterPoolExhausted(RuntimeError):
    """Every pool row is pinned by an active request — a transient
    saturation: the engine sheds the admission with a retry-after
    (ShedError → HTTP 429), it never corrupts a resident tenant."""


def _proj_dims(config: ModelConfig) -> dict[str, tuple[int, int]]:
    """(din, dout) per adapted projection. MoE: attention-only."""
    d, hd = config.d_model, config.resolved_head_dim
    h, hkv, f = config.n_heads, config.n_kv_heads, config.d_ff
    dims = {
        "wq": (d, h * hd),
        "wk": (d, hkv * hd),
        "wv": (d, hkv * hd),
        "wo": (h * hd, d),
    }
    if not config.is_moe:
        dims.update({
            "w_gate": (d, f),
            "w_up": (d, f),
            "w_down": (f, d),
        })
    return dims


def make_lora_pool(
    config: ModelConfig, rows: int, rank: int, dtype: Optional[Any] = None
) -> dict:
    """The device-resident stacked adapter pool: per projection
    ``{"a": [L, rows, din, rank], "b": [L, rows, r, dout]}`` plus
    ``"scale": [rows]`` — all zeros, so every row starts as the base row
    until a swap loads it."""
    dtype = dtype or jnp.dtype(config.dtype)
    L = config.n_layers
    pool: dict[str, Any] = {}
    for proj, (din, dout) in _proj_dims(config).items():
        pool[proj] = {
            "a": jnp.zeros((L, rows, din, rank), dtype),
            "b": jnp.zeros((L, rows, rank, dout), dtype),
        }
    pool["scale"] = jnp.zeros((rows,), jnp.float32)
    return pool


def lora_pool_bytes(
    config: ModelConfig, rows: int, rank: int, dtype: Optional[Any] = None
) -> int:
    """Plan-term arithmetic WITHOUT allocating (serving/memory.py)."""
    if rows <= 0 or rank <= 0:
        return 0
    itemsize = jnp.dtype(dtype or config.dtype).itemsize
    per_row = sum(
        (din + dout) * rank * config.n_layers * itemsize
        for din, dout in _proj_dims(config).values()
    )
    return rows * per_row + rows * 4  # + the fp32 scale vector


def rows_for_fraction(
    config: ModelConfig,
    rank: int,
    weights_bytes: int,
    fraction: float,
    n_registered: int = 0,
) -> int:
    """Pool rows from the ``adapter-pool-fraction`` HBM budget: enough rows
    that ``rows × bytes_per_row ≤ fraction × weights_bytes``, floored at
    2 (the base row + one live adapter — a 1-row pool could never serve an
    adapter at all) and capped at 65 (64 tenants + base; past that the
    gather index cost stops being noise). ``n_registered`` floors the
    result so a config that LISTS more adapters than the fraction affords
    still gets one row each — the operator asked for them by name, and the
    plan term makes the cost visible."""
    per_row = lora_pool_bytes(config, 1, rank)
    if per_row <= 0:
        return 0
    by_budget = int(max(0.0, fraction) * weights_bytes // per_row)
    return max(2, min(65, max(by_budget, n_registered + 1)))


def init_random_lora(
    config: ModelConfig, rank: int, seed: int
) -> dict[str, dict[str, np.ndarray]]:
    """Random adapter factors (tests, benches, `weights: random` parity).
    Standard LoRA init puts zeros in B so the delta starts at zero — here
    BOTH factors are random: a test adapter must CHANGE the output, or
    token-exactness tests would pass vacuously."""
    rng = np.random.default_rng(seed)
    L = config.n_layers
    out: dict[str, dict[str, np.ndarray]] = {}
    for proj, (din, dout) in _proj_dims(config).items():
        out[proj] = {
            "a": (rng.standard_normal((L, din, rank)) / math.sqrt(din)).astype(
                np.float32
            ),
            "b": (rng.standard_normal((L, rank, dout)) / math.sqrt(rank)).astype(
                np.float32
            ),
        }
    return out


@dataclass
class AdapterSpec:
    """One logical adapter, as configured (`adapters:` on tpu-serving)."""

    name: str
    rank: int = 8
    # the LoRA scaling alpha/rank; peft checkpoints carry alpha in their
    # config — here the resolved multiplier is configured directly
    scale: float = 1.0
    path: Optional[str] = None  # HF/peft safetensors dir (models/loader)
    seed: Optional[int] = None  # random init fallback (tests/benches)

    @staticmethod
    def from_dict(d: dict) -> "AdapterSpec":
        return AdapterSpec(
            name=str(d["name"]),
            rank=int(d.get("rank", 8)),
            scale=float(d.get("scale", 1.0)),
            path=d.get("path"),
            seed=int(d["seed"]) if d.get("seed") is not None else None,
        )


@dataclass
class _AdapterState:
    spec: AdapterSpec
    host: dict  # per-proj {"a": [L, din, r], "b": [L, r, dout]} numpy
    row: Optional[int] = None  # device pool row when resident
    refs: int = 0  # active slots decoding with this adapter
    last_used: int = 0
    loads: int = 0  # times swapped onto the device


@functools.partial(jax.jit, donate_argnames=("pool",))
def _load_row(pool, row, host_tree, scale):
    """Upload one adapter's factors into pool row ``row`` — traced index,
    so every swap is the SAME compiled program; an out-of-bounds row drops
    every write (the warmup dispatch)."""

    def put(p, h):
        # p: [L, rows, ...], h: [L, ...] — row axis is 1
        return p.at[:, row].set(h.astype(p.dtype), mode="drop")

    out = {
        k: jax.tree.map(put, pool[k], host_tree[k])
        for k in host_tree
    }
    out["scale"] = pool["scale"].at[row].set(scale, mode="drop")
    for k in pool:
        if k not in out:
            out[k] = pool[k]
    return out


class AdapterRegistry:
    """Host bookkeeping + device pool for hot-swappable LoRA adapters.

    All mutating methods run on the engine thread (acquire/release ride
    admissions and completions); ``advertised()`` and ``stats()`` are read
    from beacon/metrics threads, hence the one lock around the advertised
    snapshot — the same crossing-threads pattern as PrefixPageIndex."""

    # lock discipline registry (analysis pass `locks`): only the
    # advertised-names snapshot crosses threads (beacon/metrics readers).
    _GUARDED = {"_ad_lock": ("_advertised",)}

    def __init__(
        self,
        config: ModelConfig,
        rows: int,
        rank: int,
        dtype: Optional[Any] = None,
    ) -> None:
        if rows < 2 or rank < 1:
            raise ValueError(
                f"adapter pool needs >= 2 rows (base + 1) and rank >= 1; "
                f"got rows={rows} rank={rank}"
            )
        self.config = config
        self.rows = int(rows)
        self.rank = int(rank)
        self.pool = make_lora_pool(config, self.rows, self.rank, dtype)
        self.pool_bytes = lora_pool_bytes(config, self.rows, self.rank, dtype)
        self._by_name: dict[str, _AdapterState] = {}
        self._row_owner: dict[int, _AdapterState] = {}
        self._free_rows = list(range(self.rows - 1, BASE_ROW, -1))
        self._tick = 0
        self._ad_lock = threading.Lock()
        self._advertised: tuple[str, ...] = ()
        # cumulative stats (gauges)
        self.swaps_total = 0
        self.registered_total = 0
        # callback the engine installs so row uploads are counted in its
        # compiled-program set (the flat-programs guarantee has no blind
        # spots) — None outside an engine (unit tests)
        self.on_load_program: Optional[Any] = None

    # -- registration (control plane) ----------------------------------------

    def register(self, spec: AdapterSpec | dict) -> None:
        """Load/init the adapter host-side and make it ACQUIRABLE. No
        device work — residency happens at first acquire. Re-registering a
        name replaces its factors (the next acquire re-uploads)."""
        if isinstance(spec, dict):
            spec = AdapterSpec.from_dict(spec)
        if spec.rank > self.rank:
            raise ValueError(
                f"adapter {spec.name!r} rank {spec.rank} exceeds the pool "
                f"rank {self.rank}; raise the pool rank (all adapters share "
                "one padded rank — the pool shape is the compile surface)"
            )
        if spec.path:
            from langstream_tpu.models.loader import load_lora_params

            host = load_lora_params(spec.path, self.config, spec.rank)
        else:
            host = init_random_lora(
                self.config, spec.rank, spec.seed if spec.seed is not None else 0
            )
        host = self._pad_rank(host, spec.rank)
        old = self._by_name.get(spec.name)
        if old is not None and old.row is not None:
            # replaced while resident: drop the stale row (refs guard —
            # replacing a PINNED adapter waits for its requests to finish)
            if old.refs > 0:
                raise ValueError(
                    f"adapter {spec.name!r} is serving {old.refs} active "
                    "request(s); drain before replacing its weights"
                )
            self._evict_state(old)
        self._by_name[spec.name] = _AdapterState(spec=spec, host=host)
        self.registered_total += 1

    def unregister(self, name: str) -> None:
        state = self._by_name.get(name)
        if state is None:
            return
        if state.refs > 0:
            raise ValueError(
                f"adapter {name!r} is serving {state.refs} active request(s)"
            )
        if state.row is not None:
            self._evict_state(state)
        del self._by_name[name]

    def _pad_rank(self, host: dict, rank: int) -> dict:
        if rank == self.rank:
            return host
        pad = self.rank - rank
        out = {}
        for proj, ab in host.items():
            out[proj] = {
                "a": np.pad(ab["a"], ((0, 0), (0, 0), (0, pad))),
                "b": np.pad(ab["b"], ((0, 0), (0, pad), (0, 0))),
            }
        return out

    # -- residency (data plane) ----------------------------------------------

    def names(self) -> list[str]:
        return sorted(self._by_name)

    def acquire(self, name: str) -> int:
        """Resolve an adapter name to its device pool row, swapping it in
        (LRU eviction of an unpinned row) when not resident. Refcounts the
        row; the caller MUST release() once the request finishes. Raises
        KeyError (unknown name — fail the request loudly) or
        AdapterPoolExhausted (every row pinned — shed with retry-after)."""
        state = self._by_name.get(name)
        if state is None:
            raise KeyError(
                f"unknown adapter {name!r}; registered: {self.names()}"
            )
        self._tick += 1
        state.last_used = self._tick
        if state.row is None:
            self._swap_in(state)
        state.refs += 1
        return state.row

    def release(self, name: str) -> None:
        state = self._by_name.get(name)
        if state is None:
            return  # unregistered while in flight — row already recycled
        assert state.refs > 0, name
        state.refs -= 1

    def _swap_in(self, state: _AdapterState) -> None:
        if not self._free_rows:
            victims = [
                s for s in self._row_owner.values() if s.refs == 0
            ]
            if not victims:
                raise AdapterPoolExhausted(
                    f"all {self.rows - 1} adapter rows are pinned by active "
                    "requests; raise adapter-pool-fraction or retry"
                )
            self._evict_state(min(victims, key=lambda s: s.last_used))
        row = self._free_rows.pop()
        if self.on_load_program is not None:
            self.on_load_program()
        host_dev = {
            proj: {k: jnp.asarray(v) for k, v in ab.items()}
            for proj, ab in state.host.items()
        }
        self.pool = _load_row(
            self.pool, jnp.asarray(row, jnp.int32), host_dev,
            jnp.float32(state.spec.scale),
        )
        state.row = row
        state.loads += 1
        self._row_owner[row] = state
        self.swaps_total += 1
        self._refresh_advertised()

    def _evict_state(self, state: _AdapterState) -> None:
        assert state.refs == 0
        row = state.row
        state.row = None
        if row is not None:
            self._row_owner.pop(row, None)
            self._free_rows.append(row)
        self._refresh_advertised()
        # the stale factors stay in the row until the next upload — rows
        # are only reachable through adapter_rows, and nothing maps to an
        # orphaned row, so no zeroing dispatch is needed (unlike KV pages,
        # which later admissions ALIAS)

    def warmup(self) -> None:
        """Compile the row-upload program with an out-of-bounds row (every
        write drops) so the first hot swap under traffic is never a
        mid-traffic XLA compile."""
        if self.on_load_program is not None:
            self.on_load_program()
        zero = init_random_lora(self.config, 1, 0)
        zero = self._pad_rank(
            {p: {"a": np.zeros_like(v["a"]), "b": np.zeros_like(v["b"])}
             for p, v in zero.items()},
            1,
        )
        host_dev = {
            proj: {k: jnp.asarray(v) for k, v in ab.items()}
            for proj, ab in zero.items()
        }
        self.pool = _load_row(
            self.pool, jnp.asarray(self.rows, jnp.int32), host_dev,
            jnp.float32(0.0),
        )
        jax.block_until_ready(self.pool["scale"])

    # -- observability --------------------------------------------------------

    def _refresh_advertised(self) -> None:
        resident = tuple(
            sorted(s.spec.name for s in self._row_owner.values())
        )
        with self._ad_lock:
            self._advertised = resident

    def advertised(self) -> tuple[str, ...]:
        """Resident adapter names — the fleet beacon's adapter-affinity
        payload (names, never weights; read from the HTTP thread)."""
        with self._ad_lock:
            return self._advertised

    @property
    def resident(self) -> int:
        return len(self._row_owner)

    def stats(self) -> dict[str, Any]:
        return {
            "registered": len(self._by_name),
            "resident": self.resident,
            "rows": self.rows - 1,  # usable rows (base row excluded)
            "rank": self.rank,
            "swaps-total": self.swaps_total,
            "pool-bytes": self.pool_bytes,
        }
