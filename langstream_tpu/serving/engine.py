"""Continuous-batching serving engine.

The device loop owns the TPU (SURVEY §3.2 note: "the continuous batcher owns
the device; the poll loop feeds it"): requests enter a thread-safe queue, the
engine thread admits them into free KV-cache slots (prefill, bucketed padding),
then every iteration runs ONE fused decode+sample step for ALL active slots.
Tokens stream back per-slot through callbacks; finished slots free immediately
and new requests take their place — no generation waits for the longest one.

Replaces the reference's OrderedAsyncBatchExecutor slot (SURVEY §2.1) as the
batching scheduler, and the remote-API call in ChatCompletionsStep (§3.3) as
the compute. Streaming callbacks preserve the StreamingChunksConsumer timing:
first token → first chunk, before the source record commits.
"""

from __future__ import annotations

import functools
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from langstream_tpu.models.configs import GenerationOptions, ModelConfig
from langstream_tpu.models.transformer import decode_step, make_kv_cache, prefill
from langstream_tpu.serving.sampling import sample

log = logging.getLogger(__name__)


@dataclass
class GenerationRequest:
    prompt_tokens: list[int]
    options: GenerationOptions
    # called from the engine thread with each new token id (stream path)
    on_token: Optional[Callable[[int], None]] = None
    submitted_at: float = field(default_factory=time.monotonic)
    _done: threading.Event = field(default_factory=threading.Event)
    _result: Optional["GenerationResult"] = None

    def result(self, timeout: Optional[float] = None) -> "GenerationResult":
        if not self._done.wait(timeout):
            raise TimeoutError("generation did not complete in time")
        assert self._result is not None
        if self._result.error is not None:
            raise self._result.error
        return self._result


@dataclass
class GenerationResult:
    tokens: list[int]
    finish_reason: str  # stop | length
    prompt_tokens: int
    ttft_s: float
    total_s: float
    error: Optional[BaseException] = None


@dataclass
class _Slot:
    request: Optional[GenerationRequest] = None
    position: int = 0  # next write position (= prompt len + generated so far)
    generated: list[int] = field(default_factory=list)
    started_at: float = 0.0
    first_token_at: float = 0.0

    @property
    def active(self) -> bool:
        return self.request is not None


@functools.partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def _decode_and_sample(params, tokens, positions, cache, key, temp, top_k, top_p, config):
    logits, cache = decode_step(params, tokens, positions, cache, config)
    key, sub = jax.random.split(key)
    next_tokens = sample(logits, sub, temp, top_k, top_p)
    return next_tokens, cache, key


@functools.partial(
    jax.jit, static_argnames=("config",), donate_argnames=("local_cache",)
)
def _prefill_and_sample(params, tokens, length, local_cache, key, temp, top_k, top_p, config):
    logits, local_cache = prefill(params, tokens, length, local_cache, config)
    key, sub = jax.random.split(key)
    first = sample(logits, sub, temp, top_k, top_p)
    return first, local_cache, key


def _make_insert():
    @functools.partial(jax.jit, donate_argnames=("cache",))
    def insert(cache, local_cache, slot):
        # local_cache leaves: [L, 1, W, Hkv, D] → write into cache[:, slot, :W]

        def put(big, small):
            return jax.lax.dynamic_update_slice(
                big, small.astype(big.dtype), (0, slot, 0, 0, 0)
            )

        return {
            "k": put(cache["k"], local_cache["k"]),
            "v": put(cache["v"], local_cache["v"]),
        }

    return insert


class ServingEngine:
    """One engine per model per agent replica; owns the device loop."""

    def __init__(
        self,
        config: ModelConfig,
        params: Any,
        max_batch: int = 8,
        max_seq_len: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        prefill_buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024, 2048),
        rng_seed: int = 0,
    ) -> None:
        self.config = config
        self.params = params
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len or config.max_seq_len
        self.eos_token_id = eos_token_id
        self.prefill_buckets = tuple(
            b for b in prefill_buckets if b <= self.max_seq_len
        ) or (self.max_seq_len,)
        self._queue: "queue.Queue[GenerationRequest]" = queue.Queue(maxsize=max_batch * 4)
        self._slots = [_Slot() for _ in range(max_batch)]
        self._cache = make_kv_cache(config, max_batch, self.max_seq_len)
        self._insert = _make_insert()
        self._key = jax.random.PRNGKey(rng_seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dead: Optional[BaseException] = None
        # device-side per-slot sampling params, rebuilt on admit
        self._temp = np.zeros(max_batch, np.float32)
        self._top_k = np.zeros(max_batch, np.int32)
        self._top_p = np.ones(max_batch, np.float32)
        # stats
        self.total_generated = 0
        self.total_requests = 0
        self._busy_steps = 0

    # -- public API ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._dead = None
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="serving-engine", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # resolve everything still in flight so blocked callers return now
        self._fail_all(RuntimeError("serving engine stopped"))

    def submit(self, request: GenerationRequest) -> GenerationRequest:
        """Thread-safe enqueue; blocks when the queue is full (backpressure
        toward the broker poll loop — SURVEY §7 hard parts)."""
        if self._dead is not None:
            raise RuntimeError("serving engine is stopped") from self._dead
        limit = min(self.max_seq_len - 1, self.prefill_buckets[-1])
        if len(request.prompt_tokens) > limit:
            raise ValueError(
                f"prompt of {len(request.prompt_tokens)} tokens exceeds the "
                f"engine limit of {limit} (largest prefill bucket / max_seq_len)"
            )
        self._queue.put(request)
        return request

    def generate(
        self,
        prompt_tokens: list[int],
        options: Optional[GenerationOptions] = None,
        on_token: Optional[Callable[[int], None]] = None,
        timeout: float = 300.0,
    ) -> GenerationResult:
        """Blocking convenience wrapper (submit + wait)."""
        req = GenerationRequest(
            prompt_tokens=list(prompt_tokens),
            options=options or GenerationOptions(),
            on_token=on_token,
        )
        self.submit(req)
        return req.result(timeout)

    def stats(self) -> dict[str, Any]:
        active = sum(1 for s in self._slots if s.active)
        return {
            "active-slots": active,
            "max-batch": self.max_batch,
            "queued": self._queue.qsize(),
            "total-requests": self.total_requests,
            "total-generated-tokens": self.total_generated,
            "busy-steps": self._busy_steps,
        }

    # -- engine thread ------------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                admitted = self._admit()
                if not any(s.active for s in self._slots):
                    if not admitted:
                        time.sleep(0.001)
                    continue
                self._decode_iteration()
        except BaseException as e:  # noqa: BLE001 — fail every pending request
            log.exception("serving engine loop crashed")
            self._fail_all(e)

    def _bucket(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    def _admit(self) -> bool:
        """Move queued requests into free slots (prefill path)."""
        admitted = False
        for idx, slot in enumerate(self._slots):
            if slot.active:
                continue
            try:
                request = self._queue.get_nowait()
            except queue.Empty:
                break
            try:
                self._prefill_into_slot(idx, request)
            except Exception as e:  # noqa: BLE001 — fail THIS request, not the engine
                log.exception("prefill failed for one request")
                request._result = GenerationResult(
                    tokens=[], finish_reason="error", prompt_tokens=0,
                    ttft_s=0, total_s=0, error=e,
                )
                request._done.set()
                continue
            admitted = True
        return admitted

    def _prefill_into_slot(self, idx: int, request: GenerationRequest) -> None:
        slot = self._slots[idx]
        prompt = request.prompt_tokens
        n = len(prompt)
        width = self._bucket(n)
        tokens = np.zeros((1, width), np.int32)
        tokens[0, :n] = prompt
        local_cache = make_kv_cache(self.config, 1, width)
        opts = request.options
        started = time.monotonic()
        first, local_cache, self._key = _prefill_and_sample(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray([n], jnp.int32),
            local_cache,
            self._key,
            jnp.asarray([opts.temperature], jnp.float32),
            jnp.asarray([opts.top_k], jnp.int32),
            jnp.asarray([opts.top_p], jnp.float32),
            self.config,
        )
        self._cache = self._insert(self._cache, local_cache, idx)
        first_token = int(jax.device_get(first)[0])

        slot.request = request
        slot.position = n  # first generated token goes to position n
        slot.generated = []
        slot.started_at = started
        slot.first_token_at = time.monotonic()
        self._temp[idx] = opts.temperature
        self._top_k[idx] = opts.top_k
        self._top_p[idx] = opts.top_p
        self.total_requests += 1
        self._deliver_token(idx, first_token)

    def _decode_iteration(self) -> None:
        """One decode step for every slot (inactive slots run masked junk —
        static shapes keep XLA happy; their outputs are ignored)."""
        tokens = np.zeros(self.max_batch, np.int32)
        positions = np.zeros(self.max_batch, np.int32)
        for i, slot in enumerate(self._slots):
            if slot.active:
                # current token = last delivered; it sits at position-1... the
                # NEXT token is produced by feeding the last token at `position`
                tokens[i] = slot.generated[-1]
                positions[i] = slot.position
        next_tokens, self._cache, self._key = _decode_and_sample(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            self._cache,
            self._key,
            jnp.asarray(self._temp),
            jnp.asarray(self._top_k),
            jnp.asarray(self._top_p),
            self.config,
        )
        host_tokens = np.asarray(jax.device_get(next_tokens))
        self._busy_steps += 1
        for i, slot in enumerate(self._slots):
            if slot.active:
                slot.position += 1
                self._deliver_token(i, int(host_tokens[i]))

    def _deliver_token(self, idx: int, token: int) -> None:
        slot = self._slots[idx]
        request = slot.request
        assert request is not None
        opts = request.options
        finished_reason = None

        is_stop = (self.eos_token_id is not None and token == self.eos_token_id) or (
            token in opts.stop_tokens
        )
        if is_stop:
            finished_reason = "stop"
        else:
            slot.generated.append(token)
            self.total_generated += 1
            if request.on_token is not None:
                try:
                    request.on_token(token)
                except Exception:  # noqa: BLE001 — stream consumer must not kill the loop
                    log.exception("on_token callback failed")
            if len(slot.generated) >= opts.max_new_tokens:
                finished_reason = "length"
            elif slot.position >= self.max_seq_len - 1:
                # cache full — scattering past the buffer would silently drop
                finished_reason = "length"

        if finished_reason is not None:
            now = time.monotonic()
            request._result = GenerationResult(
                tokens=list(slot.generated),
                finish_reason=finished_reason,
                prompt_tokens=len(request.prompt_tokens),
                ttft_s=slot.first_token_at - request.submitted_at,
                total_s=now - request.submitted_at,
            )
            request._done.set()
            slot.request = None
            slot.generated = []
            slot.position = 0

    def _fail_all(self, error: BaseException) -> None:
        self._dead = error
        for slot in self._slots:
            if slot.request is not None:
                slot.request._result = GenerationResult(
                    tokens=[], finish_reason="error", prompt_tokens=0,
                    ttft_s=0, total_s=0, error=error,
                )
                slot.request._done.set()
                slot.request = None
        while True:
            try:
                request = self._queue.get_nowait()
            except queue.Empty:
                break
            request._result = GenerationResult(
                tokens=[], finish_reason="error", prompt_tokens=0,
                ttft_s=0, total_s=0, error=error,
            )
            request._done.set()
