"""Continuous-batching serving engine.

The device loop owns the TPU (SURVEY §3.2 note: "the continuous batcher owns
the device; the poll loop feeds it"): requests enter a thread-safe queue, the
engine thread admits them into free KV-cache slots (prefill, bucketed padding),
then every iteration runs ONE fused decode+sample step for ALL active slots.
Tokens stream back per-slot through callbacks; finished slots free immediately
and new requests take their place — no generation waits for the longest one.

Replaces the reference's OrderedAsyncBatchExecutor slot (SURVEY §2.1) as the
batching scheduler, and the remote-API call in ChatCompletionsStep (§3.3) as
the compute. Streaming callbacks preserve the StreamingChunksConsumer timing:
first token → first chunk, before the source record commits.
"""

from __future__ import annotations

import functools
import logging
import math
import os
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from langstream_tpu.models.configs import GenerationOptions, ModelConfig
from langstream_tpu.models.transformer import (
    cache_width,
    decode_step_inplace,
    make_kv_cache,
    paged_decode_step_inplace,
    paged_insert_cache,
    paged_prefill_segment_inplace,
    paged_verify_step_inplace,
    prefill,
    prefill_segment,
    verify_step_inplace,
)
from langstream_tpu.parallel import spmd_serving as wire
from langstream_tpu.serving.faultinject import FaultInjector
from langstream_tpu.serving.observability import (
    EngineObservability,
    emit_request_spans,
    load_score,
)
from langstream_tpu.serving.sampling import sample, speculative_verify
from langstream_tpu.serving.speculation import NGramIndex
from langstream_tpu.serving.tenancy import (
    DEFAULT_TENANT,
    BrownoutController,
    TenantQueue,
    TenantRegistry,
    TenantShareExceeded,
    TenantSpec,
    effective_max_new_tokens,
)

log = logging.getLogger(__name__)


def enable_persistent_compile_cache(cache_dir: str) -> None:
    """Point JAX's persistent compilation cache at ``cache_dir`` (the
    ``compile-cache-dir`` resource knob): every XLA executable compiled by
    this process is serialized there, and a LATER process compiling the
    same program deserializes instead of recompiling. This is the fleet's
    fast-cold-start lever — a scale-up replica pointed at a warm cache dir
    (shared volume / persistent disk) skips the warmup ladder's compile
    wall and is serving in seconds (docs/SERVING.md §13).

    Thresholds are forced to cache-everything: the engine's small host-side
    helper programs (row resets, chain scatters) compile fast but there are
    MANY of them, and the default min-compile-time filter would skip
    exactly the long tail that makes a cold warmup slow. Idempotent; safe
    to call before any engine is built."""
    import jax
    from jax._src import compilation_cache as _cc

    current = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    if current != str(cache_dir):
        # the cache singleton latches its enabled/dir decision on first
        # use — reset so a dir configured AFTER jax already compiled
        # something (tests, multi-engine processes) still takes effect
        _cc.reset_cache()


class ShedError(RuntimeError):
    """Admission rejected by load shedding (full queue, hopeless deadline,
    or a draining engine). ``retry_after_s`` is the engine's estimate of
    when capacity frees — callers surface it as HTTP 429 Retry-After."""

    def __init__(self, reason: str, retry_after_s: float = 1.0) -> None:
        super().__init__(reason)
        self.retry_after_s = retry_after_s


class DeadlineExceededError(TimeoutError):
    """The request's deadline / max-queue-wait expired while it was still
    queued — nothing was generated, the caller should NOT retry blindly."""


class LogitsNaNError(RuntimeError):
    """The sampling NaN guard tripped for this request's slot: its logits
    went non-finite (poisoned KV row or device fault). The slot was
    quarantined and its KV rows zeroed; other slots were untouched."""


class EngineWedgedError(RuntimeError):
    """A per-iteration device wait exceeded the SPMD watchdog bound
    (``spmd-watchdog-s``): a dispatch hung past the deadline, which on a
    multi-host slice would otherwise hang every pod of the replica. Raised
    out of the iteration so the loop supervisor escalates to a coordinated
    OP_RECOVER instead of the slice wedging silently (docs/SERVING.md
    §20). A plain Exception: the recovery path IS the handler."""


@dataclass
class GenerationRequest:
    prompt_tokens: list[int]
    options: GenerationOptions
    # called from the engine thread with each new token id (stream path)
    on_token: Optional[Callable[[int], None]] = None
    # called from the engine thread once, with the final GenerationResult —
    # lets async callers await completion WITHOUT parking a thread on
    # result() (the executor-thread-per-request pattern capped agent
    # fan-out at the thread-pool size)
    on_done: Optional[Callable[["GenerationResult"], None]] = None
    submitted_at: float = field(default_factory=time.monotonic)
    # distributed-tracing correlation id (the gateway/agent ``ls-trace-id``
    # header): the engine's request-lifecycle spans join this trace, so a
    # chat request's gateway→agent→engine path stitches on /traces
    trace_id: Optional[str] = None
    _done: threading.Event = field(default_factory=threading.Event)
    _result: Optional["GenerationResult"] = None
    _cancelled: threading.Event = field(default_factory=threading.Event)
    # engine-installed teardown hook, run EXACTLY ONCE inside _finish
    # BEFORE the waiter wakes (adapter/grammar refcount release — the one
    # place every completion path, including queued deaths and crash
    # recovery, funnels through)
    _finalize: Optional[Callable[[], None]] = None
    # compiled grammar (serving/constrain.TokenDFA), attached at submit()
    # when options.response_format is set
    _dfa: Optional[Any] = None
    # the host-mirrored DFA state AFTER the latest delivered token —
    # written on the engine thread strictly BEFORE on_token fires, so a
    # callback reading it inside on_token sees the state matching that
    # token. This is what rides the fleet wire's tokens frames: a
    # survivor resumes a constrained stream mid-derivation from it
    # (options.grammar_resume_state) instead of refusing (§18)
    dfa_state: Optional[int] = None
    # adapter/grammar pool rows + initial DFA state once resolved at
    # admission (idempotence marker for the page-deferral retry path):
    # (adapter_row, grammar_row, dfa_state0)
    _agentic_rows: Optional[tuple[int, int, int]] = None

    def cancel(self) -> None:
        """Request cancellation from ANY thread. The engine honors it at
        the next chunk boundary: an active slot frees (partial tokens are
        returned with finish_reason="cancelled"), a queued request resolves
        when the admission sweep reaches it. Idempotent; a no-op once the
        request already finished."""
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def deadline_at(self) -> Optional[float]:
        """Absolute monotonic deadline, or None when the request has none."""
        if self.options.deadline_s is None:
            return None
        return self.submitted_at + self.options.deadline_s

    def result(self, timeout: Optional[float] = None) -> "GenerationResult":
        if not self._done.wait(timeout):
            raise TimeoutError("generation did not complete in time")
        assert self._result is not None
        if self._result.error is not None:
            raise self._result.error
        return self._result

    def _finish(self, result: "GenerationResult") -> None:
        if self._done.is_set():
            return  # first resolution wins (sweep vs admission pop races)
        if self._finalize is not None:
            fin, self._finalize = self._finalize, None
            try:
                fin()
            except Exception:  # noqa: BLE001 — teardown must not eat the result
                log.exception("request finalize hook failed")
        self._result = result
        self._done.set()
        if self.on_done is not None:
            try:
                self.on_done(result)
            except Exception:  # noqa: BLE001 — callback must not kill the loop
                log.exception("on_done callback failed")


@dataclass
class GenerationResult:
    tokens: list[int]
    # stop | length | cancelled | deadline | error — cancelled/deadline
    # carry the tokens generated so far (error is None: partial output is
    # valid for a stream the client walked away from or timed out)
    finish_reason: str
    prompt_tokens: int
    ttft_s: float
    total_s: float
    error: Optional[BaseException] = None


@dataclass
class _Slot:
    request: Optional[GenerationRequest] = None
    position: int = 0  # next write position (= prompt len + generated so far)
    generated: list[int] = field(default_factory=list)
    started_at: float = 0.0
    first_token_at: float = 0.0
    # observability (docs/SERVING.md §12): lifecycle-span attributes and
    # the inter-token histogram's per-slot clock — host bookkeeping only
    last_token_at: float = 0.0
    path: str = "cold"  # cold | warm | long | ring (admission route)
    prefill_chunks: int = 0
    decode_iters: int = 0
    verify_iters: int = 0

    @property
    def active(self) -> bool:
        return self.request is not None

    def reset_obs(self, path: str, chunks: int) -> None:
        self.last_token_at = 0.0
        self.path = path
        self.prefill_chunks = chunks
        self.decode_iters = 0
        self.verify_iters = 0


def _dfa_mask(dfa, g, state):
    """Per-slot grammar mask for ONE sampling step: the PACKED legality
    bitmask row gathered by (grammar row, current state) — [B, W] uint32,
    1 bit per token, expanded to bool inside sampling's mask fold
    (serving/constrain.py, sampling._expand_allowed). ``dfa`` is the
    registry's 4-plane pool (bits, defaults, exc_key, exc_next)."""
    return dfa[0][g, state]  # [B, ceil(V/32)] uint32


def _dfa_advance(dfa, g, tokens, state, vocab_size):
    """Advance each slot's DFA state past its sampled token ON DEVICE:
    the state's default successor unless the sorted per-row exceptions
    array holds the composite key ``state · V + token`` (a searchsorted
    probe — constrain.py packs every legal-but-non-modal transition
    there, so legal tokens advance EXACTLY as the dense table did). The
    NaN sentinel (-1) clamps to token 0; wherever that lands is harmless
    — the engine quarantines the slot on sight and re-seeds its state at
    the next admit, and free slots ride row 0 (defaults all 0, no
    exceptions: the unconstrained self-loop)."""
    _, defaults, exc_key, exc_next = dfa
    tclip = jnp.clip(tokens, 0, vocab_size - 1)
    # int32-safe: the registry enforces max_states · V < 2**31
    key = state * vocab_size + tclip  # [B]
    rows_k = exc_key[g]  # [B, E] sorted, sentinel-padded
    idx = jax.vmap(functools.partial(jnp.searchsorted, side="left"))(
        rows_k, key
    )
    idx = jnp.minimum(idx, rows_k.shape[-1] - 1)
    hit_key = jnp.take_along_axis(rows_k, idx[:, None], axis=1)[:, 0]
    hit_next = jnp.take_along_axis(exc_next[g], idx[:, None], axis=1)[:, 0]
    nxt = jnp.where(hit_key == key, hit_next, defaults[g, state])
    return jnp.maximum(nxt, 0).astype(state.dtype)


@functools.partial(
    jax.jit, static_argnames=("steps", "config", "kv_bound"), donate_argnames=("cache",)
)
def _decode_chunk(
    params, tokens, positions, cache, key, temp, top_k, top_p, steps, config,
    kv_bound=None, lora=None, arows=None, dfa=None, g=None, dstate=None,
):
    """``steps`` fused decode+sample iterations in ONE dispatch (lax.scan).

    Per-step host round trips are the latency killer (a dispatch+fetch costs
    hundreds of ms through a TPU tunnel vs ~tens of ms of decode compute);
    scanning K steps on-device amortizes that overhead K-fold, and the
    engine additionally pipelines: chunk k+1 is dispatched from chunk k's
    DEVICE outputs before chunk k's tokens are fetched to the host.

    The step body uses decode_step_inplace (layer scan carries the cache,
    updated by dynamic-update-slice) so the chunk never materializes a
    second cache-sized buffer — the xs/ys layer scan's stacked output was
    live across the whole chunk, OOMing llama-3-8b past B=48 and costing
    ~20% step time (measured r5: 39.1 → 31.3 ms/step at B=48).

    ``kv_bound`` (static pow2 ≥ max position + steps, from host positions):
    the chunk scans over a [.., :kv_bound]-sliced cache and splices it back
    after — ONE pair of bound-wide copies per chunk instead of per-step
    slicing (measured r5 llama-3-8b B=96: 51.8 ms/step sliced-per-step vs
    27.9 native-narrow; decode is HBM-bound, and weights + cold cache
    columns are most of the stream)."""

    full = None
    if kv_bound is not None and kv_bound < cache_width(cache):
        full = cache
        # axis 3 is T for both the value arrays and the int8 scale arrays
        cache = jax.tree.map(lambda a: a[:, :, :, :kv_bound], cache)

    def body(carry, _):
        tokens, positions, cache, key, dstate = carry
        logits, cache = decode_step_inplace(
            params, tokens, positions, cache, config,
            lora=lora, adapter_rows=arows,
        )
        key, sub = jax.random.split(key)
        if dfa is not None:
            # constrained decoding rides the FUSED chunk: mask this step's
            # logits with each slot's packed bitmask row, then advance the
            # state past the sampled token ON DEVICE (default-successor +
            # exceptions probe) — the host mirror replays the dense table
            # per delivered token, so a 16-step chunk stays one dispatch
            # with both sides in lockstep
            allowed = _dfa_mask(dfa, g, dstate)
            next_tokens = sample(logits, sub, temp, top_k, top_p, allowed)
            dstate = _dfa_advance(
                dfa, g, next_tokens, dstate, config.vocab_size
            )
        else:
            next_tokens = sample(logits, sub, temp, top_k, top_p)
        return (next_tokens, positions + 1, cache, key, dstate), next_tokens

    (tokens, positions, cache, key, dstate), chunk = lax.scan(
        body, (tokens, positions, cache, key, dstate), None, length=steps
    )
    if full is not None:
        cache = jax.tree.map(
            lambda big, small: lax.dynamic_update_slice(
                big, small.astype(big.dtype), (0,) * big.ndim
            ),
            full,
            cache,
        )
    return chunk, tokens, positions, cache, key, dstate


@functools.partial(
    jax.jit, static_argnames=("config", "kv_bound"), donate_argnames=("cache",)
)
def _verify_chunk(
    params, tokens, positions, cache, key, temp, top_k, top_p, drafts, config,
    kv_bound=None, lora=None, arows=None, dfa=None, g=None, vstates=None,
):
    """ONE self-speculative iteration in ONE dispatch: run the multi-token
    verify forward over [current token ++ drafts] (k+1 positions per slot),
    accept the longest valid draft prefix (greedy: argmax match; sampled:
    rejection sampling — serving/sampling.py speculative_verify), and
    advance the device decode chain by accepted+1. Decode is HBM-bound —
    every step reads the full weights to emit one token per slot — so
    scoring k+1 positions per weight read is the amortization lever after
    int8, overlap and prefix reuse (PERF.md round 9). Rejected tokens need
    no KV rewind: positions simply don't advance past the accepted length,
    and the next dispatch overwrites the stale rows before any causal mask
    can reach them.

    ``kv_bound``: the same static pow2 slice/splice the decode chunk uses —
    the verify read must not stream cold cache columns either. The fetched
    result is ONE packed [B, k+2] array (emitted tokens ++ accepted count),
    one tunnel round trip per iteration. Compile surface: one program per
    (k, kv_bound) with k fixed engine-wide, so the ladder stays O(log2 T)."""
    full = None
    if kv_bound is not None and kv_bound < cache_width(cache):
        full = cache
        cache = jax.tree.map(lambda a: a[:, :, :, :kv_bound], cache)
    inputs = jnp.concatenate([tokens[:, None], drafts], axis=1)  # [B, k+1]
    logits, cache = verify_step_inplace(
        params, inputs, positions, cache, config,
        lora=lora, adapter_rows=arows,
    )
    key, sub = jax.random.split(key)
    allowed = None
    if dfa is not None:
        # ``vstates`` [B, K+1]: the host-computed DFA state at every verify
        # position (state after consuming drafts 0..j-1 — the same mask
        # plain masked decode would apply, the exactness invariant under
        # constraints; serving/constrain.py verify_states)
        allowed = dfa[0][g[:, None], vstates]  # [B, K+1, W] packed uint32
    out, accept = speculative_verify(
        logits, drafts, sub, temp, top_k, top_p, allowed
    )
    # the last emitted token (correction or bonus) is the next chunk's input
    tokens = jnp.take_along_axis(out, accept[:, None], axis=1)[:, 0]
    positions = positions + accept + 1
    dstate = None
    if dfa is not None:
        # state after the LAST emitted token: gather the pre-state at the
        # accept position, advance past the emitted correction/bonus
        pre = jnp.take_along_axis(vstates, accept[:, None], axis=1)[:, 0]
        dstate = _dfa_advance(dfa, g, tokens, pre, config.vocab_size)
    if full is not None:
        cache = jax.tree.map(
            lambda big, small: lax.dynamic_update_slice(
                big, small.astype(big.dtype), (0,) * big.ndim
            ),
            full,
            cache,
        )
    packed = jnp.concatenate([out, accept[:, None]], axis=1)  # [B, k+2]
    return packed, tokens, positions, cache, key, dstate


@functools.partial(
    jax.jit,
    donate_argnames=(
        "tokens_dev", "positions_dev", "temp_dev", "top_k_dev", "top_p_dev"
    ),
)
def _chain_scatter(
    tokens_dev, positions_dev, temp_dev, top_k_dev, top_p_dev,
    idx, first, position, temperature, top_k, top_p,
):
    """All five decode-chain scatters for ONE slot in a single dispatch.
    ``idx`` is traced, so this is one compiled program for every slot (the
    previous five eager per-slot `.at[idx].set` ops each cost a tunnel
    round trip AND compiled per slot index); out-of-bounds ``idx`` drops
    every write, which is what the warmup dispatches."""
    return (
        tokens_dev.at[idx].set(first[0], mode="drop"),
        positions_dev.at[idx].set(position, mode="drop"),
        temp_dev.at[idx].set(temperature, mode="drop"),
        top_k_dev.at[idx].set(top_k, mode="drop"),
        top_p_dev.at[idx].set(top_p, mode="drop"),
    )


@functools.partial(jax.jit, donate_argnames=("cache",))
def _reset_rows(cache, slots):
    """Zero the KV cache rows of quarantined slots — ONE fixed-shape
    traced-index dispatch for any number of slots (``slots`` is a
    max_batch-wide buffer, out-of-bounds padding rows drop). A NaN-poisoned
    row must not survive slot reuse: admission only rewrites the prompt's
    columns, and a NaN in a later column would flow back through attention
    the moment a longer request decodes into it (NaN + the -inf mask is
    still NaN through softmax)."""

    def zero(a):
        return a.at[:, slots].set(jnp.zeros((), a.dtype), mode="drop")

    return jax.tree.map(zero, cache)


@functools.partial(
    jax.jit, static_argnames=("config", "kv_bound"), donate_argnames=("local_cache",)
)
def _prefill_segment_and_sample(
    params, tokens, offsets, seg_lengths, local_cache, key, temp, top_k, top_p,
    config, kv_bound, lora=None, arows=None, dfa=None, g=None,
    state_dev=None, state_slot=None, state0=None,
):
    """One chunked-prefill segment + a sample of its last-token logits.
    Sampling every segment (vs only the last) keeps the compiled-shape count
    at O(log2 segments) (the pow2 kv_bound); non-final samples are simply
    never fetched. With a grammar, the first generated token is masked by
    the request's INITIAL DFA state ``state0`` ([1] int32 — 0 for a fresh
    derivation, the carried state for a mid-derivation fleet resume, §18)
    and the advanced state scatters into ``state_dev`` at ``state_slot``
    (out-of-bounds on non-final segments — dropped), so the decode chain
    the engine dispatches NEXT iteration already carries the right state
    without a host round trip."""
    logits, local_cache = prefill_segment(
        params, tokens, offsets, seg_lengths, local_cache, config, kv_bound,
        lora=lora, adapter_rows=arows,
    )
    key, sub = jax.random.split(key)
    if dfa is not None:
        s0 = state0 if state0 is not None else jnp.zeros_like(g)
        first = sample(logits, sub, temp, top_k, top_p, _dfa_mask(dfa, g, s0))
        s1 = _dfa_advance(dfa, g, first, s0, config.vocab_size)
        state_dev = state_dev.at[state_slot].set(s1[0], mode="drop")
    else:
        first = sample(logits, sub, temp, top_k, top_p)
    return first, local_cache, key, state_dev


@functools.partial(
    jax.jit, static_argnames=("steps", "config", "page_size"),
    donate_argnames=("pool",),
)
def _paged_decode_chunk(
    params, tokens, positions, pool, table, key, temp, top_k, top_p, steps,
    config, page_size, lora=None, arows=None, dfa=None, g=None, dstate=None,
):
    """``steps`` fused decode+sample iterations against the PAGED pool in
    ONE dispatch — the paged twin of ``_decode_chunk`` with the kv_bound
    slice/splice dance deleted: each slot reads exactly its mapped pages,
    so this is ONE compiled program for every sequence-length mix (the
    (steps × pow2-bound) ladder collapses; ROADMAP item 1). Adapter rows
    and grammar rows are DATA ([B] int32 gathers), so base + N adapters +
    constrained slots mixed in one batch is STILL that one program — the
    ISSUE-10 acceptance invariant."""

    def body(carry, _):
        tokens, positions, pool, key, dstate = carry
        logits, pool = paged_decode_step_inplace(
            params, tokens, positions, pool, table, config, page_size,
            lora=lora, adapter_rows=arows,
        )
        key, sub = jax.random.split(key)
        if dfa is not None:
            allowed = _dfa_mask(dfa, g, dstate)
            next_tokens = sample(logits, sub, temp, top_k, top_p, allowed)
            dstate = _dfa_advance(
                dfa, g, next_tokens, dstate, config.vocab_size
            )
        else:
            next_tokens = sample(logits, sub, temp, top_k, top_p)
        return (next_tokens, positions + 1, pool, key, dstate), next_tokens

    (tokens, positions, pool, key, dstate), chunk = lax.scan(
        body, (tokens, positions, pool, key, dstate), None, length=steps
    )
    return chunk, tokens, positions, pool, key, dstate


@functools.partial(
    jax.jit, static_argnames=("config", "page_size"), donate_argnames=("pool",)
)
def _paged_verify_chunk(
    params, tokens, positions, pool, table, key, temp, top_k, top_p, drafts,
    config, page_size, lora=None, arows=None, dfa=None, g=None, vstates=None,
):
    """ONE self-speculative verify iteration against the paged pool — the
    paged twin of ``_verify_chunk``, and like the decode chunk a SINGLE
    compiled program (no bound ladder). Same no-rewind invariant: positions
    advance only past accepted tokens, stale draft page columns are
    overwritten before any causal mask can reach them. Draft positions are
    masked with the host-shipped per-position DFA states (``vstates``) so
    speculative verify stays token-exact under constraints."""
    inputs = jnp.concatenate([tokens[:, None], drafts], axis=1)  # [B, k+1]
    logits, pool = paged_verify_step_inplace(
        params, inputs, positions, pool, table, config, page_size,
        lora=lora, adapter_rows=arows,
    )
    key, sub = jax.random.split(key)
    allowed = None
    if dfa is not None:
        allowed = dfa[0][g[:, None], vstates]  # [B, K+1, W] packed uint32
    out, accept = speculative_verify(
        logits, drafts, sub, temp, top_k, top_p, allowed
    )
    tokens = jnp.take_along_axis(out, accept[:, None], axis=1)[:, 0]
    positions = positions + accept + 1
    dstate = None
    if dfa is not None:
        pre = jnp.take_along_axis(vstates, accept[:, None], axis=1)[:, 0]
        dstate = _dfa_advance(dfa, g, tokens, pre, config.vocab_size)
    packed = jnp.concatenate([out, accept[:, None]], axis=1)  # [B, k+2]
    return packed, tokens, positions, pool, key, dstate


@functools.partial(
    jax.jit, static_argnames=("config", "page_size"), donate_argnames=("pool",)
)
def _paged_segment_and_sample(
    params, tokens, offsets, seg_lengths, pool, table, key, temp, top_k, top_p,
    config, page_size, lora=None, arows=None, dfa=None, g=None,
    state_dev=None, state_slot=None, state0=None,
):
    """One chunked/suffix prefill segment straight into the slot's pages +
    a sample of its last-token logits. Replaces the dense path's local
    cache + final insert + (on warm admissions) the prefix gather: aliased
    prefix pages are already visible through the table, so a warm admission
    is ONE dispatch (plus at most one copy-on-write page copy). Grammar
    handling as in ``_prefill_segment_and_sample`` (``state0`` seeds the
    first-token mask — the mid-derivation resume hook)."""
    logits, pool = paged_prefill_segment_inplace(
        params, tokens, offsets, seg_lengths, pool, table, config, page_size,
        lora=lora, adapter_rows=arows,
    )
    key, sub = jax.random.split(key)
    if dfa is not None:
        s0 = state0 if state0 is not None else jnp.zeros_like(g)
        first = sample(logits, sub, temp, top_k, top_p, _dfa_mask(dfa, g, s0))
        s1 = _dfa_advance(dfa, g, first, s0, config.vocab_size)
        state_dev = state_dev.at[state_slot].set(s1[0], mode="drop")
    else:
        first = sample(logits, sub, temp, top_k, top_p)
    return first, pool, key, state_dev


@functools.partial(jax.jit, donate_argnames=("pool",))
def _page_copy(pool, src, dst):
    """Copy ONE physical page (all layers/heads) — the copy-on-write a
    prefix alias needs when the cached prefix ends mid-page. Traced
    indices: one compiled program; an out-of-bounds ``dst`` drops (warmup).
    Axis 1 is the page axis for both the value arrays and the int8 scale
    arrays (page-pool layout [L, P, Hkv, ps(, D)])."""

    def put(a):
        row = lax.dynamic_index_in_dim(a, src, 1, keepdims=False)
        return a.at[:, dst].set(row, mode="drop")

    return jax.tree.map(put, pool)


@functools.partial(jax.jit, donate_argnames=("pool",))
def _page_zero(pool, pages):
    """Zero physical pages (quarantine: a NaN-poisoned slot's pages must
    not re-enter the free list carrying garbage that a later partial-page
    publish could alias). ``pages`` is a fixed-width buffer padded with
    out-of-bounds entries (dropped) — one compiled program for any count."""

    def zero(a):
        return a.at[:, pages].set(jnp.zeros((), a.dtype), mode="drop")

    return jax.tree.map(zero, pool)


@jax.jit
def _page_snapshot(pool, src):
    """Slice ONE physical page (all layers/heads) out of the pool into
    fresh device buffers — the spill path's decoupling trick: the engine
    thread dispatches this (async, one traced-index program) and hands the
    RESULT arrays to the spill worker, so the worker's device→host copy
    can never race a later donating dispatch that rewrites (or a free that
    recycles) the page. NOT donated: the pool stays live."""

    def take(a):
        return lax.dynamic_index_in_dim(a, src, 1, keepdims=False)

    return jax.tree.map(take, pool)


@functools.partial(jax.jit, donate_argnames=("pool",))
def _page_restore(pool, block, dst):
    """Upload ONE host-arena page back into physical page ``dst`` — the
    hibernation restore. Traced index: ONE compiled program regardless of
    destination; an out-of-bounds ``dst`` drops (warmup). int8 pools
    upload int8 + scales — half the bytes of bf16, same as the pool."""

    def put(a, b):
        return a.at[:, dst].set(b.astype(a.dtype), mode="drop")

    return jax.tree.map(put, pool, block)


def _make_admit_group(mesh):
    """Factory for the FUSED admission step: local-cache zeros + prefill +
    first-token sample + big-cache insert + every decode-chain scatter in
    ONE dispatch. On a tunneled device each host→device op costs ~40-50ms
    of round-trip latency regardless of size, so the unfused path's ~14 ops
    (7 uploads + cache alloc + prefill + insert + 5 scatters) dominated
    burst TTFT (~780ms measured); fused + packed uploads ≈ 4 ops."""
    @functools.partial(
        jax.jit,
        static_argnames=("config",),
        donate_argnames=(
            "cache", "tokens_dev", "positions_dev", "temp_dev",
            "top_k_dev", "top_p_dev",
        ),
    )
    def admit_group(
        params, cache, tokens_dev, positions_dev, temp_dev, top_k_dev,
        top_p_dev, key, tokens, meta, slots, config,
        lora=None, arows=None, dfa=None, g_rows=None, state_dev=None,
        g_state0=None,
    ):
        # tokens [P, W] int32; meta [4, P] f32 = lengths/temps/top_ks/top_ps
        lengths = meta[0].astype(jnp.int32)
        temps = meta[1]
        top_ks = meta[2].astype(jnp.int32)
        top_ps = meta[3]
        n, width = tokens.shape
        local_cache = make_kv_cache(config, n, width)  # traced zeros: free
        if mesh is not None:
            from langstream_tpu.parallel.sharding import (
                constrain_serving_local_cache,
            )

            local_cache = constrain_serving_local_cache(
                local_cache, config.n_kv_heads, mesh
            )
        logits, local_cache = prefill(
            params, tokens, lengths, local_cache, config,
            lora=lora, adapter_rows=arows,
        )
        key, sub = jax.random.split(key)
        if dfa is not None:
            # constrained rows: first generated token masked by each row's
            # INITIAL DFA state (g_state0 — 0 for fresh derivations, the
            # carried state for a mid-derivation fleet resume, §18), the
            # advanced state scattered into the decode chain alongside the
            # token — the NEXT decode chunk (often dispatched before this
            # fetch even lands) reads a coherent state
            s0 = g_state0 if g_state0 is not None else jnp.zeros_like(g_rows)
            first = sample(
                logits, sub, temps, top_ks, top_ps, _dfa_mask(dfa, g_rows, s0)
            )
            s1 = _dfa_advance(dfa, g_rows, first, s0, config.vocab_size)
            state_dev = state_dev.at[slots].set(s1, mode="drop")
        else:
            first = sample(logits, sub, temps, top_ks, top_ps)

        def put(big, small):
            w = small.shape[3]
            return big.at[:, slots, :, :w].set(small.astype(big.dtype), mode="drop")

        cache = jax.tree.map(put, cache, local_cache)
        tokens_dev = tokens_dev.at[slots].set(first, mode="drop")
        positions_dev = positions_dev.at[slots].set(lengths, mode="drop")
        temp_dev = temp_dev.at[slots].set(temps, mode="drop")
        top_k_dev = top_k_dev.at[slots].set(top_ks, mode="drop")
        top_p_dev = top_p_dev.at[slots].set(top_ps, mode="drop")
        return (
            first, cache, tokens_dev, positions_dev, temp_dev, top_k_dev,
            top_p_dev, key, state_dev,
        )

    return admit_group


def _make_paged_admit_group(mesh=None):
    """Factory for the paged FUSED admission step: local-cache zeros +
    batched prefill + first-token sample + PAGE scatter + every decode-chain
    scatter in ONE dispatch. The prefill math is byte-identical to the dense
    admit group (same local-cache forward — the token-exactness invariant);
    only the insert differs: rows scatter into each slot's mapped pages
    instead of big-cache rows. Padding rows carry all-out-of-bounds tables,
    so their writes drop exactly like the dense path's OOB slots. Under a
    mesh the transient local cache is constrained like the dense admit
    group's, so the page scatter stays shard-local."""
    @functools.partial(
        jax.jit,
        static_argnames=("config", "page_size"),
        donate_argnames=(
            "pool", "tokens_dev", "positions_dev", "temp_dev",
            "top_k_dev", "top_p_dev",
        ),
    )
    def admit_group(
        params, pool, tokens_dev, positions_dev, temp_dev, top_k_dev,
        top_p_dev, key, tokens, meta, slots, tables, config, page_size,
        lora=None, arows=None, dfa=None, g_rows=None, state_dev=None,
        g_state0=None,
    ):
        # tokens [P, W] int32; meta [4, P] f32; tables [P, Tp] int32
        lengths = meta[0].astype(jnp.int32)
        temps = meta[1]
        top_ks = meta[2].astype(jnp.int32)
        top_ps = meta[3]
        n, width = tokens.shape
        local_cache = make_kv_cache(config, n, width)  # traced zeros: free
        if mesh is not None:
            from langstream_tpu.parallel.sharding import (
                constrain_serving_local_cache,
            )

            local_cache = constrain_serving_local_cache(
                local_cache, config.n_kv_heads, mesh
            )
        logits, local_cache = prefill(
            params, tokens, lengths, local_cache, config,
            lora=lora, adapter_rows=arows,
        )
        key, sub = jax.random.split(key)
        if dfa is not None:
            # initial state per row (g_state0): 0 fresh, carried on resume
            s0 = g_state0 if g_state0 is not None else jnp.zeros_like(g_rows)
            first = sample(
                logits, sub, temps, top_ks, top_ps, _dfa_mask(dfa, g_rows, s0)
            )
            s1 = _dfa_advance(dfa, g_rows, first, s0, config.vocab_size)
            state_dev = state_dev.at[slots].set(s1, mode="drop")
        else:
            first = sample(logits, sub, temps, top_ks, top_ps)
        pool = paged_insert_cache(pool, local_cache, tables, page_size)
        tokens_dev = tokens_dev.at[slots].set(first, mode="drop")
        positions_dev = positions_dev.at[slots].set(lengths, mode="drop")
        temp_dev = temp_dev.at[slots].set(temps, mode="drop")
        top_k_dev = top_k_dev.at[slots].set(top_ks, mode="drop")
        top_p_dev = top_p_dev.at[slots].set(top_ps, mode="drop")
        return (
            first, pool, tokens_dev, positions_dev, temp_dev, top_k_dev,
            top_p_dev, key, state_dev,
        )

    return admit_group


def _make_ring_admit(mesh):
    """Factory for the RING long-prompt admission: one dispatch runs the
    sequence-sharded ring prefill (parallel.sp.ring_prefill — prompt blocks
    spread over the mesh's "seq" axis, K/V rotating over ICI), quantizes the
    returned K/V if the cache is int8, splices it into the big cache, and
    samples the first token. The multi-chip counterpart of the single-chip
    chunked-prefill segment loop: S/W sequential segment dispatches become
    ONE compiled call whose attention memory stays O(S·S/n) per device."""
    @functools.partial(
        jax.jit,
        static_argnames=("config",),
        donate_argnames=(
            "cache", "tokens_dev", "positions_dev", "temp_dev",
            "top_k_dev", "top_p_dev",
        ),
    )
    def ring_admit(
        params, cache, tokens_dev, positions_dev, temp_dev, top_k_dev,
        top_p_dev, key, tokens, meta, slots, config,
    ):
        from langstream_tpu.models.transformer import _quantize_kv
        from langstream_tpu.parallel.sp import ring_prefill

        lengths = meta[0].astype(jnp.int32)
        temps = meta[1]
        top_ks = meta[2].astype(jnp.int32)
        top_ps = meta[3]
        logits, kv = ring_prefill(params, tokens, lengths, config, mesh)
        key, sub = jax.random.split(key)
        first = sample(logits, sub, temps, top_ks, top_ps)
        if isinstance(cache["k"], dict):  # int8 big cache
            kq, ks = _quantize_kv(kv["k"])
            vq, vs = _quantize_kv(kv["v"])
            local = {"k": {"q": kq, "s": ks}, "v": {"q": vq, "s": vs}}
        else:
            local = kv

        def put(big, small):
            w = small.shape[3]
            return big.at[:, slots, :, :w].set(small.astype(big.dtype), mode="drop")

        cache = jax.tree.map(put, cache, local)
        tokens_dev = tokens_dev.at[slots].set(first, mode="drop")
        positions_dev = positions_dev.at[slots].set(lengths, mode="drop")
        temp_dev = temp_dev.at[slots].set(temps, mode="drop")
        top_k_dev = top_k_dev.at[slots].set(top_ks, mode="drop")
        top_p_dev = top_p_dev.at[slots].set(top_ps, mode="drop")
        return first, cache, tokens_dev, positions_dev, temp_dev, top_k_dev, top_p_dev, key

    return ring_admit


def _kv_bound_ladder(max_seq_len: int) -> list[int]:
    """The pow2 kv_bound ladder: 64 doubling up to (and always including)
    ``max_seq_len``. The ONE definition of the ladder rule — the decode and
    verify warmups compile exactly these rungs and _decode_kv_bound picks
    from them at dispatch time, so any drift between the three sites would
    resurface the 15-23s mid-traffic compile stall the warmups exist to
    prevent."""
    bounds = []
    bound = 64
    while bound < max_seq_len:
        bounds.append(bound)
        bound *= 2
    bounds.append(max_seq_len)
    return list(dict.fromkeys(bounds))


class _Fetch:
    """Handle for one deferred device→host token fetch. Created at dispatch
    time; the fetch thread fills ``_value`` in submission order. ``result``
    falls back to an inline ``device_get`` when no fetch thread is running
    (tests drive the loop by hand; engine drain after stop)."""

    __slots__ = ("array", "_fetcher", "_event", "_value")

    def __init__(self, array, fetcher: "_TokenFetcher") -> None:
        self.array = array
        self._fetcher = fetcher
        self._event = threading.Event()
        self._value = None

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout_s: Optional[float] = None):
        """``timeout_s`` bounds the wait (the leader's per-iteration SPMD
        watchdog — docs/SERVING.md §20): expiry raises EngineWedgedError,
        which the loop supervisor escalates to a coordinated OP_RECOVER.
        None (single-host default) keeps the unbounded wait."""
        if not self._event.is_set() and not self._fetcher.alive():
            return np.asarray(jax.device_get(self.array))
        deadline = (
            time.monotonic() + timeout_s
            if timeout_s is not None and timeout_s > 0
            else None
        )
        poll = 0.5 if deadline is None else min(0.5, max(0.01, timeout_s / 8))
        while not self._event.wait(poll):
            if not self._fetcher.alive():
                # fetch thread went away before reaching this handle
                return np.asarray(jax.device_get(self.array))
            if deadline is not None and time.monotonic() > deadline:
                raise EngineWedgedError(
                    f"device fetch exceeded the {timeout_s:.1f}s dispatch "
                    "bound (spmd-watchdog-s); escalating to coordinated "
                    "recovery"
                )
        if isinstance(self._value, BaseException):
            raise self._value
        return self._value


class _TokenFetcher:
    """Dedicated device→host fetch thread (PERF.md "levers known but not
    taken"): the ~100ms per-chunk token fetch through a device tunnel was
    only hidden behind compute at chunk ≥ 32 — a fetch thread hides it at
    EVERY chunk size, because the engine thread dispatches the next chunk
    while this thread blocks on the previous one's bytes. One FIFO queue +
    one worker keeps results strictly in submission (= chunk) order."""

    def __init__(
        self,
        injector: Optional[FaultInjector] = None,
        obs: Optional[EngineObservability] = None,
    ) -> None:
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread: Optional[threading.Thread] = None
        self._injector = injector
        self._obs = obs

    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        if self.alive():
            return
        self._thread = threading.Thread(
            target=self._run, name="serving-fetch", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._queue.put(None)
            self._thread.join(timeout=30)
            self._thread = None

    def submit(self, array) -> _Fetch:
        handle = _Fetch(array, self)
        if self.alive():
            self._queue.put(handle)
        return handle

    def _run(self) -> None:
        while True:
            handle = self._queue.get()
            if handle is None:
                return
            try:
                if self._injector is not None:
                    self._injector.stall("fetch")
                t0 = time.monotonic()
                handle._value = np.asarray(jax.device_get(handle.array))
                if self._obs is not None and self._obs.on:
                    # the tunnel fetch IS a latency tail source (PERF.md
                    # round 7) — its distribution belongs on /metrics
                    self._obs.record("engine_fetch_s", time.monotonic() - t0)
            except BaseException as e:  # noqa: BLE001 — surface at result()
                handle._value = e
            handle._event.set()


class _Spill:
    """Handle for one in-flight entry spill (device pages → host arena).
    Created on the engine thread with the page SNAPSHOTS already
    dispatched (_page_snapshot — independent buffers, so the entry's
    device pages may be freed immediately after); the spill worker copies
    them into the arena slots and stamps checksums. ``cancelled`` is set
    by the engine (entry dropped/quarantined mid-spill) — the worker
    still completes its copy, and the completion drain frees the slots
    instead of attaching them. ``gen`` fences crash recovery: handles
    from before an engine restart are discarded at drain (the arena was
    reset; their slots are not ours to free)."""

    __slots__ = ("entry", "slots", "blocks", "gen", "cancelled", "error",
                 "event")

    def __init__(self, entry, slots: list, blocks: list, gen: int) -> None:
        self.entry = entry
        self.slots = slots
        self.blocks = blocks
        self.gen = gen
        self.cancelled = False
        self.error: Optional[BaseException] = None
        self.event = threading.Event()


class _SpillWorker:
    """Dedicated spill thread (the round-7 _TokenFetcher pattern): the
    engine thread only dispatches page snapshots and bookkeeping; the
    actual device→host transfer + arena write + checksum — the slow,
    bandwidth-bound part — happens here, strictly off the hot loop. One
    FIFO queue + one worker; completions flow back through ``done`` and
    are folded in by the engine at iteration top (_drain_spills)."""

    def __init__(
        self,
        tier: Any,
        done: "queue.SimpleQueue",
        obs: Optional[EngineObservability] = None,
    ) -> None:
        self._tier = tier
        self._done = done
        self._obs = obs
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread: Optional[threading.Thread] = None

    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        if self.alive():
            return
        self._thread = threading.Thread(
            target=self._run, name="serving-spill", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 30.0) -> bool:
        """Quiesce: handles queued before the sentinel complete their
        copies first, so after a True return no thread touches the arena
        (crash recovery resets it right after). False — with the thread
        left registered so ``alive()`` stays truthful — when the worker
        failed to drain within ``timeout`` (wedged device fetch): the
        caller must NOT reuse an arena this thread may still write into."""
        t = self._thread
        if t is None:
            return True
        self._queue.put(None)
        t.join(timeout=timeout)
        if t.is_alive():
            return False
        self._thread = None
        return True

    def submit(self, handle: _Spill) -> None:
        self._queue.put(handle)

    def _run(self) -> None:
        while True:
            handle = self._queue.get()
            if handle is None:
                return
            try:
                t0 = time.monotonic()
                for block, slot in zip(handle.blocks, handle.slots):
                    leaves = [
                        np.asarray(jax.device_get(leaf))
                        for leaf in jax.tree.leaves(block)
                    ]
                    self._tier.write(slot, leaves)
                if self._obs is not None and self._obs.on:
                    self._obs.record("engine_spill_s", time.monotonic() - t0)
            except BaseException as e:  # noqa: BLE001 — surfaced at drain
                handle.error = e
            handle.blocks = None  # release the snapshot device buffers
            self._done.put(handle)
            handle.event.set()


def _durable_empty_stats() -> dict:
    """Zeroed durable-tier stats keys (tier off) — the exporter sets its
    gauges unconditionally, so the keys must exist either way."""
    from langstream_tpu.serving.durable import DurableStore

    return DurableStore.empty_stats()


class _DurableWorker:
    """Dedicated checkpoint thread for the durable tier (docs/SERVING.md
    §23; the _SpillWorker pattern one tier down): the engine thread
    materializes immutable checkpoint jobs — raw page byte images + their
    spill-time checksums, copied OUT of the arena so a later drop/evict
    cannot race the write — and the fsync-heavy temp+rename disk write
    runs here, strictly off the hot loop. Failures are counted by the
    store and logged, never raised: a failed checkpoint leaves the
    session restorable from its owner, and crash-safety is the store's
    on-disk construction, not this thread's error handling."""

    def __init__(self, store: Any, obs: Optional[EngineObservability] = None):
        self._store = store
        self._obs = obs
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread: Optional[threading.Thread] = None

    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        if self.alive():
            return
        self._thread = threading.Thread(
            target=self._run, name="serving-durable", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 30.0) -> bool:
        t = self._thread
        if t is None:
            return True
        self._queue.put(None)
        t.join(timeout=timeout)
        if t.is_alive():
            return False
        self._thread = None
        return True

    def submit(self, job: dict) -> None:
        self._queue.put(job)

    def flush(self, timeout: float = 30.0) -> bool:
        """Barrier: True once every job enqueued BEFORE this call has
        been written (or failed). Hibernation flushes before it walks
        the index so no session is checkpointed twice."""
        if not self.alive():
            return True
        ev = threading.Event()
        self._queue.put(ev)
        return ev.wait(timeout)

    def _run(self) -> None:
        from langstream_tpu.serving.durable import DurableError

        while True:
            job = self._queue.get()
            if job is None:
                return
            if isinstance(job, threading.Event):
                job.set()
                continue
            t0 = time.monotonic()
            try:
                self._store.checkpoint(
                    job["digest"], job["length"], job["tokens"],
                    job["pages_raw"], job["checksums"],
                    job["page_size"], job["bytes_per_page"],
                )
                if self._obs is not None and self._obs.on:
                    self._obs.record(
                        "engine_durable_checkpoint_s", time.monotonic() - t0
                    )
            except DurableError as e:
                log.warning("durable checkpoint failed: %s", e)
            except BaseException:  # noqa: BLE001 — degrade one entry only
                log.exception("durable checkpoint crashed")


def _make_insert_group():
    @functools.partial(jax.jit, donate_argnames=("cache",))
    def insert_group(cache, local_cache, slots):
        """Scatter a whole prefill batch into the big cache in ONE op —
        per-slot inserts each rewrote the full cache when buffer donation
        degrades to copies (remote/tunneled devices). ``slots`` entries that
        are out of bounds (padding rows) are dropped by the scatter."""

        def put(big, small):
            # [L, B, Hkv, T, ...] — T (dim 3) is the bucket width for both
            # the value arrays and the int8 cache's rank-4 scale arrays
            w = small.shape[3]
            return big.at[:, slots, :, :w].set(
                small.astype(big.dtype), mode="drop"
            )

        return jax.tree.map(put, cache, local_cache)

    return insert_group


class ServingEngine:
    """One engine per model per agent replica; owns the device loop."""

    # default rows per prefill call — fixed so each width bucket compiles ONCE
    PREFILL_BATCH = 8

    # lock discipline registry (analysis pass `locks`, docs/ANALYSIS.md):
    # every write to a guarded attribute outside `with self.<lock>:` is an
    # LSA101 finding. `__init__` and `*_locked` helpers are exempt by
    # convention.
    _GUARDED = {
        "_stats_lock": (
            "shed_total", "cancelled_total", "deadline_queue_total",
            "deadline_decode_total", "quarantined_slots_total",
            "nan_guard_total", "engine_restarts_total", "total_generated",
            "total_requests", "_busy_steps", "_queue_wait_ema_s",
        ),
        "_waiting_lock": ("_waiting",),
    }

    def __init__(
        self,
        config: ModelConfig,
        params: Any,
        max_batch: int = 8,
        max_seq_len: Optional[int] = None,
        eos_token_id: Optional[int] = None,
        prefill_buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024, 2048),
        rng_seed: int = 0,
        mesh: Optional[Any] = None,
        decode_chunk: int = 16,
        prefill_batch: Optional[int] = None,
        spmd: Optional[Any] = None,
        pipeline_depth: int = 1,
        ttft_chunk_floor: int = 4,
        precompile: Optional[bool] = None,
        overlap: bool = True,
        prefill_token_budget: Optional[int] = None,
        max_prefill_streams: Optional[int] = None,
        kv_layout: str = "paged",
        page_size: int = 64,
        kv_pages: Optional[int] = None,
        host_kv_fraction: float = 0.0,
        spill: Any = "auto",
        spill_idle_s: float = 0.0,
        restore_stall_dump_s: float = 1.0,
        durable: Any = "auto",
        durable_dir: Optional[str] = None,
        durable_max_bytes: int = 0,
        durable_timeout_s: float = 5.0,
        prefix_cache: Any = False,
        prefix_cache_fraction: float = 0.25,
        prefix_cache_entries: Optional[int] = None,
        speculation: Any = False,
        speculation_tokens: int = 4,
        adapters: Optional[list] = None,
        adapter_pool_fraction: float = 0.1,
        adapter_rank: Optional[int] = None,
        adapter_pool_rows: Optional[int] = None,
        constrained_decoding: Any = "auto",
        grammar_slots: int = 64,
        grammar_states: int = 128,
        grammar_exceptions: int = 65536,
        grammar_tokenizer: Optional[Any] = None,
        queue_depth: Optional[int] = None,
        shed_policy: str = "block",
        tenants: Optional[list] = None,
        brownout: Any = "auto",
        brownout_enter_load: float = 2.0,
        brownout_exit_load: float = 1.0,
        brownout_dwell_s: float = 0.5,
        restart_backoff_s: float = 0.1,
        max_restarts: int = 5,
        fault_injector: Optional[FaultInjector] = None,
        migrate_staging: bool = False,
        weight_load_report: Optional[dict] = None,
        observability: bool = True,
        flight_iterations: int = 256,
        flight_dir: Optional[str] = None,
    ) -> None:
        """``mesh``: a jax Mesh with a "model" (and optionally "expert") axis.
        ``params`` must already be sharded over it (parallel.sharding);
        the KV cache is sharded to match (kv heads on "model") so every
        decode step partitions over ICI with XLA-inserted collectives —
        one psum per layer, the Megatron schedule."""
        self.config = config
        self.params = params
        self.mesh = mesh
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len or config.max_seq_len
        self.eos_token_id = eos_token_id
        self.prefill_buckets = tuple(
            b for b in prefill_buckets if b <= self.max_seq_len
        ) or (self.max_seq_len,)
        # bounded admission queue. ``shed_policy`` decides what a FULL queue
        # does to submit(): "block" (default) is the broker-poll-loop
        # backpressure contract; "reject" sheds with ShedError(retry-after)
        # so a front door (gateway/HTTP) degrades to fast 429s instead of
        # stacking blocked threads while clients time out anyway.
        if queue_depth is not None and int(queue_depth) <= 0:
            # the loop pops admissions from this queue, so depth 0 cannot
            # mean "no queueing" — reject loudly instead of silently
            # substituting the default
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        # multi-tenant overload control (serving/tenancy.py, docs/SERVING.md
        # §19): per-tenant weights / slot caps / queue shares / token-rate
        # quotas, the per-tenant lifecycle counters, and the admission
        # queue itself — weighted deficit round-robin in prefill-token
        # units, so the fused iteration's budget and the free-slot pool
        # divide by weight. With no tenants configured every request lands
        # in the shared "default" tenant and the queue degrades to the
        # pre-tenancy FIFO exactly.
        tenant_specs = [
            t if isinstance(t, TenantSpec) else TenantSpec.from_dict(t)
            for t in (tenants or [])
        ]
        self._tenants = TenantRegistry(tenant_specs)
        self._queue: TenantQueue = TenantQueue(
            maxsize=(
                int(queue_depth) if queue_depth is not None else max_batch * 4
            ),
            registry=self._tenants,
            cost_fn=lambda r: float(
                self._bucket(len(getattr(r, "prompt_tokens", None) or ()))
            ),
            quantum=float(self.prefill_buckets[-1]),
        )
        # brownout controller (docs/SERVING.md §19): walks the declared
        # degradation ladder off the round-11 load score — spec shrink →
        # spec off → reject low priority → reject over-quota — each step
        # hysteresis-gated, counted, flight-dumped and fully reversed.
        brownout_off = str(brownout).lower() in ("off", "false", "0", "none")
        self._brownout = (
            None
            if brownout_off
            else BrownoutController(
                enter_load=float(brownout_enter_load),
                exit_load=float(brownout_exit_load),
                dwell_s=float(brownout_dwell_s),
            )
        )
        self.brownout_dumps_total = 0
        self._brownout_checked_at = 0.0
        if shed_policy not in ("block", "reject"):
            raise ValueError(
                f"unknown shed_policy {shed_policy!r}; supported: block, reject"
            )
        self.shed_policy = shed_policy
        self._slots = [_Slot() for _ in range(max_batch)]
        # KV memory layout (ROADMAP item 1): "paged" (default) = ONE
        # page-table-indexed device pool for decode, prefill, verify and
        # prefix reuse — no kv_bound compile ladder, prefix hits alias
        # pages zero-copy. "dense" = the per-slot big cache, kept one
        # release as the escape hatch. Paged is legal under multi-host
        # SPMD (allocator events ride the leader→follower wire — round
        # 13, docs/SERVING.md §14) and under sharded meshes (the pool
        # shards its kv heads over "model" like the dense serving cache).
        if kv_layout not in ("paged", "dense"):
            raise ValueError(
                f"unknown kv_layout {kv_layout!r}; supported: paged, dense"
            )
        self.kv_layout = kv_layout
        self._paged = kv_layout == "paged"
        self.page_size = max(1, int(page_size))
        self._pagepool = None
        self._prefix_index = None
        self._cache = None
        # deferred admissions: popped from the queue but waiting for pool
        # pages (allocator exhaustion defers — it never corrupts); retried
        # ahead of the queue every iteration, swept like the queue
        self._page_deferred: list[GenerationRequest] = []
        # physical pages to zero on the next iteration (quarantine)
        self._pending_page_zero: list[int] = []
        # -- tiered KV: host-RAM spill + session hibernation (ROADMAP 3) -----
        # host-kv-fraction sizes a pinned host arena RELATIVE to the device
        # pool (2.0 = twice the pool's pages in host RAM; host RAM is ~10×
        # HBM per host, so large values are the point). 0 disables the tier.
        if str(spill).lower() not in ("auto", "on", "true", "1", "off",
                                      "false", "0"):
            raise ValueError(f"unknown spill {spill!r}; supported: auto, off")
        spill_off = str(spill).lower() in ("off", "false", "0")
        self.host_kv_fraction = max(0.0, float(host_kv_fraction))
        self.spill_idle_s = max(0.0, float(spill_idle_s))
        self._restore_stall_s = max(0.0, float(restore_stall_dump_s))
        spill_on = (
            self._paged and not spill_off and self.host_kv_fraction > 0
        )
        if spmd is not None and spill_on:
            # spill/demote/restore decisions are leader-side host state
            # (arena free list, checksums, idle clocks) and the restore
            # upload is a device dispatch followers would need to replay —
            # neither rides the wire yet. Explicit, LOUD disable (the
            # round-14 adapters precedent): host-kv-fraction > 0 is an
            # explicit ask, so this is a WARNING, not a silent downgrade.
            log.warning(
                "tiered KV host spill is not on the SPMD wire yet; off on "
                "this multi-host replica (host-kv-fraction %.2f ignored)",
                self.host_kv_fraction,
            )
            spill_on = False
        self._spill_on = spill_on
        self._host_tier = None
        self._spill_worker: Optional[_SpillWorker] = None
        self._spill_done: "queue.SimpleQueue" = queue.SimpleQueue()
        self._spill_gen = 0
        # device-only entries awaiting hibernation, oldest first (engine
        # thread only); entries join at publish/restore time
        self._spill_candidates: deque = deque()
        # cumulative tier accounting (engine thread writes, stats() reads)
        self.spill_pages_total = 0
        self.spill_bytes_total = 0
        self.spill_failures_total = 0
        self.restore_pages_total = 0
        self.restore_bytes_total = 0
        self.restored_hits_total = 0
        self.restore_failures_total = 0
        self.recompute_fallbacks_total = 0
        # host-ms spent on spill/restore bookkeeping this iteration (flight
        # recorder phase_ms; reset at iteration top)
        self._spill_ms_iter = 0.0
        self._restore_ms_iter = 0.0
        # -- durable session tier: crash-safe KV checkpoints on disk
        # (docs/SERVING.md §23, ROADMAP 2b/3b). durable-dir names the
        # checkpoint directory (shared volume / object-store mount); the
        # tier checkpoints hibernated arenas there so sessions survive
        # replica death, drain and scale-to-zero, and a cold replica
        # rehydrates the index at boot (resurrection).
        if str(durable).lower() not in ("auto", "on", "true", "1", "off",
                                        "false", "0"):
            raise ValueError(
                f"unknown durable {durable!r}; supported: auto, off"
            )
        durable_off = str(durable).lower() in ("off", "false", "0")
        durable_ask = str(durable).lower() in ("on", "true", "1")
        self.durable_dir = str(durable_dir) if durable_dir else None
        self.durable_timeout_s = max(0.1, float(durable_timeout_s))
        self._durable_max_bytes = max(0, int(durable_max_bytes))
        durable_on = (
            self._paged and not durable_off and self.durable_dir is not None
        )
        if spmd is not None and durable_on:
            # same wire gap as the host tier above: checkpoint/restore
            # decisions are leader-side host state and the restore upload
            # is a device dispatch followers would need to replay. LOUD
            # disable — durable-dir is an explicit ask.
            log.warning(
                "durable KV tier is not on the SPMD wire yet; off on this "
                "multi-host replica (durable-dir %s ignored)",
                self.durable_dir,
            )
            durable_on = False
        if durable_ask and not durable_on:
            log.warning(
                "durable: on requested but unavailable (needs kv-layout: "
                "paged + durable-dir, single-host) — tier stays off"
            )
        self._durable_on = durable_on
        self._durable = None  # DurableStore, built with the pool below
        self._durable_worker: Optional[_DurableWorker] = None
        # admissions served by a durable-tier resurrection (the restore
        # split's third rung: device hit / host restore / durable restore)
        self.durable_restored_hits_total = 0
        # True while a durable restore is serving an admission — the
        # /healthz "restoring" readiness signal during resurrection
        self._durable_restoring = False
        # tokens covered by landed prefill dispatches: with the dispatch
        # histogram's wall-time sum this yields the landed prefill
        # throughput the router's fetch-vs-prefill cost model consumes
        self._prefill_tokens_dispatched = 0
        # -- KV-page migration (disaggregated serving, docs/SERVING.md §18):
        # commands from migration threads (HTTP handlers, the fleet
        # router's dispatch executors) executed at iteration top on the
        # engine thread — the pool/index are engine-thread-only, and the
        # command queue is how a snapshot/bind crosses into that domain
        # without a lock on the hot loop. Each command carries its own
        # reply queue; callers time out (deadline-bounded migrate) rather
        # than block forever on a dead engine.
        self._migrate_cmds: "queue.SimpleQueue" = queue.SimpleQueue()
        self.migrate_pages_out_total = 0
        self.migrate_bytes_out_total = 0
        self.migrate_pages_in_total = 0
        self.migrate_bytes_in_total = 0
        self.migrate_failures_total = 0
        if not self._paged:
            self._cache = make_kv_cache(config, max_batch, self.max_seq_len)
            if mesh is not None:
                from langstream_tpu.parallel.sharding import shard_serving_cache

                self._cache = shard_serving_cache(self._cache, mesh)
        self._insert_group = _make_insert_group()
        self._admit_group = _make_admit_group(mesh)
        self._paged_admit_group = _make_paged_admit_group(mesh)
        # ring long-prefill: mesh spans a "seq" axis → long prompts run as
        # ONE sequence-sharded dispatch instead of the segment loop. On a
        # multi-host replica the leader streams the prompt to followers in
        # fixed-shape chunks first (OP_RING), then every process makes the
        # identical dispatch. DENSE layout only: the ring admit splices
        # into the big cache; under the paged layout long prompts take the
        # budgeted segment loop (which writes straight into pages and has
        # no divisibility constraint) until a paged ring splice exists.
        self._ring_admit = (
            _make_ring_admit(mesh)
            if mesh is not None
            and not self._paged
            and "seq" in getattr(mesh, "shape", {})
            and mesh.shape["seq"] > 1
            else None
        )
        # follower-side accumulation buffer for OP_RING token chunks
        self._spmd_ring_buf: list = []
        # kept: the deterministic crash-recovery rebuild derives the fresh
        # PRNG key from seed + recovery epoch, identically on every host
        self._rng_seed = int(rng_seed)
        self._key = jax.random.PRNGKey(rng_seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dead: Optional[BaseException] = None
        # per-slot sampling params, DEVICE-resident: re-uploading them on
        # every chunk dispatch costs 3 host→device puts through the device
        # tunnel (~100ms latency each) — they only change on admit
        self._temp_dev = jnp.zeros(max_batch, jnp.float32)
        self._top_k_dev = jnp.zeros(max_batch, jnp.int32)
        self._top_p_dev = jnp.ones(max_batch, jnp.float32)
        # device-resident decode chain: last sampled token + next write
        # position per slot (kept on device so chunk k+1 can be dispatched
        # from chunk k's outputs without a host sync)
        self._tokens_dev = jnp.zeros(max_batch, jnp.int32)
        self._positions_dev = jnp.zeros(max_batch, jnp.int32)
        # slots freed since the last dispatch: their device temp must be
        # zeroed, else sample()'s batch-wide any_sample/any_filter predicates
        # keep paying the full-vocab sort for a slot that no longer exists
        self._freed_slots: list[int] = []
        # decode chunk size (tokens per dispatch per slot); clamped to
        # powers of two to bound recompiles
        self.decode_chunk = max(1, int(decode_chunk))
        # dispatch pipeline depth: how many decode chunks may stay in flight
        # (dispatched, unfetched) at once. Depth 1 — dispatch chunk k+1,
        # then fetch chunk k — already overlaps the fetch with compute and
        # measured BEST on the tunneled chip (deeper pipelines delay
        # completion discovery and first-token fetches by a full chunk:
        # +700ms p50 TTFT, no throughput gain). The knob stays for
        # low-dispatch-latency environments where depth 2 can pay.
        self.pipeline_depth = max(1, int(pipeline_depth))
        # smallest chunk the TTFT shrink may pick when admissible work waits
        self.ttft_chunk_floor = max(1, int(ttft_chunk_floor))
        # total steps of the currently in-flight (dispatched, unfetched)
        # chunks, summed over the pipeline
        self._inflight_steps = 0
        # rows per prefill dispatch: bigger = fewer serial prefill calls
        # under a burst (each call costs a tunnel dispatch), at the price of
        # one compile per (prefill_batch, width) shape
        self.prefill_batch = int(prefill_batch or self.PREFILL_BATCH)
        # fused prefill–decode scheduling: every iteration dispatches a
        # token-budgeted slice of pending prefill work (admission groups +
        # chunked-prefill segments) IMMEDIATELY followed by the decode chunk
        # — two back-to-back async dispatches, so a new arrival's first
        # segment rides the very next device dispatch instead of waiting out
        # whole-backlog prefill, and decode never stalls behind more than
        # one budget of prefill. The budget guarantees at least ONE unit of
        # progress (one admission group / one segment per active stream) per
        # iteration; beyond that, prefill work past the budget waits for the
        # next iteration so decode chunks keep interleaving.
        self.overlap = bool(overlap)
        # tokens of prefill work per fused iteration, sized off the
        # chunked-prefill segment width (= the largest prefill bucket): one
        # full-width segment or one admission group rides every iteration
        self.prefill_token_budget = max(
            1, int(prefill_token_budget or self.prefill_buckets[-1])
        )
        # concurrent chunked-prefill streams: with overlap on, two long
        # prompts may interleave their segments (each holds its own local
        # cache — serving/memory.py accounts the per-stream term)
        self.max_prefill_streams = max(
            1, int(max_prefill_streams or (2 if self.overlap else 1))
        )
        # chunked prefill (long-context): prompts wider than the largest
        # bucket loop prefill_segment over bucket-width segments into a
        # batch-1 local cache, budgeted segments per engine iteration so
        # decode keeps flowing in between. One state dict + local cache per
        # stream, keyed by the reserved slot index (the key also rides the
        # SPMD wire, so followers evolve the same per-stream caches).
        self._longs: dict[int, dict] = {}
        self._long_rr: int = -1  # round-robin cursor over stream slots
        self._long_queue: list[GenerationRequest] = []
        # bound the chunked-prefill backlog so submit()'s queue-full
        # backpressure engages for long prompts too (ADVICE r3)
        self._long_queue_cap = 8
        # one long request drained from the queue while the long backlog is
        # full waits HERE (engine thread only) until _long_queue frees —
        # reaching into queue.Queue internals to push it back broke the
        # maxsize/unfinished accounting (ADVICE r4)
        self._held_back: Optional[GenerationRequest] = None
        self._reserved: set[int] = set()
        # long-prefill local caches keyed by slot index, kept on self (not
        # the state dicts) so SPMD followers evolve the same attr through
        # _dev_long_segment (the slot index rides every OP_LONG_SEG block)
        self._long_caches: dict[int, Any] = {}
        # multi-host SPMD: the leader announces every device dispatch over
        # this channel before making it; followers replay via follower_loop
        # (parallel/spmd_serving.py). None = single-host, zero overhead.
        self._spmd = spmd
        # automatic prefix KV reuse (serving/prefix_cache.py): radix index
        # over bucket-aligned token prefixes + a device pool in the slot-
        # cache layout. Warm admissions gather the cached prefix and prefill
        # ONLY the suffix (one segment at the reuse offset); every completed
        # prefill publishes its bucket-aligned prefix back (copy-on-publish,
        # refcounted, LRU-evicted). Legal under SPMD since round 13: the
        # admission (gather+segment) and publish dispatches ride the wire
        # as OP_PREFIX_ADMIT/OP_PREFIX_PUBLISH with the pool ROW index —
        # the radix trie itself stays leader-only host state.
        enabled = (
            prefix_cache is True
            or str(prefix_cache).lower() in ("auto", "on", "true", "1")
        )
        # self-speculative decoding (prompt-lookup drafts + one-dispatch
        # multi-token verification): host-side per-slot n-gram indexes
        # propose up to ``speculation_tokens`` drafts per iteration; the
        # _verify_chunk program scores them all in ONE weight read and
        # advances each slot by accepted+1 tokens. Legal under SPMD since
        # round 13: drafts ride OP_VERIFY (acceptance is computed on
        # device, identically on every host — only the proposals need the
        # wire; the n-gram index stays leader-only).
        spec_on = (
            speculation is True
            or str(speculation).lower() in ("auto", "on", "true", "1")
        )
        self._spec_enabled = spec_on
        # ONE static k engine-wide: every distinct k is a separate compiled
        # verify ladder (k × the pow2 bounds), and a 15-23s mid-traffic
        # compile costs more than any per-request k tuning could win
        self.spec_tokens = max(1, int(speculation_tokens)) if spec_on else 0
        self._spec_index: dict[int, NGramIndex] = {}
        self.spec_dispatches_total = 0
        self.spec_draft_tokens_total = 0
        self.spec_accepted_tokens_total = 0
        self.spec_emitted_tokens_total = 0
        # slot-steps: one per (active slot, verify dispatch) pair — the
        # denominator that makes accepted-tokens-per-step a PER-SLOT number
        # in [1, k+1], comparable to plain decode's fixed 1.0
        self.spec_slot_steps_total = 0
        self.spec_draft_lookups_total = 0
        self.spec_draft_hits_total = 0
        # -- the agentic serving tier (ISSUE 10 / ROADMAP item 4) ------------
        # Multi-LoRA multiplexing: a fixed-shape device pool of stacked
        # low-rank factors (serving/adapters.py); every dispatch gathers
        # each slot's factors by its adapter ROW (host-uploaded [B] int32 —
        # data, not shape, so base + N adapters mix in ONE program).
        # Constrained decoding: response_format grammars compile to token
        # DFAs (serving/constrain.py); the PACKED pool — legality bitmask
        # [G+1, S, ceil(V/32)] uint32 + default-successor/exceptions
        # transition planes, ~32× smaller than the old dense [G+1, S, V]
        # int32 table — lives on device, per-slot grammar rows ride each
        # dispatch, and the DFA state advances ON DEVICE inside fused
        # chunks (searchsorted exceptions probe) while the host mirrors it
        # per delivered token (completion detection + the speculative
        # verify masks).
        adapters_cfg = list(adapters or [])
        constrain_on = (
            constrained_decoding is True
            or str(constrained_decoding).lower() in ("auto", "on", "true", "1")
        )
        if spmd is not None and (adapters_cfg or constrain_on):
            # neither the adapter rows nor the grammar pool ride the
            # leader→follower wire yet; a multi-host replica serves base
            # free-form only (docs/SERVING.md §15). `constrained-decoding:
            # auto` means "enable where supported", so the default degrades
            # SILENTLY here — only an explicit ask (adapters configured, or
            # constrained forced on) deserves the warning
            explicit = bool(adapters_cfg) or (
                constrained_decoding is True
                or str(constrained_decoding).lower() in ("on", "true", "1")
            )
            log.log(
                logging.WARNING if explicit else logging.INFO,
                "adapters/constrained decoding are not on the SPMD wire "
                "yet; off on this multi-host replica",
            )
            adapters_cfg = []
            constrain_on = False
        self._adapters = None
        self._constrain_reg = None
        # dispatch-facing + authoritative per-slot adapter rows: the pair
        # exists so the `adapter` fault site (host corruption drill) is
        # DETECTABLE — _adapter_integrity_check compares them before every
        # decode/verify dispatch, same design as the page tables' _owned
        self._adapter_rows = np.zeros(max_batch, np.int32)
        self._adapter_rows_auth = np.zeros(max_batch, np.int32)
        self._slot_adapter_name: dict[int, str] = {}
        self._g_rows = np.zeros(max_batch, np.int32)
        self._dfa_state_dev = None
        self._slot_dfa: dict[int, Any] = {}
        self._dfa_host_state: dict[int, int] = {}
        self.constrained_requests_total = 0
        self._constrain_host_ema_ms = 0.0
        self._agentic = bool(adapters_cfg) or constrain_on
        adapter_rows_cap, adapter_rank_eff = 0, 0
        if adapters_cfg:
            from langstream_tpu.serving.adapters import (
                AdapterRegistry,
                AdapterSpec,
                rows_for_fraction,
            )

            specs = [
                a if isinstance(a, AdapterSpec) else AdapterSpec.from_dict(a)
                for a in adapters_cfg
            ]
            adapter_rank_eff = int(
                adapter_rank or max((s.rank for s in specs), default=8)
            )
            weights_bytes = sum(
                leaf.size * leaf.dtype.itemsize
                for leaf in jax.tree.leaves(params)
            )
            adapter_rows_cap = (
                int(adapter_pool_rows)
                if adapter_pool_rows is not None
                else rows_for_fraction(
                    config, adapter_rank_eff, weights_bytes,
                    adapter_pool_fraction, n_registered=len(specs),
                )
            )
            self._adapters = AdapterRegistry(
                config, adapter_rows_cap, adapter_rank_eff
            )
            self._adapters.on_load_program = functools.partial(
                self._record_program, "adapter-load"
            )
            for s in specs:
                self._adapters.register(s)
        if constrain_on and int(grammar_slots) <= 0:
            # the zero/disabled contract (shared with grammar_pool_bytes,
            # which returns 0 here, and with the registry, which refuses
            # slots < 1): no pool rows means constrained decoding is OFF,
            # not a silently-coerced 1-slot pool
            log.info(
                "grammar-slots <= 0: constrained decoding disabled "
                "(grammar_pool_bytes contract)"
            )
            constrain_on = False
            self._agentic = bool(adapters_cfg)
        if constrain_on:
            from langstream_tpu.serving.constrain import GrammarRegistry

            tok = grammar_tokenizer
            if tok is None:
                from langstream_tpu.serving.tokenizer import ByteTokenizer

                tok = ByteTokenizer()
            self._constrain_reg = GrammarRegistry(
                tok, config.vocab_size, eos_token_id,
                slots=int(grammar_slots),
                max_states=max(2, int(grammar_states)),
                max_exceptions=max(1, int(grammar_exceptions)),
            )
            self._constrain_reg.on_load_program = functools.partial(
                self._record_program, "grammar-load"
            )
            self._dfa_state_dev = jnp.zeros(max_batch, jnp.int32)
        self._prefix_pool = None
        pool_entries, pool_width = 0, 0
        if enabled and not self._paged:
            from langstream_tpu.serving.prefix_cache import (
                pool_entries_for_fraction,
            )

            pool_width = self.prefill_buckets[-1]
            # an EXPLICIT entry count wins outright — including 0, which
            # disables the pool (`or` would silently re-enable it)
            pool_entries = (
                int(prefix_cache_entries)
                if prefix_cache_entries is not None
                else pool_entries_for_fraction(
                    max_batch, self.max_seq_len, pool_width,
                    prefix_cache_fraction,
                )
            )
        # paged pool sizing: dense-parity token capacity + the prefix-cache
        # fraction as ALIAS headroom (shared pages pinned by the prefix
        # index). prefix_cache_entries caps the INDEX (0 disables reuse);
        # the pages themselves live in the one pool either way.
        self._page_fraction = (
            prefix_cache_fraction if (enabled and self._paged) else 0.0
        )
        self._kv_pages = 0
        prefix_index_entries = 0
        if self._paged:
            from langstream_tpu.serving.pagepool import pages_for_fraction

            self._kv_pages = (
                int(kv_pages)
                if kv_pages is not None
                else pages_for_fraction(
                    max_batch, self.max_seq_len, self.page_size,
                    self._page_fraction,
                )
            )
            if enabled:
                prefix_index_entries = (
                    int(prefix_cache_entries)
                    if prefix_cache_entries is not None
                    else 512
                )
            # the device pool itself is allocated AFTER the memory plan
            # below has logged its arithmetic — an over-committed pool
            # then OOMs with the plan's numbers already on record instead
            # of an unexplained RESOURCE_EXHAUSTED
        # compile the decode kv_bound ladder up front (TPU default): a lazy
        # ladder compile (~20s through the tunnel) otherwise lands MID-
        # TRAFFIC and stalls every active stream — measured as the r5
        # gateway bench regression (96 sessions all at 23.1s p50 TTFT
        # because the first admission wave pushed positions+inflight past
        # the largest warmed bound). Off by default on CPU: tests build
        # hundreds of engines.
        self._precompile = (
            precompile
            if precompile is not None
            else jax.default_backend() == "tpu"
        )
        # request-lifecycle / fault-recovery state ---------------------------
        # drain: finish everything already accepted (active slots + queue),
        # reject new submissions — the graceful half of shutdown; stop()
        # stays the hard half (fail whatever is left)
        self._draining = False
        # True while the engine thread is inside an iteration's admission
        # phase — the only window where a request can be popped from the
        # queue but not yet assigned to a slot; _quiesced() (drain, caller
        # thread) reads it
        self._mid_iteration = False
        # loop-restart supervisor: a crashed iteration quarantines the
        # in-flight slots, rebuilds device state, and restarts under
        # bounded exponential backoff instead of killing the process's
        # serving capacity. Since round 19 this covers SPMD replicas too
        # (docs/SERVING.md §20): the leader announces OP_RECOVER with a
        # fresh epoch instead of STOP, both sides run the identical
        # deterministic rebuild, and QUEUED admissions survive leader-side.
        self.restart_backoff_s = max(0.01, float(restart_backoff_s))
        self.max_restarts = max(0, int(max_restarts))
        self._last_crash_t = 0.0
        # SPMD slice resilience state (§20): the recovery epoch both sides
        # rebuild under (also the PRNG-reset input, so sampled streams stay
        # host-identical after recovery), the beacon's `recovering` window,
        # and the divergence-poll throttle clock
        self._spmd_epoch = 0
        self._recovering = False
        self._spmd_div_checked_at = 0.0
        self.spmd_recoveries_total = 0
        self.spmd_resyncs_total = 0
        self.spmd_watchdog_trips_total = 0
        # slots whose KV rows must be zeroed on the next iteration (NaN
        # quarantine); coalesced into ONE row-reset dispatch
        self._pending_row_resets: list[int] = []
        # fault injection (serving/faultinject.py): explicit injector wins,
        # else env activation (LSTPU_FAULTS) for staging drills
        self._injector = (
            fault_injector if fault_injector is not None else FaultInjector.from_env()
        )
        # observability layer (serving/observability.py): streaming
        # histograms + request-lifecycle spans + the flight recorder.
        # ``observability: off`` is the measured-overhead escape hatch (and
        # the bench's off leg); everything hot-path gates on one flag.
        self._obs = EngineObservability(
            enabled=observability,
            flight_capacity=flight_iterations,
            flight_dir=flight_dir,
        )
        # checkpoint→device load accounting (models/streamload.py via the
        # tpu-serving holder; docs/SERVING.md §22): surfaced in stats()
        # and sampled ONCE into the cold-start histogram — engines build
        # once, so the fleet-wide distribution is the scale-up drill's
        # weight-load bound
        self._weight_load_report: dict[str, Any] = dict(weight_load_report or {})
        if self._weight_load_report.get("total-s"):
            self._obs.record(
                "engine_weight_load_s",
                float(self._weight_load_report["total-s"]),
            )
        # engine iterations, idle included (the flight recorder's clock)
        self._iterations_total = 0
        # dedicated device→host token fetch thread (started with the loop);
        # carries the injector for the fetch-stall site and the fetch
        # histogram
        self._fetcher = _TokenFetcher(self._injector, self._obs)
        # EMA of observed queue wait (submit → admission), feeding the
        # hopeless-deadline shed decision and ShedError.retry_after_s
        self._queue_wait_ema_s = 0.0
        # shadow set of queued-but-unadmitted requests: queue.Queue cannot
        # be inspected without popping, so the per-iteration expiry sweep
        # walks this instead — a queued request whose deadline/cancellation
        # lands while every slot is busy resolves within one iteration, not
        # when a slot finally frees; its (already-resolved) queue entry is
        # skipped at pop time
        self._waiting: dict[int, GenerationRequest] = {}  # id() → request
        self._waiting_lock = threading.Lock()
        # lifecycle counters (stats() → genai gauges → Grafana). ONE lock
        # covers every counter mutation AND the whole stats() read, so a
        # stats() snapshot is internally consistent (shed totals cannot
        # disagree with queue depth read a microsecond later) — the
        # uncontended acquire is ~100ns, noise next to any dispatch
        self._stats_lock = threading.Lock()
        self.shed_total = 0
        self.cancelled_total = 0
        self.deadline_queue_total = 0
        self.deadline_decode_total = 0
        self.quarantined_slots_total = 0
        self.nan_guard_total = 0
        self.engine_restarts_total = 0
        # stats
        self.total_generated = 0
        self.total_requests = 0
        self._busy_steps = 0
        # distinct device-program signatures dispatched so far. Every tuple
        # here is a separate XLA compile (jit cache key = static args +
        # input shapes, which these capture exactly), so the counter going
        # UP after warmup means a 15-23s mid-traffic compile stall landed —
        # tests assert it stays flat (stats()["compiled_programs"]).
        self._programs: set[tuple] = set()
        # achieved-bandwidth gauge: EMA of measured decode step time + the
        # bytes-read model from the memory plan (weights + the kv_bound
        # slice of the cache per step) → HBM GB/s actually sustained, so the
        # gap to the chip's roofline is a shipped metric, not a PERF.md
        # footnote
        self._step_time_ema_s: float = 0.0
        self._last_chunk_ready_t: float = 0.0
        self._last_kv_bound: int = 0
        self._plan = None
        # HBM accounting up front: an over-committed config should announce
        # its arithmetic here, not die in an opaque RESOURCE_EXHAUSTED
        # mid-request (serving/memory.py; divide by the mesh's device count
        # for the per-chip share when sharded)
        # bytes of the expert-sharded weight tensors (MoE w_gate/w_up/
        # w_down — the ONLY tensors param_specs puts on the "expert" axis),
        # measured from the real tree so the bandwidth gauge can divide
        # per-axis instead of flattening model×expert over ALL weights
        self._expert_weight_bytes = 0
        if config.is_moe:
            try:
                self._expert_weight_bytes = sum(
                    leaf.size * leaf.dtype.itemsize
                    for name in ("w_gate", "w_up", "w_down")
                    for leaf in jax.tree.leaves(params["layers"][name])
                )
            except Exception:  # noqa: BLE001 — gauge accounting only
                pass
        try:
            from langstream_tpu.serving.memory import plan_serving_memory

            quantized = any(
                leaf.dtype == jnp.int8 for leaf in jax.tree.leaves(params)
            )
            if self._spill_on and prefix_index_entries <= 0:
                # nothing to hibernate without the alias index: spilled
                # pages are only reachable through prefix entries. Decided
                # BEFORE the plan below so the startup log never claims
                # host arena RAM that is never allocated
                log.warning(
                    "tiered KV host spill needs the prefix index "
                    "(prefix-cache on, prefix-cache-entries > 0); off"
                )
                self._spill_on = False
            plan = plan_serving_memory(
                config, max_batch, self.max_seq_len, quantized_weights=quantized,
                prefill_batch=self.prefill_batch,
                prefill_bucket=self.prefill_buckets[-1],
                prefill_streams=self.max_prefill_streams,
                prefix_pool_entries=pool_entries,
                prefix_pool_width=pool_width,
                speculation_tokens=self.spec_tokens,
                kv_layout=self.kv_layout,
                page_size=self.page_size,
                kv_pages=self._kv_pages,
                page_fraction=self._page_fraction,
                host_kv_fraction=(
                    self.host_kv_fraction if self._spill_on else 0.0
                ),
                adapter_pool_rows=adapter_rows_cap,
                adapter_rank=adapter_rank_eff,
                grammar_slots=(
                    self._constrain_reg.slots if self._constrain_reg else 0
                ),
                grammar_states=(
                    self._constrain_reg.max_states if self._constrain_reg else 0
                ),
                grammar_exceptions=(
                    self._constrain_reg.max_exceptions
                    if self._constrain_reg
                    else 0
                ),
                # role-tagged replicas (§18): budget the host-RAM staging
                # one in-flight KV migration claims on this end
                migrate_staging=bool(migrate_staging) and self._paged,
                # streamed weight load (§22): the measured host staging
                # high-water mark, so the startup log's RSS story covers
                # the load phase the pod was health-probed through
                weight_load_staging=int(
                    self._weight_load_report.get("staging-peak-bytes", 0)
                ),
                # durable tier (§23): disk budget, reported-only
                durable_max_bytes=(
                    self._durable_max_bytes if self._durable_on else 0
                ),
            )
            self._plan = plan
            devices = mesh.devices.size if mesh is not None else 1
            log.info(
                "serving memory plan (%s, B=%d, T=%d, %d device%s): %s%s",
                config.name, max_batch, self.max_seq_len, devices,
                "s" if devices != 1 else "", plan.summary(),
                (
                    f" (~{plan.per_chip_bytes(devices) / 1024**3:.2f}GiB/chip)"
                    if devices > 1
                    else ""
                ),
            )
        except Exception:  # noqa: BLE001 — accounting must never block serving
            log.debug("serving memory plan unavailable", exc_info=True)
        if pool_entries > 0:
            from langstream_tpu.serving.prefix_cache import PrefixCachePool

            self._prefix_pool = PrefixCachePool(
                config, pool_entries, pool_width,
                boundaries=self.prefill_buckets, mesh=mesh,
            )
        if self._paged:
            from langstream_tpu.serving.pagepool import PagePool, PrefixPageIndex

            # allocated AFTER the memory plan logged its arithmetic, like
            # the dense prefix pool: an over-committed pool OOMs with the
            # numbers on record
            self._pagepool = PagePool(
                config, self._kv_pages, self.page_size, max_batch,
                self.max_seq_len,
            )
            if mesh is not None:
                # kv heads on "model" (replicated when they don't divide),
                # same policy as the dense serving cache — every paged
                # program then propagates the sharding from the pool input
                from langstream_tpu.parallel.sharding import shard_page_pool

                self._pagepool.dev = shard_page_pool(self._pagepool.dev, mesh)
            if prefix_index_entries > 0:
                self._prefix_index = PrefixPageIndex(
                    self.prefill_buckets, max_entries=prefix_index_entries
                )
            if self._spill_on:
                from langstream_tpu.serving.pagepool import HostPageTier

                host_pages = max(
                    1, math.ceil(self._kv_pages * self.host_kv_fraction)
                )
                self._host_tier = HostPageTier(self._pagepool.dev, host_pages)
                self._prefix_index.host_tier = self._host_tier
                # hibernation capacity is governed by the arena alone: the
                # index's entry cap counts (and cap-evicts) only
                # DEVICE-resident entries, so idle hibernated sessions are
                # never dropped to make room for a publish
                self._spill_worker = _SpillWorker(
                    self._host_tier, self._spill_done, self._obs
                )
                log.info(
                    "tiered KV host arena: %d host pages (%.2f GiB RAM, "
                    "%.2fx the device pool) — idle prefixes spill after "
                    "%.1fs, LRU eviction demotes before dropping",
                    host_pages, self._host_tier.bytes_total / 1024**3,
                    self.host_kv_fraction, self.spill_idle_s,
                )
            if self._durable_on and self._prefix_index is not None:
                from langstream_tpu.serving.durable import DurableStore

                try:
                    self._durable = DurableStore(
                        self.durable_dir,
                        max_bytes=self._durable_max_bytes,
                        injector=self._injector,
                    )
                    rehydrated = self._durable.rehydrate()
                except OSError:
                    # an unwritable volume must not fail the boot — the
                    # tier degrades to off, sessions fall back to the
                    # host tier / re-prefill exactly as with durable: off
                    log.exception(
                        "durable tier unavailable (%s) — off", self.durable_dir
                    )
                    self._durable = None
                if self._durable is not None:
                    self._durable_worker = _DurableWorker(
                        self._durable, self._obs
                    )
                    log.info(
                        "durable KV tier: %s (%d checkpointed session "
                        "prefix(es) rehydrated%s) — hibernated arenas "
                        "checkpoint crash-safe; sessions survive replica "
                        "death and scale-to-zero",
                        self.durable_dir, rehydrated,
                        (
                            f", cap {self._durable_max_bytes / 1024**3:.2f} GiB"
                            if self._durable_max_bytes
                            else ""
                        ),
                    )
            elif self._durable_on:
                log.warning(
                    "durable tier needs the prefix index (prefix-cache: "
                    "auto) — off"
                )
                self._durable_on = False

    # -- public API ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._dead = None
        self._stop.clear()
        self._fetcher.start()
        if self._spill_worker is not None:
            self._spill_worker.start()
        if self._durable_worker is not None:
            self._durable_worker.start()
        self._thread = threading.Thread(target=self._run, name="serving-engine", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        self._fetcher.stop()
        if self._spill_worker is not None:
            self._spill_worker.stop()
        if self._durable_worker is not None:
            self._durable_worker.stop()
        # resolve everything still in flight so blocked callers return now
        self._fail_all(RuntimeError("serving engine stopped"))

    def drain(self, grace_s: float = 30.0) -> bool:
        """Graceful quiescence, DISTINCT from stop(): reject new submissions
        (ShedError) but let everything already accepted — active slots,
        queued admissions, long-prefill streams — run to completion. Returns
        True when the engine went quiet within ``grace_s``, False when the
        grace period expired with work still in flight (the caller then
        decides between waiting longer and a hard stop()). Does NOT stop the
        engine thread; call stop() after. Re-entrant; ``_draining`` stays set
        so a drain→stop sequence never readmits."""
        self._draining = True
        deadline = time.monotonic() + max(0.0, grace_s)
        while time.monotonic() < deadline:
            if self._quiesced():
                return True
            if self._thread is None or not self._thread.is_alive():
                return self._quiesced()  # loop is gone; nothing will drain
            time.sleep(0.01)
        return self._quiesced()

    def _quiesced(self) -> bool:
        return (
            not self._mid_iteration
            and not any(s.active for s in self._slots)
            and self._queue.qsize() == 0
            and not self._longs
            and not self._long_queue
            and not self._page_deferred
            and self._held_back is None
        )

    def submit(self, request: GenerationRequest) -> GenerationRequest:
        """Thread-safe enqueue. A full queue blocks (shed_policy="block",
        backpressure toward the broker poll loop — SURVEY §7 hard parts) or
        sheds with ShedError carrying a retry-after estimate
        (shed_policy="reject"). Requests whose deadline cannot survive the
        CURRENT observed queue wait are shed immediately either way —
        admitting them would burn queue slots and prefill FLOPs on work
        that is already dead on arrival."""
        if self._dead is not None:
            raise RuntimeError("serving engine is stopped") from self._dead
        # (re)stamp on every submit attempt: a ShedError retry reuses the
        # SAME request object, and a construction-time stamp would count
        # the retry sleep as queue wait — expiring max_queue_wait_s
        # immediately and feeding the inflated wait into the shed EMA
        request.submitted_at = time.monotonic()
        tenant = getattr(request.options, "tenant", None) or DEFAULT_TENANT
        self._tenants.note_submit(tenant)
        if self._draining:
            self._count_shed(tenant)
            raise ShedError("serving engine is draining", retry_after_s=5.0)
        limit = self.max_seq_len - 1
        if len(request.prompt_tokens) > limit:
            raise ValueError(
                f"prompt of {len(request.prompt_tokens)} tokens exceeds the "
                f"engine limit of {limit} (max_seq_len - 1)"
            )
        opts = request.options
        cost_budget = getattr(opts, "max_cost_tokens", None)
        if cost_budget is not None:
            if int(cost_budget) <= 0:
                raise ValueError(
                    f"max_cost_tokens must be >= 1, got {cost_budget}"
                )
            if len(request.prompt_tokens) + 1 > int(cost_budget):
                # the budget cannot afford a single generated token: a
                # client error, not a capacity problem — never a 429
                raise ValueError(
                    f"prompt of {len(request.prompt_tokens)} tokens leaves "
                    f"no generation room in a max_cost_tokens budget of "
                    f"{cost_budget}"
                )
        # brownout admission gates (docs/SERVING.md §19): ladder level 3
        # sheds low-priority work at the door, level 4 sheds over-quota
        # tenants outright — decode of admitted work is never touched
        bo = self._brownout
        if bo is not None and bo.reject_low and (
            getattr(opts, "priority", "normal") == "low"
        ):
            self._count_shed(tenant)
            raise ShedError(
                f"brownout level {bo.level}: low-priority admissions are "
                "shed until load clears",
                retry_after_s=max(self._tenant_wait_estimate(tenant), 0.5),
            )
        over_quota = self._tenants.over_quota(tenant)
        if bo is not None and bo.reject_quota and over_quota:
            self._count_shed(tenant)
            raise ShedError(
                f"brownout level {bo.level}: tenant {tenant!r} is over its "
                "token-rate quota",
                retry_after_s=max(
                    self._tenants.quota_retry_after_s(tenant), 0.5
                ),
            )
        # quota-aware shedding OUTSIDE brownout: over-quota tenants shed
        # FIRST — whenever there is queue pressure AND someone else's work
        # is waiting, the over-quota tenant yields before any in-quota
        # tenant is shed. With the engine otherwise idle its work still
        # runs (work-conserving: quotas bound sustained rate, not access
        # to spare capacity).
        if over_quota and self._queue.qsize() > 0:
            others = [
                t for t in self._queue.tenants_with_work() if t != tenant
            ]
            if others:
                self._count_shed(tenant)
                raise ShedError(
                    f"tenant {tenant!r} is over its token-rate quota while "
                    "other tenants wait",
                    retry_after_s=max(
                        self._tenants.quota_retry_after_s(tenant), 0.1
                    ),
                )
        adapter_name = getattr(opts, "adapter", None)
        if adapter_name and self._adapters is None:
            raise ValueError(
                f"request names adapter {adapter_name!r} but this engine has "
                "no adapter registry (configure `adapters:` on tpu-serving)"
            )
        response_format = getattr(opts, "response_format", None)
        if response_format and self._constrain_reg is None:
            raise ValueError(
                "request carries response_format but constrained decoding is "
                "off on this engine"
                + (
                    " (not supported on multi-host SPMD replicas yet — "
                    "docs/SERVING.md §15)"
                    if self._spmd is not None
                    else " (constrained-decoding: off was configured)"
                )
            )
        if response_format and request._dfa is None:
            # compile (or cache-hit) on the SUBMITTER's thread — grammar
            # compilation is pure host work and must not stall the engine
            # loop; an uncompilable schema fails HERE, loudly
            request._dfa = self._constrain_reg.compile(dict(response_format))
        resume = getattr(opts, "grammar_resume_state", None)
        if request._dfa is not None and resume is not None:
            if request._dfa.is_complete(int(resume)):
                # the derivation already FINISHED when the original stream
                # died (the cut ate only the terminal frame): there is
                # nothing left to generate — resolve immediately instead
                # of sampling a token the uninterrupted run never produced
                request.dfa_state = int(resume)
                request._finish(GenerationResult(
                    tokens=[], finish_reason="stop",
                    prompt_tokens=len(request.prompt_tokens),
                    ttft_s=0.0, total_s=0.0,
                ))
                return request
        deadline_s = request.options.deadline_s
        if deadline_s is not None:
            # the tenant's OWN observed wait decides hopelessness (and the
            # retry-after estimate): a victim tenant with an empty lane is
            # not hopeless just because an aggressor inflated the global EMA
            est_wait = self._tenant_wait_estimate(tenant)
            if deadline_s <= 0 or (self._queue.qsize() > 0 and est_wait >= deadline_s):
                self._count_shed(tenant)
                raise ShedError(
                    f"deadline of {deadline_s:.2f}s cannot survive the "
                    f"current ~{est_wait:.2f}s queue wait",
                    retry_after_s=max(est_wait, 0.1),
                )
        with self._waiting_lock:
            self._waiting[id(request)] = request
        try:
            try:
                if self.shed_policy == "reject":
                    self._queue.put_nowait(request)
                else:
                    self._queue.put(request)
            except queue.Full:
                self._count_shed(tenant)
                raise ShedError(
                    f"admission queue full ({self._queue.maxsize} deep)",
                    retry_after_s=max(self._tenant_wait_estimate(tenant), 0.1),
                ) from None
            except TenantShareExceeded as e:
                # the tenant's SLICE is full even though the global queue
                # may have room: always a shed for that tenant — blocking
                # the shared submitter on one tenant's backlog would be
                # the noisy-neighbor coupling tenancy exists to remove
                self._count_shed(tenant)
                raise ShedError(
                    str(e),
                    retry_after_s=max(self._tenant_wait_estimate(tenant), 0.1),
                ) from None
        except BaseException:
            with self._waiting_lock:
                self._waiting.pop(id(request), None)
            raise
        return request

    def _tenant_wait_estimate(self, tenant: str) -> float:
        """The queue-wait estimate shed decisions and Retry-After use:
        a NAMED tenant's own EMA when it has one — a victim with an empty
        lane must not look hopeless because an aggressor inflated the
        average — falling back to the global EMA for first contact. The
        default tenant IS the untenanted population, so it reads the
        global EMA directly (the pre-tenancy semantics, which the §9
        hopeless-deadline drill pins)."""
        if tenant == DEFAULT_TENANT:
            return self._queue_wait_ema_s
        own = self._tenants.queue_wait_ema_s(tenant)
        return own if own > 0 else self._queue_wait_ema_s

    def generate(
        self,
        prompt_tokens: Optional[list[int]] = None,
        options: Optional[GenerationOptions] = None,
        on_token: Optional[Callable[[int], None]] = None,
        timeout: float = 300.0,
        request: Optional[GenerationRequest] = None,
    ) -> GenerationResult:
        """Blocking convenience wrapper (submit + wait). A wait timeout
        CANCELS the request — before cancellation existed, the caller got
        its TimeoutError while the engine kept decoding the orphan to
        max_new_tokens, burning a slot nobody would ever read.

        ``request``: submit a caller-BUILT request instead of constructing
        one (the fleet dispatch path pre-builds it so the peer can
        register it for cross-process cancel before submitting);
        prompt_tokens/options/on_token are ignored then."""
        if request is None and prompt_tokens is None:
            # fail at the call site, not as a confusing empty-prompt
            # generation three layers later
            raise ValueError("generate() needs prompt_tokens or request")
        req = request if request is not None else GenerationRequest(
            prompt_tokens=list(prompt_tokens),
            options=options or GenerationOptions(),
            on_token=on_token,
        )
        self.submit(req)
        try:
            return req.result(timeout)
        except TimeoutError:
            req.cancel()
            raise

    def _count_shed(self, tenant: Optional[str] = None) -> None:
        """Shed bookkeeping shared by every shed site: count under the
        stats lock (attributed to the shedding tenant when known), then
        let the flight recorder's sliding window decide whether this shed
        completes a BURST worth a postmortem dump (an isolated shed is
        routine backpressure, not an incident)."""
        with self._stats_lock:
            self.shed_total += 1
        if tenant is not None:
            self._tenants.note_shed(tenant)
        if self._obs.on and self._obs.flight.note_shed():
            self._flight_dump("shed-burst")

    def _flight_dump(self, reason: str, extra: Optional[dict] = None,
                     force: bool = False) -> Optional[dict]:
        """Snapshot the flight ring into a dump artifact, stamped with the
        lifecycle counters at dump time. Callable from ANY thread (the
        shed path runs on submitters); debounced per reason inside the
        recorder."""
        if not self._obs.on:
            return None
        extra = dict(extra or {})
        if self._injector is not None:
            # which injected fault preceded this incident (chaos drills)
            extra["injector-events"] = self._injector.events_snapshot()
        return self._obs.flight.dump(
            reason, counters=self._counters_snapshot(), extra=extra,
            force=force,
        )

    def reset_histograms(self) -> None:
        """Zero the streaming histograms (buckets keep). Bench phases call
        this after their warmup request so one compile-heavy cold TTFT
        doesn't own p99 of a steady-state distribution."""
        self._obs.reset_histograms()

    def prefix_advertisement(
        self, top_k: int = 32,
    ) -> tuple[tuple[int, ...], list[tuple[str, int, str]]]:
        """The fleet beacon's affinity payload: the prefix index's bucket
        boundaries plus its most-recently-used ``top_k`` prefixes as
        ``(digest, length, tier)`` triples (serving/fleet.py). ``tier``
        splits device-resident from hibernated (host-tier) sessions so
        sticky routing survives a spill — the router scores ``host`` at a
        discount. Non-mutating and thread-safe — beacon building runs on
        the runtime HTTP thread and must neither touch LRU recency nor
        leak token content."""
        index = self._prefix_index if self._prefix_index is not None else self._prefix_pool
        if index is None:
            return (), []
        ads = index.advertised(top_k)
        if self._prefix_index is not None and self._durable is not None:
            # checkpoints that outlived their live entry still serve (the
            # snapshot path reads them off disk): beacon them at tier
            # "durable" so the router can prefetch/route onto them —
            # resurrection is useless if nobody knows the bytes exist
            live = {d for d, _, _ in ads}
            extra = top_k
            for digest, length in self._durable.entries():
                if extra <= 0:
                    break
                if digest in live:
                    continue
                ads.append((digest, length, "durable"))
                extra -= 1
        return tuple(index.boundaries), ads

    def _counters_snapshot(self) -> dict[str, Any]:
        with self._stats_lock:
            return {
                "shed": self.shed_total,
                "cancelled": self.cancelled_total,
                "deadline-queue": self.deadline_queue_total,
                "deadline-decode": self.deadline_decode_total,
                "quarantined-slots": self.quarantined_slots_total,
                "nan-guard": self.nan_guard_total,
                "engine-restarts": self.engine_restarts_total,
                "spmd-recoveries": self.spmd_recoveries_total,
                "spmd-resyncs": self.spmd_resyncs_total,
                "spmd-watchdog-trips": self.spmd_watchdog_trips_total,
                "total-requests": self.total_requests,
                "total-generated-tokens": self.total_generated,
                "queued": self._queue.qsize(),
                "active-slots": sum(1 for s in self._slots if s.active),
            }

    def stats(self, dump: bool = False) -> dict[str, Any]:
        """One CONSISTENT snapshot: every counter below is read under the
        same lock their writers hold, so shed totals, queue depth and the
        deadline counters can never disagree mid-iteration. Values are
        plain ints/floats/strs/dicts — safe to json.dumps as-is.
        ``dump=True`` additionally snapshots the flight recorder (an
        on-demand postmortem artifact; see docs/SERVING.md §12)."""
        # histogram snapshots take the per-histogram locks only — compute
        # BEFORE the stats lock so lock order is always hist→stats-free
        hist = self._obs.histograms()
        queue_wait_p90 = hist.get("engine_queue_wait_s", {}).get("p90", 0.0)
        # per-tenant block (registry + queue locks, never nested with the
        # stats lock): counters, quota state, live queue depth and active
        # slots by tenant — what beacons and the Grafana gauges consume
        active_by_tenant: dict[str, int] = {}
        for s in self._slots:
            req = s.request
            if req is not None:
                t = getattr(req.options, "tenant", None) or DEFAULT_TENANT
                active_by_tenant[t] = active_by_tenant.get(t, 0) + 1
        tenants = self._tenants.snapshot(
            queued=self._queue.depth_by_tenant(), active=active_by_tenant
        )
        with self._stats_lock:
            out = self._stats_locked()
        out["tenants"] = tenants
        out["brownout"] = (
            self._brownout.snapshot() if self._brownout is not None else None
        )
        out["brownout-level"] = (
            self._brownout.level if self._brownout is not None else 0
        )
        out["brownout-transitions-total"] = (
            self._brownout.transitions_total
            if self._brownout is not None
            else 0
        )
        out["observability"] = self._obs.on
        out["histograms"] = hist
        # load score (ROADMAP item 3): the replica-balancer routing signal
        pool = self._pagepool
        page_pressure = (
            pool.pages_in_use / max(1, pool.num_pages)
            if pool is not None
            else min(1.0, out["queued"] / max(1, self._queue.maxsize))
        )
        out["load-score"] = load_score(
            queue_wait_p90,
            out["active-slots"] / max(1, self.max_batch),
            page_pressure,
        )
        out["flight-dumps-total"] = self._obs.flight.dumps_total
        if dump:
            out["flight-recorder"] = self._flight_dump("on-demand", force=True)
        return out

    def _stats_locked(self) -> dict[str, Any]:
        active = sum(1 for s in self._slots if s.active)
        return {
            "active-slots": active,
            "max-batch": self.max_batch,
            "queued": self._queue.qsize(),
            "long-prefill-active": bool(self._longs),
            "long-prefill-streams": len(self._longs),
            "long-prefill-queued": len(self._long_queue),
            "total-requests": self.total_requests,
            "total-generated-tokens": self.total_generated,
            "busy-steps": self._busy_steps,
            "overlap": self.overlap,
            "prefill-token-budget": self.prefill_token_budget,
            # distinct device programs dispatched (= XLA compiles): flat
            # after warmup ⇔ no mid-traffic compile stalls. Underscore key
            # (vs the dict's dash convention) is the round-6 issue contract
            # — tests and the metrics exporter consume it by this exact
            # name; do not "fix" the spelling
            "compiled_programs": len(self._programs),
            "decode-step-ms": round(self._step_time_ema_s * 1e3, 3),
            "hbm-gbps-decode": self._achieved_hbm_gbps(),
            # unified paged KV pool (zeros under the dense escape hatch, so
            # the metrics exporter sets its gauges unconditionally)
            "kv-layout": self.kv_layout,
            "page-size": self.page_size if self._paged else 0,
            "kv-pages-total": (
                self._pagepool.num_pages if self._pagepool else 0
            ),
            "kv-pages-in-use": (
                self._pagepool.pages_in_use if self._pagepool else 0
            ),
            "kv-bytes-per-page": (
                self._pagepool.bytes_per_page if self._pagepool else 0
            ),
            "kv-page-alias-rate": (
                round(
                    self._pagepool.aliased_pages_total
                    / max(1, self._pagepool.reserved_pages_total),
                    4,
                )
                if self._pagepool
                else 0.0
            ),
            "prefix-copy-bytes-saved-total": (
                self._prefix_index.copy_bytes_saved if self._prefix_index else 0
            ),
            # prefix KV reuse (zeros with the cache off, so the metrics
            # exporter can set its gauges unconditionally); sourced from the
            # dense pool or the paged alias index, whichever is live
            "prefix-cache": (
                self._prefix_pool is not None or self._prefix_index is not None
            ),
            "prefix-cache-hit-rate": (
                self._prefix_pool.hit_rate()
                if self._prefix_pool
                else self._prefix_index.hit_rate() if self._prefix_index else 0.0
            ),
            "prefill-tokens-saved-total": (
                self._prefix_pool.tokens_saved
                if self._prefix_pool
                else self._prefix_index.tokens_saved if self._prefix_index else 0
            ),
            "prefix-pool-bytes-in-use": (
                self._prefix_pool.bytes_in_use()
                if self._prefix_pool
                else self._prefix_index_bytes()
            ),
            "prefix-cache-evictions-total": (
                self._prefix_pool.evictions
                if self._prefix_pool
                else self._prefix_index.evictions if self._prefix_index else 0
            ),
            "prefix-cache-entries": (
                self._prefix_pool.live_entries
                if self._prefix_pool
                else self._prefix_index.live_entries if self._prefix_index else 0
            ),
            # tiered KV: host-RAM spill + session hibernation (zeros with
            # the tier off, so the metrics exporter sets its gauges
            # unconditionally — the standing contract of every block here)
            "host-tier": self._host_tier is not None,
            "host-pages-total": (
                self._host_tier.num_pages if self._host_tier else 0
            ),
            "host-pages-in-use": (
                self._host_tier.slots_in_use if self._host_tier else 0
            ),
            "host-tier-bytes-total": (
                self._host_tier.bytes_total if self._host_tier else 0
            ),
            "spill-pages-total": self.spill_pages_total,
            "spill-bytes-total": self.spill_bytes_total,
            "spill-failures-total": self.spill_failures_total,
            "restore-pages-total": self.restore_pages_total,
            "restore-bytes-total": self.restore_bytes_total,
            # the restore-vs-recompute hit split: a warm hit whose pages
            # lived host-side either restored (DMA) or fell back to a
            # re-prefill (fault/checksum/no-room) — the ratio is THE
            # health gauge of the tier
            "restored-hits-total": self.restored_hits_total,
            "restore-failures-total": self.restore_failures_total,
            "recompute-fallbacks-total": self.recompute_fallbacks_total,
            "host-demotions-total": (
                self._prefix_index.demotions if self._prefix_index else 0
            ),
            "host-evictions-total": (
                self._prefix_index.host_evictions if self._prefix_index else 0
            ),
            # KV-page migration (disaggregated serving, §18): pages/bytes
            # serialized OUT of this replica's pool and bound IN from a
            # peer's — the sender side only counts after the receiver's
            # ACK released the local copy
            "migrate-pages-out-total": self.migrate_pages_out_total,
            "migrate-bytes-out-total": self.migrate_bytes_out_total,
            "migrate-pages-in-total": self.migrate_pages_in_total,
            "migrate-bytes-in-total": self.migrate_bytes_in_total,
            "migrate-failures-total": self.migrate_failures_total,
            # durable session tier (§23) — zeros with the tier off, same
            # exporter contract as every block above
            "durable-tier": self._durable is not None,
            "durable-restored-hits-total": self.durable_restored_hits_total,
            **(
                self._durable.stats()
                if self._durable is not None
                else _durable_empty_stats()
            ),
            # self-speculative decoding (zeros with speculation off, so the
            # metrics exporter sets its gauges unconditionally)
            "speculation": self._spec_enabled,
            "speculation-tokens": self.spec_tokens,
            "spec-acceptance-rate": (
                round(
                    self.spec_accepted_tokens_total
                    / self.spec_draft_tokens_total,
                    4,
                )
                if self.spec_draft_tokens_total
                else 0.0
            ),
            "spec-accepted-tokens-per-step": (
                round(
                    self.spec_emitted_tokens_total / self.spec_slot_steps_total,
                    4,
                )
                if self.spec_slot_steps_total
                else 0.0
            ),
            "spec-draft-hit-rate": (
                round(
                    self.spec_draft_hits_total / self.spec_draft_lookups_total,
                    4,
                )
                if self.spec_draft_lookups_total
                else 0.0
            ),
            "spec-draft-tokens-total": self.spec_draft_tokens_total,
            "spec-accepted-tokens-total": self.spec_accepted_tokens_total,
            "spec-verify-dispatches-total": self.spec_dispatches_total,
            # multi-LoRA multiplexing + constrained decoding (zeros with
            # the agentic tier off, so the metrics exporter sets its
            # gauges unconditionally — the same contract every subsystem
            # block above follows)
            "adapters": self._adapters is not None,
            "adapters-registered": (
                self._adapters.stats()["registered"] if self._adapters else 0
            ),
            "adapters-resident": (
                self._adapters.resident if self._adapters else 0
            ),
            "adapter-pool-rows": (
                self._adapters.rows - 1 if self._adapters else 0
            ),
            "adapter-swaps-total": (
                self._adapters.swaps_total if self._adapters else 0
            ),
            "adapter-pool-bytes": (
                self._adapters.pool_bytes if self._adapters else 0
            ),
            "constrained-decoding": self._constrain_reg is not None,
            "constrained-requests-total": self.constrained_requests_total,
            "grammars-resident": (
                self._constrain_reg.resident if self._constrain_reg else 0
            ),
            "grammar-swaps-total": (
                self._constrain_reg.swaps_total if self._constrain_reg else 0
            ),
            "grammar-pool-bytes": (
                self._constrain_reg.pool_bytes if self._constrain_reg else 0
            ),
            "constrain-overhead-ms": round(self._constrain_host_ema_ms, 4),
            # request lifecycle / fault recovery (this PR's acceptance
            # surface: every degradation path is countable in production)
            "draining": self._draining,
            "shed-total": self.shed_total,
            "cancelled-total": self.cancelled_total,
            "deadline-exceeded-total": (
                self.deadline_queue_total + self.deadline_decode_total
            ),
            "deadline-queue-total": self.deadline_queue_total,
            "deadline-decode-total": self.deadline_decode_total,
            "quarantined-slots-total": self.quarantined_slots_total,
            "nan-guard-total": self.nan_guard_total,
            "engine-restarts-total": self.engine_restarts_total,
            "queue-wait-ema-s": round(self._queue_wait_ema_s, 4),
            "fault-injection": (
                self._injector.stats() if self._injector is not None else None
            ),
            # SPMD wire accounting (PERF.md round 13: ControlBlock
            # bytes/iteration is a MEASURED number, not an estimate)
            "spmd": self._spmd is not None,
            "spmd-announces-total": (
                getattr(self._spmd, "announces_total", 0)
                if self._spmd is not None
                else 0
            ),
            "spmd-announce-bytes-total": (
                getattr(self._spmd, "bytes_announced_total", 0)
                if self._spmd is not None
                else 0
            ),
            # SPMD slice resilience (§20): the recover-in-place ledger.
            # `recovering` is True through the crash→rebuild→backoff
            # window — beacons advertise it so routers exclude the
            # replica WITHOUT quarantining it (sticky sessions held).
            # Zeros single-host, so the exporter sets gauges
            # unconditionally (the standing contract of every block here)
            "recovering": self._recovering,
            "spmd-recovery-epoch": self._spmd_epoch,
            "spmd-recoveries-total": self.spmd_recoveries_total,
            "spmd-resyncs-total": self.spmd_resyncs_total,
            "spmd-watchdog-trips-total": self.spmd_watchdog_trips_total,
            # streamed weight load (docs/SERVING.md §22): the cold-start
            # ledger — per-phase wall times of the checkpoint→device
            # pipeline this engine was built from (zeros for random init,
            # so the metrics exporter sets its gauges unconditionally —
            # the standing contract of every block here)
            "weight-load-streamed": bool(
                self._weight_load_report.get("streamed", False)
            ),
            "weight-load-s": float(
                self._weight_load_report.get("total-s", 0.0)
            ),
            "weight-load-read-s": float(
                self._weight_load_report.get("read-s", 0.0)
            ),
            "weight-load-transform-s": float(
                self._weight_load_report.get("transform-s", 0.0)
            ),
            "weight-load-transfer-s": float(
                self._weight_load_report.get("transfer-s", 0.0)
            ),
            "weight-load-bytes-total": int(
                self._weight_load_report.get("bytes-read", 0)
            ),
            "weight-load-staging-peak-bytes": int(
                self._weight_load_report.get("staging-peak-bytes", 0)
            ),
            "weight-load-shards": int(
                self._weight_load_report.get("shards", 0)
            ),
            "weight-load-workers": int(
                self._weight_load_report.get("workers", 0)
            ),
        }

    @property
    def recovering(self) -> bool:
        """True while the loop supervisor is between a crash and the
        post-backoff restart — the cheap accessor /healthz and beacons
        read (one attribute, no stats() walk)."""
        return self._recovering

    def _prefix_index_bytes(self) -> int:
        """HBM held by pages the paged alias index references (distinct —
        deeper entries share their shallower prefixes' pages). pages_held
        is a counter the ENGINE thread maintains, so reading it from the
        metrics thread never races a _live mutation."""
        if self._prefix_index is None or self._pagepool is None:
            return 0
        return self._prefix_index.pages_held * self._pagepool.bytes_per_page

    def _achieved_hbm_gbps(self) -> float:
        """Bytes-read model per decode step (weights + the kv_bound-sliced
        cache columns, from the memory plan) over the measured step time —
        the achieved-HBM-bandwidth gauge, PER CHIP. The plan's tree is
        global, so on a sharded mesh each chip reads only its shard per
        step — divided per AXIS (weights shard over model×expert but
        replicate over data; the cache shards kv heads over model only
        when they divide), else the gauge reads a multiple of a chip's
        bandwidth and the roofline comparison goes >100% exactly on the
        multi-chip configs it exists to diagnose. Decode is
        bandwidth-bound, so this ÷ the chip's spec sheet IS the utilization
        number (the ~25%-of-roofline gap the r5 verdict flagged becomes a
        live metric)."""
        if self._plan is None or self._step_time_ema_s <= 0:
            return 0.0
        if self._paged:
            # pages actually READ per step: each active slot streams the
            # pages covering its written prefix — content-proportional,
            # which is the paged layout's whole bandwidth story
            pages_read = sum(
                -(-(s.position + 1) // self.page_size)
                for s in self._slots
                if s.active
            )
            read = (
                self._plan.weights_bytes
                + self._pagepool.bytes_per_page * pages_read
            )
            return round(read / self._step_time_ema_s / 1e9, 2)
        bound = min(self._last_kv_bound or self.max_seq_len, self.max_seq_len)
        weights = self._plan.weights_bytes
        cache = self._plan.cache_bytes * bound // max(1, self.max_seq_len)
        if self.mesh is not None:
            shape = dict(getattr(self.mesh, "shape", {}))
            model_ways = max(1, shape.get("model", 1))
            expert_ways = max(1, shape.get("expert", 1))
            # per-axis weight division (parallel/sharding.py param_specs):
            # ONLY the MoE expert FFN tensors carry the "expert" axis —
            # attention/norm/embed/router weights replicate across it, so
            # flattening model×expert over all weights under-reports on
            # exactly the MoE meshes this gauge exists to diagnose
            expert_w = min(self._expert_weight_bytes, weights)
            weights = (
                expert_w // (model_ways * expert_ways)
                + (weights - expert_w) // model_ways
            )
            # the serving cache shards its kv heads over model ONLY when
            # they divide — else it replicates (serving_cache_specs)
            if model_ways > 1 and self.config.n_kv_heads % model_ways == 0:
                cache //= model_ways
        return round((weights + cache) / self._step_time_ema_s / 1e9, 2)

    def _record_program(self, *signature) -> None:
        self._programs.add(tuple(signature))

    # -- engine thread ------------------------------------------------------

    def _warmup_decode_ladder(self) -> None:
        """Run one throwaway decode chunk per kv_bound ladder step so every
        decode shape is compiled BEFORE the first request is served. Runs on
        the engine thread; slots are all free, so the garbage the warmup
        writes into cache/token buffers is dead state (admission rewrites
        every row it activates) — positions/tokens are reset anyway. SPMD:
        the whole family is announced as ONE OP_WARMUP block and the
        follower runs this same function — both sides make the identical
        deterministic dispatch sequence (docs/SERVING.md §14)."""
        def warm(steps: int, bound: Optional[int], stale=()) -> None:
            self._dev_decode(steps, list(stale), bound).block_until_ready()

        bounds = _kv_bound_ladder(self.max_seq_len)
        for i, bound in enumerate(bounds):
            if self._stop.is_set():
                return
            # the first rung also warms the stale-slot temp-reset scatter
            # with an all-out-of-bounds index (every write drops): its
            # first real use is the first completion under traffic, which
            # must not be a compile
            warm(self.decode_chunk, bound, stale=[self.max_batch] if i == 0 else ())
        floor = min(self.ttft_chunk_floor, self.decode_chunk)
        if floor != self.decode_chunk and not self.overlap:
            # the TTFT-shrunk chunk is its own (steps, unbounded) program —
            # only dispatched by the legacy (overlap off) scheduler; fused
            # iterations run full chunks only, so warming it would add a
            # compile the engine can never use
            warm(floor, None)
        # no buffer reset: admission rewrites every row it activates, and
        # leaving the (deterministic) garbage in place keeps SPMD followers
        # — which replay this same warmup — in exact lockstep
        self._warmup_row_reset()
        log.info(
            "decode ladder precompiled: bounds %s, chunk %d",
            bounds, self.decode_chunk,
        )

    def _warmup_row_reset(self) -> None:
        """Quarantine row-reset, warmed all-out-of-bounds (every write
        drops, state untouched) so the first NaN-guard trip under traffic
        is never a compile. Under SPMD both sides warm it inside the
        replayed warmup family — the quarantine dispatch itself rides the
        wire as OP_ROW_RESET (round 13: victim-only quarantine replaced
        the crash-only NaN contract)."""
        self._record_program("row-reset")
        idxs = np.full(self.max_batch, self.max_batch, np.int32)
        self._cache = _reset_rows(self._cache, jnp.asarray(idxs))
        jax.block_until_ready(jax.tree.leaves(self._cache)[0])

    def _warmup_verify_ladder(self) -> None:
        """Speculative twin of _warmup_decode_ladder: one throwaway verify
        dispatch per kv_bound rung (all-zero drafts; slots are free so the
        garbage KV the warmup writes is dead state, exactly like the decode
        warmup), so the (k, bound) verify surface — the ONLY decode-phase
        programs a speculative engine dispatches — is compiled before the
        first request. The first rung also warms the stale-slot temp-reset
        scatter and the tail warms the quarantine row-reset, both with
        all-out-of-bounds indexes (every write drops). Under SPMD the
        family replays whole (OP_WARMUP), like the decode ladder."""
        drafts = np.zeros((self.max_batch, self.spec_tokens), np.int32)
        bounds = _kv_bound_ladder(self.max_seq_len)
        for i, bound in enumerate(bounds):
            if self._stop.is_set():
                return
            stale = [self.max_batch] if i == 0 else []
            self._dev_verify(drafts, stale, bound).block_until_ready()
        self._warmup_row_reset()
        log.info(
            "verify ladder precompiled: bounds %s, k %d",
            bounds, self.spec_tokens,
        )

    def _warmup_paged(self) -> None:
        """Precompile the PAGED program surface before the first request:
        ONE decode (or verify) program — the ladder the dense layout warmed
        rung by rung no longer exists — plus the batch-1 segment family
        (warm suffixes + long-prompt chunks, one per bucket width), the
        copy-on-write page copy, and the quarantine page-zero. Every
        throwaway dispatch runs against all-out-of-bounds tables/indices:
        writes drop, reads clamp into masked columns, so engine state is
        untouched except the PRNG key (which advances before any request is
        served, like the bucket warmup). The admission (paged-prefill)
        family is warmed by _warmup_prefill_buckets as usual."""
        if self._spec_enabled:
            drafts = np.zeros((self.max_batch, self.spec_tokens), np.int32)
            self._dev_verify(drafts, [self.max_batch], 0).block_until_ready()
        else:
            self._dev_decode(
                self.decode_chunk, [self.max_batch], None
            ).block_until_ready()
            floor = min(self.ttft_chunk_floor, self.decode_chunk)
            if floor != self.decode_chunk and not self.overlap:
                # the TTFT-shrunk chunk is its own (steps,) program, but
                # only the legacy (overlap off) scheduler dispatches it
                self._dev_decode(floor, [], None).block_until_ready()
        for ws in self.prefill_buckets:
            if self._stop.is_set():
                return
            first = self._dev_paged_segment(
                np.zeros((1, ws), np.int32), 0, 1, self.max_batch,
                0.0, 0, 1.0, final=False, prompt_len=1,
            )
            jax.block_until_ready(first)
        pool = self._pagepool
        self._record_program("page-copy")
        pool.dev = _page_copy(
            pool.dev, jnp.asarray(0, jnp.int32), jnp.asarray(pool.oob, jnp.int32)
        )
        self._record_program("page-zero")
        pool.dev = _page_zero(
            pool.dev, jnp.asarray(np.full(pool.table_len, pool.oob, np.int32))
        )
        # the snapshot/restore pair serves BOTH the tiered-KV spill path
        # and the §18 migration wire (every paged engine can send/receive
        # a migration) — warmed so the first restore OR first migration is
        # DMA, not DMA + compile (the unwarmed pair measured ~14s of a
        # first HTTP migration's wall). Restore targets the OOB sentinel:
        # drops.
        self._record_program("page-snapshot")
        snap = _page_snapshot(pool.dev, jnp.asarray(0, jnp.int32))
        self._record_program("page-restore")
        pool.dev = _page_restore(
            pool.dev, snap, jnp.asarray(pool.oob, jnp.int32)
        )
        jax.block_until_ready(jax.tree.leaves(pool.dev)[0])
        log.info(
            "paged programs precompiled: ONE %s program (chunk %d), %d "
            "segment widths, page-copy, page-zero — no kv_bound ladder",
            "verify" if self._spec_enabled else "decode",
            self.spec_tokens + 1 if self._spec_enabled else self.decode_chunk,
            len(self.prefill_buckets),
        )

    def _warmup_prefill_buckets(self) -> None:
        """Precompile one admission program per prefill bucket width so the
        fused iterations' prefill halves quantize into the warmed set too —
        before this, the first admission wave at each width compiled
        admit_group MID-TRAFFIC (the same 15-23s stall class the decode
        ladder warmup closed; the gateway bench only dodged it because its
        warmup chat happened to use the only configured bucket). All rows
        are padding (slots out of bounds → every scatter drops), so engine
        state is untouched except the PRNG key, which advances before any
        request is served. SPMD: the family replays whole (OP_WARMUP) so
        followers warm and key-advance identically."""
        n_pad = self.prefill_batch
        for width in self.prefill_buckets:
            if self._stop.is_set():
                return
            tokens = np.zeros((n_pad, width), np.int32)
            lengths = np.ones(n_pad, np.int32)
            temps = np.zeros(n_pad, np.float32)
            top_ks = np.zeros(n_pad, np.int32)
            top_ps = np.ones(n_pad, np.float32)
            slots = np.full(n_pad, self.max_batch, np.int32)  # all dropped
            self._dev_prefill(
                width, tokens, lengths, temps, top_ks, top_ps, slots
            ).block_until_ready()
        # the decode-chain scatter (warm prefix admissions AND the final
        # chunked-prefill segment dispatch it): one traced-index program,
        # warmed with an all-dropped slot so its first real use — the first
        # completed long prompt, prefix cache or not — is never a compile
        self._record_program("chain-scatter")
        (
            self._tokens_dev, self._positions_dev, self._temp_dev,
            self._top_k_dev, self._top_p_dev,
        ) = _chain_scatter(
            self._tokens_dev, self._positions_dev, self._temp_dev,
            self._top_k_dev, self._top_p_dev,
            jnp.asarray(self.max_batch, jnp.int32),
            jnp.zeros(1, jnp.int32), 0, 0.0, 0, 1.0,
        )
        jax.block_until_ready(self._tokens_dev)
        log.info(
            "prefill buckets precompiled: widths %s, rows %d",
            list(self.prefill_buckets), n_pad,
        )

    def _warmup_prefix_programs(self) -> None:
        """Warm every program a warm admission can dispatch — publish, the
        gather at every local-cache width (pool width for short prompts
        plus the pow2 long-prompt ladder), the pool-width insert, and all
        reachable suffix-segment shapes — with all-dropped / throwaway
        dispatches, so NO prefix-cache code path ever compiles
        mid-traffic (the compiled_programs-flat guarantee; the
        chain-scatter is warmed unconditionally in
        _warmup_prefill_buckets)."""
        from langstream_tpu.ops.kvcopy import gather_prefix_local, publish_prefix_rows

        pool = self._prefix_pool
        assert pool is not None
        # publish with an out-of-bounds entry row: every write drops
        self._record_program("prefix-publish")
        pool.dev = publish_prefix_rows(
            pool.dev, self._cache,
            jnp.asarray(0, jnp.int32), jnp.asarray(pool.entries, jnp.int32),
        )
        # gather ladder: pool width (short warm admissions) + every
        # _long_width value (warm long-prompt starts) — O(log) programs,
        # the decode-ladder policy. Each throwaway local frees before the
        # next, so peak transient = one long-prefill cache (plan term).
        widths = [pool.width]
        w = pool.width
        while w < self.max_seq_len:
            w *= 2
            widths.append(min(w, self.max_seq_len))
        local = None
        for width in dict.fromkeys(widths):
            if self._stop.is_set():
                return
            self._record_program("prefix-gather", width)
            got = gather_prefix_local(
                pool.dev, jnp.asarray(0, jnp.int32), self.config, width
            )
            if width == pool.width:
                local = got
            else:
                jax.block_until_ready(got)
        # the warm-admission insert at pool width; slot out of bounds → drop
        self._record_program("insert", pool.width)
        self._cache = self._insert_group(
            self._cache, local, jnp.asarray(np.full(1, self.max_batch, np.int32))
        )
        jax.block_until_ready(self._cache)
        # suffix-segment shapes: a warm SHORT admission prefills one
        # (ws ∈ buckets) segment into a pool-width local cache at a
        # kv_bound from ws's doubling ladder — shapes nothing else
        # compiles (cold admissions use admit_group; long prompts use
        # t_long ≥ 2× pool width). Warm every reachable pair so the first
        # prefix HIT per shape is never the 15-23s stall that would make
        # the cache slower than no cache until amortized. O(|buckets| ×
        # log) programs, the same front-load-the-compiles policy as the
        # decode ladder; offset/lengths are traced so one throwaway
        # dispatch per shape covers all reuse offsets. The PRNG key
        # advances per dispatch — before any request is served, like the
        # bucket warmup.
        segment_shapes = []
        for ws in self.prefill_buckets:
            bound = ws
            while True:
                segment_shapes.append((ws, min(bound, pool.width)))
                if bound >= pool.width:
                    break
                bound *= 2
        for ws, bound in dict.fromkeys(segment_shapes):
            if self._stop.is_set():
                return
            throwaway = gather_prefix_local(
                pool.dev, jnp.asarray(0, jnp.int32), self.config, pool.width
            )
            self._record_program("segment", ws, bound, pool.width)
            kw = self._segment_agentic_kwargs(None, self.max_batch)
            first, throwaway, self._key, state_dev = (
                _prefill_segment_and_sample(
                    self.params,
                    jnp.zeros((1, ws), jnp.int32),
                    jnp.zeros(1, jnp.int32),
                    jnp.ones(1, jnp.int32),
                    throwaway,
                    self._key,
                    jnp.zeros(1, jnp.float32),
                    jnp.zeros(1, jnp.int32),
                    jnp.ones(1, jnp.float32),
                    self.config,
                    bound,
                    **kw,
                )
            )
            if state_dev is not None:
                self._dfa_state_dev = state_dev
            jax.block_until_ready(first)
        log.info(
            "prefix-cache programs precompiled: pool %d×%d, gather widths %s, "
            "%d suffix-segment shapes",
            pool.entries, pool.width, list(dict.fromkeys(widths)),
            len(dict.fromkeys(segment_shapes)),
        )

    def _run(self) -> None:
        """Engine-thread supervisor: run the serving loop; on a crash,
        quarantine the in-flight slots, rebuild device state, and restart
        under bounded exponential backoff instead of leaving the process
        alive but unable to serve until a pod restart. Under SPMD the crash
        is COORDINATED (docs/SERVING.md §20): OP_RECOVER with a fresh epoch
        rides the wire before the rebuild, followers run the identical
        deterministic rebuild in place, and idle heartbeats keep their
        watchdogs fed through the backoff wait — zero process exits.
        Unrecoverable paths (a proven divergence — half the mesh must never
        serve alone — non-Exception BaseExceptions, or the restart budget
        exhausted) keep the crash-only contract: fail everything, announce
        STOP."""
        backoff = self.restart_backoff_s
        restarts = 0
        try:
            while True:
                try:
                    self._recovering = False
                    self._run_once(warm=restarts == 0)
                    return  # clean stop
                except BaseException as e:  # noqa: BLE001 — classify below
                    now = time.monotonic()
                    if self._last_crash_t and now - self._last_crash_t > 60.0:
                        # a crash long after the previous one is a fresh
                        # incident, not an escalation — reset the budget
                        restarts = 0
                        backoff = self.restart_backoff_s
                    self._last_crash_t = now
                    recoverable = (
                        isinstance(e, Exception)
                        # a PROVEN leader/follower divergence stays fatal:
                        # rebuilding in place would let half the mesh serve
                        # state the other half provably disagrees with
                        and not isinstance(e, wire.SpmdDivergenceError)
                        and restarts < self.max_restarts
                        and not self._stop.is_set()
                    )
                    if not recoverable:
                        log.exception("serving engine loop crashed (unrecoverable)")
                        self._fail_all(e)
                        return
                    restarts += 1
                    self._recovering = True
                    with self._stats_lock:
                        self.engine_restarts_total += 1
                        if self._spmd is not None:
                            self.spmd_recoveries_total += 1
                        if isinstance(e, EngineWedgedError):
                            # the leader-side watchdog caught a wedged
                            # iteration and escalated it here (§20)
                            self.spmd_watchdog_trips_total += 1
                    # dump BEFORE _recover clears state: the ring holds the
                    # iterations that led to the crash — the postmortem
                    self._flight_dump(
                        "engine-restart",
                        extra={"error": type(e).__name__, "restart": restarts},
                    )
                    log.exception(
                        "serving engine loop crashed; quarantining %d in-flight "
                        "slot(s), restarting in %.2fs (restart %d/%d)",
                        sum(1 for s in self._slots if s.active) + len(self._longs),
                        backoff, restarts, self.max_restarts,
                    )
                    # SPMD only: epoch bump FIRST (the deterministic
                    # rebuild keys its PRNG reset off it), then the
                    # coordinated announce — followers start their
                    # identical rebuild while the leader tears down, and
                    # the seq chain restarts at the epoch base on both
                    # sides. Single-host restarts keep epoch 0 and their
                    # live PRNG (no cross-host determinism to protect).
                    if self._spmd is not None:
                        self._spmd_epoch += 1
                        try:
                            self._spmd.announce(wire.ControlBlock(
                                op=wire.OP_RECOVER, count=self._spmd_epoch,
                            ))
                            self._spmd.reset_seq()
                        except Exception:  # noqa: BLE001 — transport gone:
                            # followers will watchdog out and the pods
                            # restart together (the pre-round-19 contract)
                            log.exception(
                                "failed to announce OP_RECOVER to followers"
                            )
                        self._flight_dump(
                            "spmd-recover",
                            extra={
                                "epoch": self._spmd_epoch,
                                "error": type(e).__name__,
                                "restart": restarts,
                            },
                        )
                    try:
                        self._recover(e)
                    except BaseException as e2:  # noqa: BLE001 — recovery itself failed
                        # e.g. the cache rebuild OOMed: the crash-only
                        # contract must hold — without this, the thread
                        # dies with _dead unset and submit() keeps feeding
                        # a queue nobody serves
                        log.exception("crash recovery failed; engine is dead")
                        self._fail_all(e2)
                        return
                    if self._backoff_wait(backoff):
                        return  # stop() raced the backoff; it fails the rest
                    backoff = min(backoff * 2, 30.0)
        finally:
            self._recovering = False
            if self._spmd is not None:
                # release follower processes parked in recv() — best-effort
                # on the crash path too, else they block in the collective
                # forever while the leader pod looks alive. Announcements
                # only ever come from this thread, so STOP is totally
                # ordered after every dispatch.
                try:
                    self._spmd.announce(wire.ControlBlock(op=wire.OP_STOP))
                except Exception:  # noqa: BLE001 — transport may be gone too
                    log.exception("failed to announce STOP to SPMD followers")

    def _backoff_wait(self, backoff_s: float) -> bool:
        """The restart-backoff sleep, sliced so SPMD followers keep seeing
        idle heartbeats through it (their watchdog cannot tell a backoff
        wait from a dead leader otherwise — §20). Returns True when stop()
        raced the wait. Single-host (or watchdog off): one plain wait."""
        spmd = self._spmd
        if spmd is None or getattr(spmd, "watchdog_s", 0) <= 0:
            return self._stop.wait(backoff_s)
        slice_s = max(0.05, spmd.watchdog_s / 4)
        deadline = time.monotonic() + backoff_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            if self._stop.wait(min(slice_s, remaining)):
                return True
            self._spmd_heartbeat()

    def _run_once(self, warm: bool) -> None:
        from collections import deque

        # batches of deferred fetch entries, one per loop iteration, newest
        # last; up to pipeline_depth batches stay unfetched so their device
        # work overlaps host bookkeeping AND the next dispatches
        pending: deque[list[tuple]] = deque()
        if self._precompile and warm:
            # restarts skip the warmups: every program is already in the jit
            # cache (shapes are unchanged), and recovery latency is the point.
            # SPMD: each family is ONE OP_WARMUP announcement — the follower
            # runs the same function, so both sides make the identical
            # deterministic dispatch sequence without per-dispatch wire
            # traffic (docs/SERVING.md §14)
            def announce_warmup(kind: int) -> None:
                if self._spmd is not None:
                    self._spmd.announce(
                        wire.ControlBlock(op=wire.OP_WARMUP, count=kind)
                    )

            if self._paged:
                # the whole point of the paged layout: the decode-phase
                # surface is ONE program (per step count), not a ladder
                announce_warmup(wire.WARMUP_PAGED)
                self._warmup_paged()
            elif self._spec_enabled:
                # a speculative engine dispatches the verify ladder instead
                # of decode chunks — warming both would double startup time
                # for programs it can never run
                announce_warmup(wire.WARMUP_VERIFY_LADDER)
                self._warmup_verify_ladder()
            else:
                announce_warmup(wire.WARMUP_DECODE_LADDER)
                self._warmup_decode_ladder()
            announce_warmup(wire.WARMUP_PREFILL_BUCKETS)
            self._warmup_prefill_buckets()
            if self._prefix_pool is not None:
                announce_warmup(wire.WARMUP_PREFIX_PROGRAMS)
                self._warmup_prefix_programs()
            if self._agentic:
                # no announce: the agentic tier is construction-disabled
                # under SPMD, so this warmup never runs on a replica
                self._warmup_agentic()
        while not self._stop.is_set():
            self._iterate(pending)
        while pending:
            for entry in pending.popleft():
                self._process_entry(entry)

    def _recover(self, error: BaseException) -> None:
        """Quarantine-and-rebuild after a loop crash, WITHOUT failing
        untouched work: in-flight slots and long-prefill streams (their
        device state is suspect — the crashed dispatch may have consumed
        its donated buffers) fail with the error and count as quarantined;
        QUEUED admissions were never dispatched, so they stay queued and
        are served after the restart. Every device-resident array is
        rebuilt from scratch — with buffer donation there is no safe way
        to keep using arrays a failed dispatch may have invalidated."""
        quarantined = 0
        # teardown STRICTLY BEFORE _finish: the waiter wakes INSIDE _finish
        # (on_done / result()), and anything it reads right away — active
        # slots, stats(), the slot's token list — must already reflect the
        # quarantine. Finishing first left a window where a woken waiter
        # observed its own half-torn slot (the finish-waker race; the
        # regression test loses it deterministically under the injector).
        finished: list[tuple[GenerationRequest, GenerationResult]] = []
        for i, slot in enumerate(self._slots):
            request = slot.request
            if request is not None:
                quarantined += 1
                result = GenerationResult(
                    tokens=list(slot.generated), finish_reason="error",
                    prompt_tokens=len(request.prompt_tokens),
                    ttft_s=0, total_s=0, error=error,
                )
                slot.request = None
                slot.generated = []
                slot.position = 0
                slot.last_token_at = 0.0
                self._slot_clear_agentic(i)
                finished.append((request, result))
        for idx in list(self._longs):
            st = self._longs.pop(idx)
            entry = st.pop("prefix", None)
            if entry is not None and self._prefix_pool is not None:
                try:
                    self._prefix_pool.release(entry)
                except Exception:  # noqa: BLE001 — pool resets below anyway
                    pass
            quarantined += 1
            self._reserved.discard(idx)
            self._long_caches.pop(idx, None)
            finished.append((st["request"], GenerationResult(
                tokens=[], finish_reason="error", prompt_tokens=0,
                ttft_s=0, total_s=0, error=error,
            )))
        with self._stats_lock:
            self.quarantined_slots_total += quarantined
        self._longs.clear()
        self._long_caches.clear()
        self._reserved.clear()
        for request, result in finished:
            request._finish(result)
        self._inflight_steps = 0
        # PRNG reset is an SPMD determinism measure (both hosts re-key
        # from seed+epoch); a single-host restart keeps its live key —
        # the pre-round-19 behavior, nothing cross-host to protect
        self._rebuild_device_state(reset_key=self._spmd is not None)
        if isinstance(error, EngineWedgedError):
            # the fetch worker may still be parked inside the hung
            # device_get that tripped the watchdog — every post-recovery
            # fetch would queue BEHIND it on the FIFO and re-wedge until
            # the restart budget burned down to the old crash-only
            # outcome. Abandon it like the device arrays (its late
            # result lands in an orphaned handle) and start fresh.
            log.warning("abandoning the wedged fetch worker")
            self._fetcher = _TokenFetcher(self._injector, self._obs)
        if not self._fetcher.alive():
            self._fetcher.start()

    def _rebuild_device_state(self, reset_key: bool = True) -> None:
        """Deterministic device-state rebuild after a loop crash — every
        device-resident array is remade from scratch (with buffer donation
        there is no safe way to keep arrays a failed dispatch may have
        invalidated), same shapes so no recompiles land on restart.

        SHARED by the leader's ``_recover`` and the SPMD follower's
        OP_RECOVER replay (``_spmd_follower_recover``): same config + same
        epoch ⇒ byte-identical post-recovery state on every host — the
        OP_WARMUP rule applied to recovery (docs/SERVING.md §20). With
        ``reset_key`` the fresh PRNG key derives from seed + recovery
        epoch so even SAMPLED streams stay host-identical after a
        recovery (the crashed dispatch may have consumed the key on one
        side only)."""
        self._spmd_ring_buf.clear()
        self._freed_slots.clear()
        self._spec_index.clear()
        self._pending_row_resets.clear()
        self._step_time_ema_s = 0.0
        self._last_chunk_ready_t = 0.0
        # fresh device state (same shapes → no recompiles on restart)
        if self._paged:
            # pool buffer is donation-suspect like the dense cache; the
            # allocator and every table reset with it (the in-flight slots
            # whose pages they tracked were just failed above). Queued and
            # page-deferred admissions keep their backlog spots.
            self._pending_page_zero.clear()
            # tiered KV: quiesce the spill worker BEFORE resetting the
            # arena (stop() completes in-flight copies first, so no thread
            # writes a slot the fresh free list is about to re-issue);
            # stale done-handles are fenced off by the generation bump
            spill_quiesced = True
            if self._spill_worker is not None:
                spill_quiesced = self._spill_worker.stop()
            self._spill_gen += 1
            self._spill_candidates.clear()
            while True:
                try:
                    self._spill_done.get_nowait()
                except queue.Empty:
                    break
            if self._host_tier is not None:
                if spill_quiesced:
                    self._host_tier.reset()
                else:
                    # the worker is wedged past the join timeout (hung
                    # device fetch — the very failure mode recovery
                    # handles) and may still write into whatever arena it
                    # holds a reference to. Resetting THAT arena would let
                    # the late write land in a slot the fresh free list
                    # re-issued, with a valid checksum: silent wrong KV at
                    # a later restore. Abandon arena AND worker — the
                    # straggler's writes land in orphaned memory
                    log.error(
                        "spill worker failed to quiesce — abandoning the "
                        "host arena (%.2f GiB) and spawning a fresh one",
                        self._host_tier.bytes_total / 1024**3,
                    )
                    from langstream_tpu.serving.pagepool import HostPageTier

                    self._host_tier = HostPageTier(
                        self._pagepool.dev, self._host_tier.num_pages
                    )
                    if self._prefix_index is not None:
                        self._prefix_index.host_tier = self._host_tier
                    self._spill_worker = _SpillWorker(
                        self._host_tier, self._spill_done, self._obs
                    )
            self._pagepool.reset()
            if self.mesh is not None:
                from langstream_tpu.parallel.sharding import shard_page_pool

                self._pagepool.dev = shard_page_pool(
                    self._pagepool.dev, self.mesh
                )
            if self._prefix_index is not None:
                self._prefix_index.reset()
            if self._spill_worker is not None:
                self._spill_worker.start()
        else:
            self._cache = make_kv_cache(
                self.config, self.max_batch, self.max_seq_len
            )
            if self.mesh is not None:
                from langstream_tpu.parallel.sharding import shard_serving_cache

                self._cache = shard_serving_cache(self._cache, self.mesh)
        self._tokens_dev = jnp.zeros(self.max_batch, jnp.int32)
        self._positions_dev = jnp.zeros(self.max_batch, jnp.int32)
        self._temp_dev = jnp.zeros(self.max_batch, jnp.float32)
        self._top_k_dev = jnp.zeros(self.max_batch, jnp.int32)
        self._top_p_dev = jnp.ones(self.max_batch, jnp.float32)
        if self._dfa_state_dev is not None:
            self._dfa_state_dev = jnp.zeros(self.max_batch, jnp.int32)
        if self._prefix_pool is not None:
            # pool rows may hold rows published from the poisoned cache (or
            # the pool buffer itself may be donation-invalidated mid-publish)
            self._prefix_pool.reset()
        if reset_key:
            self._key = jax.random.PRNGKey(self._rng_seed + self._spmd_epoch)

    def _spmd_follower_recover(self, epoch: int) -> None:
        """Follower half of OP_RECOVER (parallel/spmd_serving.py): adopt
        the leader's recovery epoch and run the identical deterministic
        rebuild. The follower never owns requests or a queue — only its
        device arrays and page tables evolve — so the rebuild IS its whole
        recovery; replay resumes at the epoch-base seq afterwards."""
        self._spmd_epoch = int(epoch)
        self._rebuild_device_state()

    def _iterate(self, pending) -> None:
        """ONE fused engine iteration: a token-budgeted slice of pending
        prefill work (chunked-prefill segments first, then admission groups)
        dispatched back-to-back with the decode chunk — two async dispatches
        on the in-order device stream, so the prefill slice and the chunk
        interleave at iteration granularity and neither backlog starves the
        other. Extracted from _run so tests can drive exactly one iteration
        (the engine thread just loops this)."""
        obs_on = self._obs.on
        self._iterations_total += 1
        t0 = time.monotonic() if obs_on else 0.0
        # SPMD slice resilience (§20): the spmd-crash drill site, the
        # divergence-resync poll, and the idle heartbeat — all at the
        # iteration top, OUTSIDE any dispatch's announce sequence
        if self._spmd is not None:
            self._spmd_tick()
        if self._pending_row_resets:
            self._flush_row_resets()
        if self._pending_page_zero:
            self._flush_page_zeros()
        # tiered KV: fold completed spills in and start hibernation spills
        # for idle prefixes — bounded per iteration, O(1) when idle; the
        # restore half runs inside admission (_paged_bind) where it gates
        self._spill_ms_iter = 0.0
        self._restore_ms_iter = 0.0
        if self._spill_on:
            self._spill_tick()
        # KV-page migration commands (snapshot/bind/release — §18) cross
        # into the engine-thread domain here; O(1) when idle (one
        # SimpleQueue emptiness check), and the idle loop spins at ~1ms so
        # a migration never waits behind more than one iteration
        self._drain_migrations()
        self._sweep_waiting()
        # brownout ladder (docs/SERVING.md §19): throttled load check on
        # the engine thread — transitions count, dump and log here
        if self._brownout is not None:
            self._brownout_tick()
        # deterministic noisy-neighbor drill: the `tenant-burst` fault
        # site injects a synthetic aggressor burst at the iteration top
        if self._injector is not None:
            self._tenant_burst_tick()
        t_sweep = time.monotonic() if obs_on else 0.0
        # chunks dispatched in previous iterations are still unfetched when
        # this iteration's dispatch computes its headroom bound — subtract
        # ALL of them
        self._inflight_steps = sum(
            e[3] for batch in pending for e in batch if e[0] == "chunk"
        )
        had_active = any(s.active for s in self._slots)
        # the fused-iteration prefill budget (overlap off: unbounded, the
        # pre-overlap whole-backlog admission). Long prefill FIRST: it
        # claims a freed slot before _admit hands them all to short
        # requests, so a long prompt can't be starved forever under
        # sustained short traffic.
        budget = self.prefill_token_budget if self.overlap else None
        # _mid_iteration marks drain()'s pop-to-slot blind spot: a request
        # get_nowait()'d here but not yet visible as an active slot exists
        # only inside this admission phase, so _quiesced() (sampling from
        # the drain caller's thread) must not report quiet during it —
        # while staying False on idle iterations, which never pop anything
        self._mid_iteration = True
        try:
            new_pending, spent = self._long_step(budget)
            n_long_entries = len(new_pending)
            if budget is not None:
                budget = max(0, budget - spent)
            new_pending.extend(self._admit(budget))  # deferred first-token fetches
        finally:
            self._mid_iteration = False
        # prefill dispatched this iteration rides the in-order stream AHEAD
        # of the chunk below — its chunk must not feed the step-time gauge
        prefill_ahead = bool(new_pending) or spent > 0
        t_prefill = time.monotonic() if obs_on else 0.0
        n_admitted = sum(
            len(e[2]) for e in new_pending if e[0] == "prefill"
        )
        # prefill tokens this iteration = long-segment tokens (``spent``) +
        # the ADMISSION groups' prompts (entries past the _long_step slice
        # — a long prompt's final-segment entry must not double-count the
        # segments already in ``spent``)
        prefill_tokens = spent + sum(
            len(req.prompt_tokens)
            for e in new_pending[n_long_entries:]
            if e[0] == "prefill"
            for _, req in e[2]
        )
        if new_pending and not had_active:
            # cold start (nothing was decoding): there is no compute
            # to overlap the deferred fetch with, and on a tunneled
            # device the fetch would otherwise queue BEHIND the first
            # decode chunk dispatched below (~a full chunk of extra
            # TTFT, measured: 700ms → ~300ms at 96-session burst).
            # Do NOT widen this to low-but-nonzero occupancy: an
            # inline fetch under ANY active decode serializes the
            # loop on the in-flight chunk and collapsed the chat
            # bench to 740 tok/s / 14.8s p50 TTFT when tried (r4)
            for entry in new_pending:
                self._process_entry(entry)
            new_pending = []
        if (
            self._spec_enabled
            # brownout level 2 (spec-off) falls back to plain decode
            # chunks — token-exact for greedy streams by the round-9
            # invariant, so in-flight work is never degraded in
            # correctness, only in weight-read amortization
            and not (self._brownout is not None and self._brownout.spec_off)
            and (new_pending or pending or any(s.active for s in self._slots))
        ):
            # self-speculation serializes the host loop on fetched results:
            # the next iteration's drafts must CONTINUE from the last
            # accepted token, which only the previous verify's (and this
            # iteration's prefill entries') fetch knows. Drain everything
            # before proposing — the conscious pipelining trade the verify
            # dispatch's k+1-tokens-per-weight-read amortization buys back
            # (docs/SERVING.md §10 has the tuning story).
            while pending:
                for entry in pending.popleft():
                    self._process_entry(entry)
            for entry in new_pending:
                self._process_entry(entry)
            new_pending = []
            if any(s.active for s in self._slots):
                new_pending.append(self._dispatch_verify(
                    clean=not prefill_ahead
                ))
                disp_kind, disp_steps = "verify", self.spec_tokens + 1
            else:
                disp_kind, disp_steps = "", 0
        elif any(s.active for s in self._slots):
            new_pending.append(self._dispatch_chunk(
                clean=not prefill_ahead,
                # a chunk dispatched while earlier chunks are still in
                # flight executes back-to-back with them on the in-order
                # stream — its step time is the inter-COMPLETION interval,
                # not dispatch→ready wall (which would double-count the
                # predecessor still running at dispatch time)
                pipelined=self._inflight_steps > 0,
            ))
            disp_kind, disp_steps = "decode", new_pending[-1][3]
        else:
            disp_kind, disp_steps = "", 0
            if not new_pending and not pending and not self._longs:
                time.sleep(0.001)
        t_dispatch = time.monotonic() if obs_on else 0.0
        pending.append(new_pending)
        # process the oldest batch when its device arrays are READY
        # (no host block, completions/first tokens discovered at
        # chunk granularity), or unconditionally once the pipeline
        # is full / nothing new was dispatched to overlap with
        while pending and (
            len(pending) > self.pipeline_depth
            or not new_pending
            or self._batch_ready(pending[0])
        ):
            for entry in pending.popleft():
                self._process_entry(entry)
        if obs_on and (disp_kind or n_admitted or spent or had_active):
            # flight-recorder frame — idle iterations (nothing active,
            # nothing dispatched) are skipped so the ring holds ~N frames
            # of actual WORK leading up to an incident, not sleep noise.
            # One dict build + deque append per iteration (not per token).
            t_end = time.monotonic()
            self._obs.flight.record({
                "i": self._iterations_total,
                "t": round(time.time(), 3),
                "active": sum(1 for s in self._slots if s.active),
                "queued": self._queue.qsize(),
                "longs": len(self._longs),
                "admitted": n_admitted,
                "prefill_tokens": prefill_tokens,
                "dispatch": disp_kind,
                "steps": disp_steps,
                "kv_pages": (
                    self._pagepool.pages_in_use if self._pagepool else 0
                ),
                # host-tier occupancy (tiered KV): arena slots holding
                # hibernated prefix pages; 0 with the tier off
                "host_pages": (
                    self._host_tier.slots_in_use if self._host_tier else 0
                ),
                "programs": len(self._programs),
                "injector": (
                    dict(self._injector.fired)
                    if self._injector is not None
                    else {}
                ),
                "phase_ms": {
                    "sweep": round((t_sweep - t0) * 1e3, 3),
                    "prefill": round((t_prefill - t_sweep) * 1e3, 3),
                    "dispatch": round((t_dispatch - t_prefill) * 1e3, 3),
                    "process": round((t_end - t_dispatch) * 1e3, 3),
                    # spill = this iteration's hibernation bookkeeping
                    # (snapshot dispatch + drain); restore = host→device
                    # upload time inside admissions. Both host-wall ms.
                    "spill": round(self._spill_ms_iter, 3),
                    "restore": round(self._restore_ms_iter, 3),
                },
            })

    def _sweep_waiting(self) -> None:
        """Resolve queued-but-unadmitted requests that died while waiting
        (cancelled, expired deadline/max-queue-wait) WITHOUT waiting for a
        slot to free: queue.Queue is opaque, so the sweep walks the shadow
        _waiting dict, the long-prompt backlog, and the held-back slot; a
        swept request's queue entry is skipped at pop time (_done already
        set). Bounded by the queue depth (≤ max_batch×4 by default), so
        this is noise next to a device dispatch."""
        now = time.monotonic()
        with self._waiting_lock:
            waiting = list(self._waiting.values())
        for request in waiting:
            if request._done.is_set() or self._resolve_if_dead(request, now):
                with self._waiting_lock:
                    self._waiting.pop(id(request), None)
        # the long-prompt backlog, page-deferred list + held-back slot are
        # engine-thread-only
        self._long_queue = [
            r for r in self._long_queue
            if not (r._done.is_set() or self._resolve_if_dead(r, now))
        ]
        self._page_deferred = [
            r for r in self._page_deferred
            if not (r._done.is_set() or self._resolve_if_dead(r, now))
        ]
        if self._held_back is not None and (
            self._held_back._done.is_set()
            or self._resolve_if_dead(self._held_back, now)
        ):
            self._held_back = None

    def _current_load_score(self) -> float:
        """The brownout controller's input: the §12 load-score formula
        over CURRENT signals. The wait term is the queue-wait EMA gated
        on an actual backlog — NOT the stats() histogram p90, which is
        cumulative and would hold the ladder engaged forever after one
        bad burst (the full-reversal contract), and not the bare EMA,
        which freezes at its last value the moment the queue empties."""
        backlog_wait = (
            self._queue_wait_ema_s if self._queue.qsize() > 0 else 0.0
        )
        pool = self._pagepool
        page_pressure = (
            pool.pages_in_use / max(1, pool.num_pages)
            if pool is not None
            else min(1.0, self._queue.qsize() / max(1, self._queue.maxsize))
        )
        occupancy = (
            sum(1 for s in self._slots if s.active) / max(1, self.max_batch)
        )
        return load_score(backlog_wait, occupancy, page_pressure)

    def _brownout_tick(self) -> None:
        """Advance the brownout ladder off the current load score
        (throttled — the p90 walk is cheap but not free at a ~1ms idle
        loop). A transition in EITHER direction is counted, logged and
        flight-dumped (`brownout` reason, debounced by the recorder) —
        the full reversal back to level 0 is part of the contract."""
        now = time.monotonic()
        if now - self._brownout_checked_at < 0.05:
            return
        self._brownout_checked_at = now
        transition = self._brownout.observe(self._current_load_score(), now)
        if transition is None:
            return
        old, new = transition
        snap = self._brownout.snapshot()
        log.warning(
            "brownout %s: level %d -> %d (step %s, load %.3f)",
            "escalated" if new > old else "released",
            old, new, snap["step"], snap["last-load"],
        )
        dumped = self._flight_dump("brownout", extra={
            "brownout-from": old,
            "brownout-to": new,
            "brownout-step": snap["step"],
            "load-score": snap["last-load"],
        })
        if dumped is not None:
            with self._stats_lock:
                self.brownout_dumps_total += 1

    BURST_TENANT = "chaos-burst"

    def _tenant_burst_tick(self) -> None:
        """`tenant-burst` fault site (docs/SERVING.md §19): when the
        schedule fires, enqueue a burst of synthetic low-priority
        admissions under the "chaos-burst" tenant — the deterministic
        aggressor of the noisy-neighbor drill. The burst takes the normal
        submit bookkeeping EXCEPT the blocking put (the engine thread
        must never park on its own full queue): full-queue/share
        rejections count as the aggressor's sheds, exactly what the drill
        asserts the victim never absorbs."""
        if not self._injector.fires("tenant-burst"):
            return
        for j in range(self.max_batch):
            prompt = [3 + (j % 5), 5, 7, 11, 13, 17, 19, 23]
            request = GenerationRequest(
                prompt_tokens=prompt,
                options=GenerationOptions(
                    max_new_tokens=16,
                    tenant=self.BURST_TENANT,
                    priority="low",
                ),
            )
            self._tenants.note_submit(self.BURST_TENANT)
            if self._draining:
                self._count_shed(self.BURST_TENANT)
                continue
            with self._waiting_lock:
                self._waiting[id(request)] = request
            try:
                self._queue.put_nowait(request)
            except (queue.Full, TenantShareExceeded):
                with self._waiting_lock:
                    self._waiting.pop(id(request), None)
                self._count_shed(self.BURST_TENANT)

    def _flush_row_resets(self) -> None:
        """Zero the KV rows of NaN-quarantined slots, coalesced into one
        row-reset dispatch per iteration. SPMD: the dispatch rides the
        wire (OP_ROW_RESET) so followers zero the same rows — victim-only
        quarantine holds on every host (docs/SERVING.md §14)."""
        stale = sorted(set(self._pending_row_resets))
        self._pending_row_resets.clear()
        if self._spmd is not None:
            self._spmd.announce(wire.ControlBlock(
                op=wire.OP_ROW_RESET, n_rows=len(stale),
                slots=np.asarray(stale, np.int32),
            ))
        self._dev_row_reset(stale)

    def _dev_row_reset(self, stale) -> None:
        """Device layer of the coalesced quarantine row zero (leader + SPMD
        followers): one fixed-shape traced-index dispatch, out-of-bounds
        padding rows drop."""
        idxs = np.full(self.max_batch, self.max_batch, np.int32)
        idxs[: len(stale)] = list(stale)
        self._record_program("row-reset")
        self._cache = _reset_rows(self._cache, jnp.asarray(idxs))

    @staticmethod
    def _batch_ready(batch: list[tuple]) -> bool:
        """True when every device array in the batch has materialized (the
        fetch would not block). Backends without is_ready() report ready —
        degrading to depth-1 behavior, never deadlock."""
        for entry in batch:
            handle = entry[1]
            if isinstance(handle, _Fetch):
                if handle.done:
                    continue  # fetch thread already landed the bytes
                handle = handle.array
            arr = handle
            is_ready = getattr(arr, "is_ready", None)
            if is_ready is None:
                continue
            try:
                if not is_ready():
                    return False
            except Exception:  # noqa: BLE001 — treat probe failure as ready
                continue
        return True

    def _fetch_result(self, handle):
        """Materialize one deferred fetch. Under SPMD with the watchdog
        armed, the wait is BOUNDED by ``spmd-watchdog-s``: a fetch that
        never lands (wedged device, hung tunnel) raises EngineWedgedError
        out of the iteration, and the supervisor escalates to the
        coordinated OP_RECOVER — a leader must never hang the whole slice
        on one dispatch (docs/SERVING.md §20). Single-host keeps the
        unbounded wait (a pod-local hang has pod-local blast radius)."""
        if not isinstance(handle, _Fetch):
            return np.asarray(jax.device_get(handle))
        bound = getattr(self._spmd, "watchdog_s", 0) if self._spmd else 0
        return handle.result(timeout_s=bound if bound > 0 else None)

    def _process_entry(self, entry: tuple) -> None:
        kind = entry[0]
        if kind == "prefill":
            # ONE fetch for the whole prefill group — per-request 1-token
            # fetches cost a full tunnel round trip each (~100ms); the
            # fetch thread has usually landed the bytes already
            _, first_dev, group = entry
            first = self._fetch_result(first_dev)
            now = time.monotonic()
            for j, (idx, request) in enumerate(group):
                slot = self._slots[idx]
                if slot.request is not request:
                    continue
                slot.first_token_at = now
                slot.last_token_at = now  # inter-token clock starts here
                if self._obs.on:
                    self._obs.record(
                        "engine_ttft_s", now - request.submitted_at
                    )
                # per-tenant TTFT (the noisy-neighbor drill's victim-p99
                # evidence — docs/SERVING.md §19); engine thread only,
                # the histogram single-writer contract
                self._tenants.note_ttft(
                    getattr(request.options, "tenant", None)
                    or DEFAULT_TENANT,
                    now - request.submitted_at,
                )
                self._deliver_token(idx, int(first[j]))
        elif kind == "verify":
            self._process_verify(entry)
        else:
            _, chunk, snapshot, steps, t_dispatch, clean, pipelined = entry
            self._process_chunk(
                chunk, snapshot, steps, t_dispatch, clean, pipelined
            )

    def _sample_step_time(
        self, snapshot, steps: int, t_dispatch: float, clean: bool,
        pipelined: bool,
    ) -> None:
        """Achieved-bandwidth gauge sample, taken the moment the chunk's
        bytes LAND (before token delivery: a request finishing mid-chunk
        wakes its waiter inside the delivery loop, and the gauge must
        already be current when that caller reads stats() — sampling after
        delivery both raced that read and charged host delivery work to
        device step time). Only CLEAN chunks (no prefill ahead on the
        stream that iteration) are sampled. A PIPELINED chunk (dispatched
        while its predecessor still ran) executes back-to-back on the
        in-order stream, so its device time is the interval since the
        PREVIOUS chunk's completion — dispatch→ready wall would count the
        predecessor's remaining execution too and read ~2× at steady
        state. A non-pipelined chunk (idle stream) uses dispatch→ready
        wall directly. EMA smooths tunnel jitter; the model side is
        _achieved_hbm_gbps."""
        now = time.monotonic()
        step_s = None
        if snapshot and clean:
            if pipelined and self._last_chunk_ready_t > 0:
                step_s = (now - self._last_chunk_ready_t) / max(1, steps)
            elif not pipelined:
                step_s = (now - t_dispatch) / max(1, steps)
        if step_s is not None:
            self._step_time_ema_s = (
                step_s
                if self._step_time_ema_s == 0
                else 0.9 * self._step_time_ema_s + 0.1 * step_s
            )
            if self._obs.on:
                # per-STEP device time — the EMA's distribution; a fat
                # p99 with a clean p50 is the mid-traffic-compile (or
                # tunnel-hiccup) signature §12 documents
                self._obs.record("engine_decode_step_s", step_s)
        self._last_chunk_ready_t = now

    def _bucket(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    @staticmethod
    def _expired(request: GenerationRequest, now: float) -> bool:
        opts = request.options
        wait = now - request.submitted_at
        return (
            opts.deadline_s is not None and wait >= opts.deadline_s
        ) or (
            opts.max_queue_wait_s is not None and wait > opts.max_queue_wait_s
        )

    def _resolve_if_dead(self, request: GenerationRequest, now: float) -> bool:
        """Resolve a queued-but-unadmitted request that died while waiting
        (client cancel, expired deadline / max-queue-wait) WITHOUT spending
        a slot or prefill FLOPs on it. True = resolved (or already done);
        the single place the cancelled/deadline-in-queue outcome is built,
        shared by the pop gate (_prequalify) and the expiry sweep across
        every backlog (short queue, long backlog, held-back slot)."""
        if request._done.is_set():
            return True  # already resolved elsewhere — don't double-count
        wait = now - request.submitted_at
        tenant = getattr(request.options, "tenant", None) or DEFAULT_TENANT
        if request.cancelled:
            with self._stats_lock:
                self.cancelled_total += 1
            self._tenants.note_cancelled(tenant)
            request._finish(GenerationResult(
                tokens=[], finish_reason="cancelled",
                prompt_tokens=len(request.prompt_tokens),
                ttft_s=0, total_s=wait,
            ))
            self._emit_queued_death_spans(request, "cancelled", now)
            return True
        if self._expired(request, now):
            opts = request.options
            with self._stats_lock:
                self.deadline_queue_total += 1
            self._tenants.note_deadline(tenant)
            request._finish(GenerationResult(
                tokens=[], finish_reason="deadline",
                prompt_tokens=len(request.prompt_tokens),
                ttft_s=0, total_s=wait,
                error=DeadlineExceededError(
                    f"request waited {wait:.2f}s in queue against "
                    f"deadline={opts.deadline_s} "
                    f"max-queue-wait={opts.max_queue_wait_s}"
                ),
            ))
            self._emit_queued_death_spans(request, "deadline", now)
            return True
        return False

    def _emit_queued_death_spans(
        self, request: GenerationRequest, reason: str, now: float
    ) -> None:
        """Trace a request that died before admission: root + queued child
        only (no slot, no prefill, no tokens)."""
        if not self._obs.on:
            return
        emit_request_spans(
            request.trace_id,
            {"submitted": request.submitted_at, "finished": now},
            {
                "slot": -1,
                "path": "queued",
                "prompt_len": len(request.prompt_tokens),
                "generated_tokens": 0,
                "finish_reason": reason,
            },
            status="ok" if reason == "cancelled" else f"error: {reason}",
        )

    def _prequalify(self, request: GenerationRequest) -> bool:
        """Queue-exit gate (engine thread): True = still worth admitting;
        live requests feed the queue-wait EMA that submit()'s
        hopeless-deadline shed reads."""
        now = time.monotonic()
        if self._resolve_if_dead(request, now):
            return False
        wait = now - request.submitted_at
        with self._stats_lock:
            self._queue_wait_ema_s = (
                wait
                if self._queue_wait_ema_s == 0
                else 0.8 * self._queue_wait_ema_s + 0.2 * wait
            )
        self._tenants.note_queue_wait(
            getattr(request.options, "tenant", None) or DEFAULT_TENANT, wait
        )
        if self._obs.on:
            # the DISTRIBUTION the EMA flattens: queue-wait p90 is the
            # dominant term of the load score the balancer routes on
            self._obs.record("engine_queue_wait_s", wait)
        return True

    # -- multi-LoRA + constrained decoding (the agentic tier, ISSUE 10) ------

    def _resolve_agentic(self, request: GenerationRequest) -> bool:
        """Resolve a request's adapter name and grammar to their device
        pool ROWS, refcounting both; idempotent (page-deferred admissions
        retry through here). Failure — unknown adapter, pinned-full pool —
        fails the REQUEST with the error, never the engine. Installs the
        request's _finalize hook so the refcounts release exactly once, on
        whatever path the request eventually finishes (completion, cancel,
        deadline, quarantine, crash recovery — they all funnel through
        _finish)."""
        if request._agentic_rows is not None:
            return True
        from langstream_tpu.serving.adapters import AdapterPoolExhausted

        opts = request.options
        adapter_name = getattr(opts, "adapter", None)
        arow, grow = 0, 0
        try:
            if adapter_name:
                arow = self._adapters.acquire(adapter_name)
            if request._dfa is not None:
                t0 = time.monotonic()
                try:
                    grow = self._constrain_reg.acquire(request._dfa)
                except Exception:
                    if adapter_name:
                        self._adapters.release(adapter_name)
                    raise
                self._note_constrain_host((time.monotonic() - t0) * 1e3)
                with self._stats_lock:
                    self.constrained_requests_total += 1
        except Exception as e:  # noqa: BLE001 — fail the request, not the loop
            log.warning("agentic resolution failed: %s", e)
            if isinstance(e, AdapterPoolExhausted) or (
                request._dfa is not None and "pinned" in str(e)
            ):
                # every row pinned by ACTIVE requests is a transient
                # saturation, not a client error: shed with a retry-after
                # (ShedError → HTTP 429; the front door's paced retries
                # will land once an in-flight tenant finishes) — the
                # contract the registries document
                self._count_shed(
                    getattr(opts, "tenant", None) or DEFAULT_TENANT
                )
                e = ShedError(
                    str(e),
                    retry_after_s=max(self._queue_wait_ema_s, 0.25),
                )
            request._finish(GenerationResult(
                tokens=[], finish_reason="error",
                prompt_tokens=len(request.prompt_tokens),
                ttft_s=0, total_s=0, error=e,
            ))
            return False
        state0 = 0
        if request._dfa is not None:
            resume = getattr(opts, "grammar_resume_state", None)
            if resume is not None:
                state0 = int(resume)
                if not (0 <= state0 < request._dfa.n_states):
                    # an out-of-range resume state means the carried wire
                    # state indexes a DIFFERENT grammar: continuing would
                    # emit off-grammar output dressed as valid — refuse
                    if adapter_name:
                        self._adapters.release(adapter_name)
                    self._constrain_reg.release(request._dfa)
                    request._finish(GenerationResult(
                        tokens=[], finish_reason="error",
                        prompt_tokens=len(request.prompt_tokens),
                        ttft_s=0, total_s=0,
                        error=ValueError(
                            f"grammar-resume-state {state0} is out of range "
                            f"for this grammar ({request._dfa.n_states} "
                            "states) — the resumed stream's grammar does "
                            "not match"
                        ),
                    ))
                    return False
        request._agentic_rows = (arow, grow, state0)

        def _release() -> None:
            if adapter_name:
                self._adapters.release(adapter_name)
            if request._dfa is not None:
                self._constrain_reg.release(request._dfa)

        request._finalize = _release
        return True

    def _slot_bind_agentic(self, idx: int, request: GenerationRequest) -> None:
        """Copy the request's resolved rows into the per-slot dispatch
        state at activation (the moment slot.request is set)."""
        arow, grow, state0 = request._agentic_rows or (0, 0, 0)
        if self._adapters is not None:
            self._adapter_rows[idx] = arow
            self._adapter_rows_auth[idx] = arow
            name = getattr(request.options, "adapter", None)
            if name:
                self._slot_adapter_name[idx] = name
        if self._constrain_reg is not None:
            self._g_rows[idx] = grow
            if request._dfa is not None:
                self._slot_dfa[idx] = request._dfa
                # a mid-derivation fleet resume starts at the carried
                # state, not 0 (§18) — host mirror and device state agree
                # because the admit programs seed their mask from state0
                self._dfa_host_state[idx] = state0
                request.dfa_state = state0

    def _slot_clear_agentic(self, idx: int) -> None:
        if self._adapters is not None:
            self._adapter_rows[idx] = 0
            self._adapter_rows_auth[idx] = 0
            self._slot_adapter_name.pop(idx, None)
        if self._constrain_reg is not None:
            self._g_rows[idx] = 0
            self._slot_dfa.pop(idx, None)
            self._dfa_host_state.pop(idx, None)

    def _note_constrain_host(self, ms: float) -> None:
        """EMA of host-side constrained-decoding bookkeeping (grammar
        residency swaps + per-verify state tables) — the `mask overhead`
        gauge's host half; the device half is what bench_adapters measures
        as the per-step on/off delta."""
        self._constrain_host_ema_ms = (
            ms
            if self._constrain_host_ema_ms == 0
            else 0.9 * self._constrain_host_ema_ms + 0.1 * ms
        )

    def _agentic_args(self) -> tuple:
        """(lora, arows, dfa, g) dispatch inputs. The [B] row arrays are
        host-uploaded per dispatch — tiny, and keeping them host-side is
        what makes the `adapter` fault site's integrity check possible
        (compare dispatch-facing vs authoritative before upload)."""
        lora = self._adapters.pool if self._adapters is not None else None
        arows = (
            jnp.asarray(self._adapter_rows)
            if self._adapters is not None
            else None
        )
        dfa = (
            self._constrain_reg.pool if self._constrain_reg is not None else None
        )
        g = (
            jnp.asarray(self._g_rows)
            if self._constrain_reg is not None
            else None
        )
        return lora, arows, dfa, g

    def _agentic_row_args(self, requests: list) -> tuple:
        """Per-ROW (not per-slot) adapter/grammar row + initial-DFA-state
        vectors for a batched admission: entry j serves requests[j];
        padding rows ride as base (state 0)."""
        if not self._agentic:
            return None, None, None
        n = self.prefill_batch
        arows = np.zeros(n, np.int32)
        g_rows = np.zeros(n, np.int32)
        g_state0 = np.zeros(n, np.int32)
        for j, request in enumerate(requests[:n]):
            ar, gr, s0 = (
                (request._agentic_rows or (0, 0, 0)) if request else (0, 0, 0)
            )
            arows[j] = ar
            g_rows[j] = gr
            g_state0[j] = s0
        return arows, g_rows, g_state0

    def _adapter_integrity_check(self) -> None:
        """Validate every active slot's dispatch-facing adapter row against
        the authoritative copy before a decode/verify dispatch — the
        `adapter` fault site's detection path (host memory corruption or a
        bookkeeping bug would otherwise serve slot X with tenant Y's
        weights, the worst kind of silent wrong). A mismatch quarantines
        ONLY that slot; every other slot's tokens stay exact (the chaos
        suite asserts both)."""
        if self._adapters is None:
            return
        if self._injector is not None:
            snapshot = [
                (i, s.request) for i, s in enumerate(self._slots) if s.active
            ]
            self._injector.corrupt_adapter_rows(self._adapter_rows, snapshot)
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            if self._adapter_rows[i] == self._adapter_rows_auth[i]:
                continue
            with self._stats_lock:
                self.quarantined_slots_total += 1
            # restore the dispatch-facing row before anything dispatches
            self._adapter_rows[i] = self._adapter_rows_auth[i]
            if self._paged:
                self._quarantine_pages(i)
            else:
                self._pending_row_resets.append(i)
            self._flight_dump("adapter-quarantine", extra={"slot": i})
            self._finish_slot(
                i, "error",
                error=RuntimeError(
                    f"adapter-row corruption detected for slot {i}; slot "
                    "quarantined"
                ),
            )

    def _warmup_agentic(self) -> None:
        """Warm the adapter/grammar row-upload programs with out-of-bounds
        rows (every write drops) so the first hot swap under traffic is
        never a mid-traffic compile — the same front-load-the-compiles
        policy as every other warmup."""
        if self._adapters is not None:
            self._adapters.warmup()
        if self._constrain_reg is not None:
            self._constrain_reg.warmup()

    def adapter_advertisement(self) -> tuple[str, ...]:
        """Resident adapter names for the fleet beacon (serving/fleet.py):
        the router scores adapter affinity alongside prefix affinity —
        routing a tenant's request to a replica already holding its
        factors skips a swap dispatch. Names only, never weights."""
        if self._adapters is None:
            return ()
        return self._adapters.advertised()

    def register_adapter(self, spec) -> None:
        """Hot-register an adapter through the control plane (no device
        work until its first request). Thread-safety note: registration
        mutates host bookkeeping the engine thread reads — call while the
        engine serves only OTHER adapters' traffic, or quiesce first."""
        if self._adapters is None:
            raise RuntimeError("this engine has no adapter registry")
        self._adapters.register(spec)

    def _admit(self, budget: Optional[int] = None) -> list[tuple]:
        """Move queued requests into free slots (prefill path); returns ALL
        the deferred first-token fetch entries. Nothing is fetched here —
        entries ride the ready-gated pending pipeline in _run (under active
        decode) or are processed immediately by _run's cold-start branch,
        which delivers a burst's groups progressively (group j's fetch
        overlaps group j+1's device compute since dispatches are async).

        Prefills are BATCHED per prompt bucket: admitting K requests costs
        one forward at batch K (memory-bound: ~the cost of batch 1), not K
        serial dispatches — serial prefill dominated wall-clock when a burst
        filled a large slot pool. Prompts wider than the largest bucket take
        the chunked-prefill path instead (_long_step).

        ``budget``: fused-scheduling token cap for THIS iteration, floored
        at one full admission group. The first group always rides whole (an
        arrival's prefill must make the very next dispatch, and a
        ≤prefill_batch burst still lands in ONE dispatch — the r4
        wave-admission win); past both the budget and a group boundary,
        further queued requests stay queued so the decode chunk dispatched
        right after is never separated from its predecessor by more than
        ~max(budget, one group) of prefill work. None = unbounded
        (overlap off)."""
        free = [
            i
            for i, slot in enumerate(self._slots)
            if not slot.active and i not in self._reserved
        ]
        pairs: list[tuple[int, GenerationRequest]] = []
        admitted_tokens = 0
        short_limit = self.prefill_buckets[-1]
        # page exhaustion gate, sampled ONCE per iteration: while deferred
        # admissions wait for pool pages, only they retry — the queue keeps
        # its entries (and its submit()-side backpressure/shedding)
        allow_new = not (self._paged and self._page_deferred)
        # fair-share slot division (docs/SERVING.md §19): tenants admitted
        # THIS call count toward their share immediately, so one pop loop
        # cannot hand a bursting tenant every free slot before the skip
        # set notices
        pending_counts: dict[str, int] = {}
        tenant_occupancy = self._tenant_occupancy()
        # a held-back long request gets first claim on freed backlog space
        if (
            self._held_back is not None
            and len(self._long_queue) < self._long_queue_cap
        ):
            self._long_queue.append(self._held_back)
            self._held_back = None
        for idx in free:
            got_short = False
            while not got_short and self._held_back is None:
                # budget gate, FLOORED at one full admission group: a burst
                # ≤ prefill_batch still lands in ONE dispatch (the r4 wave-
                # admission win — budgeting per-request serialized a 4-wave
                # into 4 iterations and REGRESSED TTFT when first tried);
                # past both the budget and a group boundary, the rest stays
                # queued for the next fused iteration
                if (
                    budget is not None
                    and admitted_tokens >= budget
                    and len(pairs) >= self.prefill_batch
                ):
                    break
                try:
                    request = self._pop_admission(
                        allow_new,
                        skip=self._tenant_slot_skip(
                            tenant_occupancy, pending_counts
                        ),
                    )
                except queue.Empty:
                    break
                req_tenant = (
                    getattr(request.options, "tenant", None) or DEFAULT_TENANT
                )
                with self._waiting_lock:
                    self._waiting.pop(id(request), None)
                if request._done.is_set():
                    continue  # already resolved by the expiry sweep
                if not self._prequalify(request):
                    continue  # resolved in queue (cancelled / deadline)
                if len(request.prompt_tokens) > short_limit:
                    # chunked-prefill path — but keep it bounded so submit()'s
                    # queue-full backpressure still engages under sustained
                    # long-prompt traffic (otherwise memory grows unbounded)
                    if len(self._long_queue) >= self._long_queue_cap:
                        self._held_back = request
                        break
                    self._long_queue.append(request)
                    pending_counts[req_tenant] = (
                        pending_counts.get(req_tenant, 0) + 1
                    )
                elif self._agentic and not self._resolve_agentic(request):
                    continue  # unknown adapter / pinned-full pool: resolved
                else:
                    pairs.append((idx, request))
                    admitted_tokens += self._bucket(len(request.prompt_tokens))
                    pending_counts[req_tenant] = (
                        pending_counts.get(req_tenant, 0) + 1
                    )
                    got_short = True
            if not got_short:
                break
        if not pairs:
            return []
        entries: list[tuple] = []
        # paged: reserve every admission's worst-case pages up front (defer
        # on exhaustion — never corrupt) and peel prefix-ALIAS hits off to
        # their one-dispatch warm path; the rest take the batched cold
        # admission below with their pages already bound
        if self._paged:
            cold_paged: list[tuple[int, GenerationRequest]] = []
            for idx, request in pairs:
                if self._paged_admit_one(idx, request, entries) == "cold":
                    cold_paged.append((idx, request))
            pairs = cold_paged
        # prefix reuse (dense): peel off requests whose longest cached prefix
        # can be extended in place (gather + suffix-only segment prefill);
        # the rest take the batched cold admission below
        if self._prefix_pool is not None:
            cold: list[tuple[int, GenerationRequest]] = []
            for idx, request in pairs:
                # an adapter tenant's prefix KV carries its wk/wv deltas —
                # never publish it under the shared trie, never reuse the
                # base trie for it (same rule on the paged alias path)
                hit = (
                    None
                    if getattr(request.options, "adapter", None)
                    else self._prefix_lookup(request.prompt_tokens)
                )
                if hit is not None:
                    entries.extend(self._prefill_prefix(idx, request, *hit))
                else:
                    cold.append((idx, request))
            pairs = cold
        groups: dict[int, list[tuple[int, GenerationRequest]]] = {}
        for idx, request in pairs:
            width = self._bucket(len(request.prompt_tokens))
            groups.setdefault(width, []).append((idx, request))
        for width, group in sorted(groups.items()):
            # fixed sub-batch size: each distinct (batch, width) shape is a
            # separate XLA compile (expensive through a TPU tunnel), so every
            # prefill call uses exactly prefill_batch rows
            for start in range(0, len(group), self.prefill_batch):
                sub = group[start : start + self.prefill_batch]
                try:
                    new = self._prefill_group(width, sub)
                except Exception as e:  # noqa: BLE001 — fail the group, not the engine
                    if self._spmd is not None:
                        # multi-host: an announced dispatch that failed here
                        # may have diverged (or killed) the followers —
                        # catch-and-continue would wedge every collective.
                        # Raise: the supervisor escalates to the coordinated
                        # OP_RECOVER (both sides rebuild in place, §20).
                        raise
                    log.exception("prefill failed for a batch of %d requests", len(sub))
                    for idx, request in sub:
                        if self._paged:
                            self._free_slot_pages(idx)  # reserved at admit
                        request._finish(GenerationResult(
                            tokens=[], finish_reason="error", prompt_tokens=0,
                            ttft_s=0, total_s=0, error=e,
                        ))
                    continue
                # NEVER fetch here: blocking on a group's first tokens waits
                # out the in-flight decode chunk with the engine thread
                # stalled, so the next chunk dispatches late and the device
                # idles (measured: admit fetches ate ~30% of steady-state
                # wall at B=96). Entries ride the same ready-gated pending
                # pipeline as decode chunks; on a cold start _run processes
                # them immediately (progressive group-by-group delivery).
                entries.extend(new)
        return entries

    def _prefill_group(
        self, width: int, group: list[tuple[int, GenerationRequest]]
    ) -> list[tuple]:
        """One batched prefill for every (slot, request) pair of one prompt
        bucket; always padded to prefill_batch rows (single compiled shape
        per width bucket)."""
        n_pad = self.prefill_batch
        assert len(group) <= n_pad
        tokens = np.zeros((n_pad, width), np.int32)
        lengths = np.ones(n_pad, np.int32)
        temps = np.zeros(n_pad, np.float32)
        top_ks = np.zeros(n_pad, np.int32)
        top_ps = np.ones(n_pad, np.float32)
        started = time.monotonic()
        for j, (_, request) in enumerate(group):
            prompt = request.prompt_tokens
            tokens[j, : len(prompt)] = prompt
            lengths[j] = len(prompt)
            temps[j] = request.options.temperature
            top_ks[j] = request.options.top_k
            top_ps[j] = request.options.top_p

        # one scatter for the whole group; padding rows point out of bounds
        # and are dropped
        slots = np.full(n_pad, self.max_batch, np.int32)
        for j, (idx, _) in enumerate(group):
            slots[j] = idx
        if self._spmd is not None:
            self._spmd.announce(wire.ControlBlock(
                op=wire.OP_PREFILL, width=width, n_rows=n_pad, tokens=tokens,
                lengths=lengths, slots=slots, temps=temps, top_ks=top_ks,
                top_ps=top_ps,
            ))
        arows, g_rows, g_state0 = self._agentic_row_args(
            [r for _, r in group]
        )
        first = self._dev_prefill(
            width, tokens, lengths, temps, top_ks, top_ps, slots,
            arows=arows, g_rows=g_rows, g_state0=g_state0,
        )
        if self._obs.on:
            self._obs.record(
                "engine_prefill_dispatch_s", time.monotonic() - started
            )
        self._prefill_tokens_dispatched += sum(
            len(r.prompt_tokens) for _, r in group
        )

        for idx, request in group:
            slot = self._slots[idx]
            slot.request = request
            slot.position = len(request.prompt_tokens)  # next write position
            slot.generated = []
            slot.started_at = started
            slot.first_token_at = 0.0  # stamped when the deferred fetch lands
            slot.reset_obs("cold", 1)
            self._slot_bind_agentic(idx, request)
            with self._stats_lock:
                self.total_requests += 1
            self._note_tenant_admitted(request)
            self._spec_admit(idx, request.prompt_tokens)
            self._maybe_publish(idx, request.prompt_tokens)
        return [("prefill", self._fetcher.submit(first), list(group))]

    def _agentic_admit_kwargs(
        self, n: int, arows, g_rows, g_state0=None,
    ) -> dict:
        """Keyword args the admit-group programs take when the agentic
        tier is on — zeros (base rows) for warmups and padding. Empty dict
        when off, so legacy engines trace the exact pre-ISSUE-10 programs.
        ``g_state0``: per-row initial DFA states (zeros except for
        mid-derivation fleet resumes, §18)."""
        kw: dict[str, Any] = {}
        if self._adapters is not None:
            kw["lora"] = self._adapters.pool
            kw["arows"] = jnp.asarray(
                arows if arows is not None else np.zeros(n, np.int32)
            )
        if self._constrain_reg is not None:
            kw["dfa"] = self._constrain_reg.pool
            kw["g_rows"] = jnp.asarray(
                g_rows if g_rows is not None else np.zeros(n, np.int32)
            )
            kw["state_dev"] = self._dfa_state_dev
            kw["g_state0"] = jnp.asarray(
                g_state0 if g_state0 is not None else np.zeros(n, np.int32)
            )
        return kw

    def _dev_prefill(
        self, width, tokens, lengths, temps, top_ks, top_ps, slots,
        arows=None, g_rows=None, g_state0=None,
    ):
        """Device layer of a batched prefill — runs IDENTICALLY on the
        leader and (via follower_loop) every SPMD follower, so the sharded
        cache and decode chain evolve in lockstep from pure host inputs.
        (Agentic args never appear under SPMD — the tier is construction-
        disabled on multi-host replicas, so the wire needs no new ops.)"""
        if self._injector is not None:
            self._injector.fire("prefill")  # before any state mutates
        n = len(tokens)
        assert all(len(a) == n for a in (lengths, temps, top_ks, top_ps, slots))
        if self._paged:
            return self._dev_paged_prefill(
                tokens, lengths, temps, top_ks, top_ps, slots,
                arows=arows, g_rows=g_rows, g_state0=g_state0,
            )
        self._record_program("prefill", tokens.shape[1], n)
        # pack the per-row scalars into one upload (per-op tunnel latency)
        meta = np.stack([lengths, temps, top_ks, top_ps]).astype(np.float32)
        kw = self._agentic_admit_kwargs(n, arows, g_rows, g_state0)
        (
            first,
            self._cache,
            self._tokens_dev,
            self._positions_dev,
            self._temp_dev,
            self._top_k_dev,
            self._top_p_dev,
            self._key,
            state_dev,
        ) = self._admit_group(
            self.params,
            self._cache,
            self._tokens_dev,
            self._positions_dev,
            self._temp_dev,
            self._top_k_dev,
            self._top_p_dev,
            self._key,
            jnp.asarray(tokens),
            jnp.asarray(meta),
            jnp.asarray(slots),
            self.config,
            **kw,
        )
        if state_dev is not None:
            self._dfa_state_dev = state_dev
        return first

    def _dev_paged_prefill(
        self, tokens, lengths, temps, top_ks, top_ps, slots,
        arows=None, g_rows=None, g_state0=None,
    ):
        """Paged device layer of a batched cold prefill: the SAME fused
        local-cache forward as the dense admit group (token-exactness), but
        the insert scatters into each row's reserved pages. Rows whose slot
        is out of bounds (padding, warmups) carry an all-sentinel table —
        every write drops."""
        pool = self._pagepool
        n = len(tokens)
        tables = np.full((n, pool.table_len), pool.oob, np.int32)
        for j, s in enumerate(slots):
            if 0 <= s < self.max_batch:
                tables[j] = pool.tables[s]
        self._record_program("paged-prefill", tokens.shape[1], n)
        meta = np.stack([lengths, temps, top_ks, top_ps]).astype(np.float32)
        kw = self._agentic_admit_kwargs(n, arows, g_rows, g_state0)
        (
            first,
            pool.dev,
            self._tokens_dev,
            self._positions_dev,
            self._temp_dev,
            self._top_k_dev,
            self._top_p_dev,
            self._key,
            state_dev,
        ) = self._paged_admit_group(
            self.params,
            pool.dev,
            self._tokens_dev,
            self._positions_dev,
            self._temp_dev,
            self._top_k_dev,
            self._top_p_dev,
            self._key,
            jnp.asarray(tokens),
            jnp.asarray(meta),
            jnp.asarray(slots),
            jnp.asarray(tables),
            self.config,
            self.page_size,
            **kw,
        )
        if state_dev is not None:
            self._dfa_state_dev = state_dev
        return first

    # -- prefix KV reuse -----------------------------------------------------

    def _prefix_lookup(
        self, prompt: list[int], full_width_only: bool = False
    ) -> Optional[tuple]:
        """Longest usable cached prefix for this prompt as ``(p, entry)``,
        recording the lookup in the pool's hit-rate stats. ``p`` may be
        SHORTER than the entry (reusing the first p columns of a deeper
        prefix). Short path: reject lengths where the suffix segment's
        bucket padding would overhang the pool-width local cache (the
        clamp-scatter would corrupt the last real column). Long path
        (``full_width_only``): only a full-segment-width prefix keeps the
        chunked-prefill segment grid aligned with the local cache."""
        pool = self._prefix_pool
        assert pool is not None
        best = None
        for p, entry in pool.candidates(prompt):  # ascending by p
            if full_width_only:
                if p == pool.width:
                    best = (p, entry)
            elif p + self._bucket(len(prompt) - p) <= pool.width:
                best = (p, entry)
        pool.record_lookup(best[1] if best else None)
        return best

    def _prefill_prefix(
        self, idx: int, request: GenerationRequest, p: int, entry
    ) -> list[tuple]:
        """Warm admission: gather the cached prefix (pool row → pool-width
        local cache), prefill ONLY the suffix as one segment at offset
        ``p``, insert, and scatter the decode chain — the cold path minus
        the prefix's prefill FLOPs and cache writes. The entry is pinned
        for the span of the dispatch so eviction can never hand its row to
        a concurrent publish mid-read."""
        pool = self._prefix_pool
        prompt = request.prompt_tokens
        suffix = prompt[p:]
        ws = self._bucket(len(suffix))
        t_pool = pool.width
        # static pow2-multiple cap on readable columns, same ladder as the
        # chunked-prefill segments: the suffix never attends past p + ws
        kv_bound = ws
        while kv_bound < min(p + ws, t_pool):
            kv_bound *= 2
        kv_bound = min(kv_bound, t_pool)
        tokens = np.zeros((1, ws), np.int32)
        tokens[0, : len(suffix)] = suffix
        opts = request.options
        started = time.monotonic()
        pool.acquire(entry)
        if self._spmd is not None:
            # warm admission on the wire: the follower replays the same
            # gather(entry.row) + suffix segment + insert + chain scatter
            # (the radix lookup that CHOSE the entry stays leader-only)
            self._spmd.announce(wire.ControlBlock(
                op=wire.OP_PREFIX_ADMIT, width=ws, n_rows=1, tokens=tokens,
                s0=p, seg_len=len(suffix), kv_bound=kv_bound,
                entry_row=entry.row, long_idx=idx,
                temps=np.asarray([opts.temperature], np.float32),
                top_ks=np.asarray([opts.top_k], np.int32),
                top_ps=np.asarray([opts.top_p], np.float32),
            ))
        try:
            first = self._dev_prefix_admit(
                tokens, p, len(suffix), kv_bound, entry.row,
                opts.temperature, opts.top_k, opts.top_p, idx,
                agentic_rows=request._agentic_rows,
            )
        except Exception as e:  # noqa: BLE001 — fail the request, not the engine
            if self._spmd is not None:
                raise  # multi-host: crash the replica (see _admit rationale)
            log.exception("prefix-reuse prefill failed (p=%d)", p)
            request._finish(GenerationResult(
                tokens=[], finish_reason="error", prompt_tokens=0,
                ttft_s=0, total_s=0, error=e,
            ))
            return []
        finally:
            pool.release(entry)
        pool.tokens_saved += p
        if self._obs.on:
            self._obs.record(
                "engine_prefill_dispatch_s", time.monotonic() - started
            )
        self._prefill_tokens_dispatched += len(suffix)
        slot = self._slots[idx]
        slot.request = request
        slot.position = len(prompt)
        slot.generated = []
        slot.started_at = started
        slot.first_token_at = 0.0
        slot.reset_obs("warm", 1)
        self._slot_bind_agentic(idx, request)
        with self._stats_lock:
            self.total_requests += 1
        self._note_tenant_admitted(request)
        self._spec_admit(idx, prompt)
        # the prompt may extend past the reused prefix's bucket boundary:
        # publish the deeper prefix so the next lookup reuses more
        self._maybe_publish(idx, prompt)
        return [("prefill", self._fetcher.submit(first), [(idx, request)])]

    def _segment_agentic_kwargs(self, agentic_rows, state_slot) -> dict:
        """Agentic kwargs for the batch-1 segment programs (warm suffix /
        long-prompt chunks). ``state_slot`` out of bounds (non-final
        segments, warmups) drops the DFA state scatter. The request's
        initial DFA state (the _agentic_rows triple) seeds the first-token
        mask — nonzero only on a mid-derivation fleet resume (§18)."""
        kw: dict[str, Any] = {}
        arow, grow, state0 = agentic_rows or (0, 0, 0)
        if self._adapters is not None:
            kw["lora"] = self._adapters.pool
            kw["arows"] = jnp.asarray([arow], jnp.int32)
        if self._constrain_reg is not None:
            kw["dfa"] = self._constrain_reg.pool
            kw["g"] = jnp.asarray([grow], jnp.int32)
            kw["state_dev"] = self._dfa_state_dev
            kw["state_slot"] = jnp.asarray(state_slot, jnp.int32)
            kw["state0"] = jnp.asarray([state0], jnp.int32)
        return kw

    def _dev_prefix_admit(
        self, tokens, offset, seg_len, kv_bound, entry_row,
        temperature, top_k, top_p, idx, agentic_rows=None,
    ):
        """Device layer of a warm admission: prefix gather + suffix segment
        + big-cache insert + decode-chain scatters. The segment and insert
        programs are the SAME shapes the chunked-prefill path compiles
        (local width = pool width = the largest bucket), so reuse adds only
        the gather/publish pair to the program surface."""
        from langstream_tpu.ops.kvcopy import gather_prefix_local

        pool = self._prefix_pool
        t_pool = pool.width
        self._record_program("prefix-gather", t_pool)
        local = gather_prefix_local(
            pool.dev, jnp.asarray(entry_row, jnp.int32), self.config, t_pool
        )
        if self.mesh is not None:
            from langstream_tpu.parallel.sharding import shard_serving_cache

            local = shard_serving_cache(local, self.mesh)
        self._record_program("segment", tokens.shape[1], kv_bound, t_pool)
        kw = self._segment_agentic_kwargs(agentic_rows, idx)
        first, local, self._key, state_dev = _prefill_segment_and_sample(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray([offset], jnp.int32),
            jnp.asarray([seg_len], jnp.int32),
            local,
            self._key,
            jnp.asarray([temperature], jnp.float32),
            jnp.asarray([top_k], jnp.int32),
            jnp.asarray([top_p], jnp.float32),
            self.config,
            kv_bound,
            **kw,
        )
        if state_dev is not None:
            self._dfa_state_dev = state_dev
        self._record_program("insert", t_pool)
        self._cache = self._insert_group(
            self._cache, local, jnp.asarray(np.full(1, idx, np.int32))
        )
        self._record_program("chain-scatter")
        (
            self._tokens_dev, self._positions_dev, self._temp_dev,
            self._top_k_dev, self._top_p_dev,
        ) = _chain_scatter(
            self._tokens_dev, self._positions_dev, self._temp_dev,
            self._top_k_dev, self._top_p_dev,
            jnp.asarray(idx, jnp.int32), first, offset + seg_len,
            temperature, top_k, top_p,
        )
        return first

    # -- paged admission / prefix aliasing -----------------------------------

    def _pop_admission(
        self, allow_new: bool = True, skip: Optional[set] = None,
    ) -> GenerationRequest:
        """Admission source for _admit: page-deferred requests (popped
        earlier, waiting for pool pages) retry ahead of the queue so
        allocator pressure never reorders them behind newer arrivals.
        ``allow_new=False`` (set while deferred admissions wait) stops
        draining the queue — the deferred list must stay bounded so the
        bounded queue keeps backpressuring submit() during exhaustion
        instead of silently absorbing the backlog host-side. ``skip``:
        tenants held back this pop (at their slot cap / fair share) —
        forwarded to the tenant queue's DRR, never applied to deferred
        retries (those already own a pop)."""
        if self._page_deferred:
            return self._page_deferred.pop(0)
        if not allow_new:
            raise queue.Empty
        return self._queue.get_nowait(skip=skip)

    def _tenant_occupancy(self) -> dict[str, int]:
        """Active-slot + long-prefill-stream counts by tenant. Computed
        ONCE per _admit call (slot occupancy cannot change inside it —
        slots activate after the pop loop); per-pop deltas ride the
        caller's pending_counts."""
        active: dict[str, int] = {}

        def _bump(req) -> None:
            t = getattr(req.options, "tenant", None) or DEFAULT_TENANT
            active[t] = active.get(t, 0) + 1

        for s in self._slots:
            if s.active:
                _bump(s.request)
        for st in self._longs.values():
            r = st.get("request")
            if r is not None:
                _bump(r)
        return active

    def _tenant_slot_skip(
        self, occupancy: dict[str, int], pending_counts: dict[str, int],
    ) -> set:
        """Tenants that must NOT claim another free slot right now: at
        their configured ``max_slots`` hard cap, or at their weighted fair
        share of the slot pool while OTHER tenants have queued work. Fair
        share = max_batch × weight / Σweights over the contending set —
        the "a bursting tenant can never exceed its weight when others
        are waiting" rule. Work-conserving both ways: a single tenant is
        never capped by fairness, and when EVERY waiting tenant would be
        fair-capped with slots still free, the caps relax (hard max_slots
        never does). Engine thread only."""
        waiting = self._queue.tenants_with_work()
        if not waiting:
            return set()
        active: dict[str, int] = dict(occupancy)
        for t, n in pending_counts.items():
            active[t] = active.get(t, 0) + n
        hard: set = set()
        fair_skip: set = set()
        contending = set(waiting) | {t for t, n in active.items() if n}
        multi = len(contending) > 1
        total_w = sum(self._tenants.weight(t) for t in contending) or 1.0
        for t in waiting:
            spec = self._tenants.state(t).spec
            n = active.get(t, 0)
            if spec.max_slots is not None and n >= spec.max_slots:
                hard.add(t)
                continue
            if multi:
                fair = max(
                    1,
                    round(self.max_batch * self._tenants.weight(t) / total_w),
                )
                if n >= fair:
                    fair_skip.add(t)
        if fair_skip and set(waiting) <= (fair_skip | hard):
            # everyone waiting is fair-capped yet slots are free: borrow
            fair_skip = set()
        return fair_skip | hard

    def _paged_bind(self, idx: int, request: GenerationRequest) -> Optional[int]:
        """Reserve slot ``idx``'s worst-case pages, aliasing the deepest
        cached prefix when the index has one: full prefix pages join the
        table by refcount bump (ZERO copies), a mid-page prefix tail gets
        one copy-on-write page copy. Under pool pressure the LRU unpinned
        prefix entries make room first. Returns the reuse offset (0 = cold
        miss) or None — slot untouched — when the pool cannot cover the
        reservation (the caller defers; exhaustion sheds upstream, it never
        corrupts). Shared by the short-admission and long-prompt paths so
        the alias/COW/eviction rules cannot drift between them."""
        pool, index = self._pagepool, self._prefix_index
        prompt = request.prompt_tokens
        # reserve only what the request can actually write: a
        # max_cost_tokens budget below max_new_tokens shrinks the
        # worst-case page reservation too (§19)
        need = pool.pages_needed(
            len(prompt),
            max(1, effective_max_new_tokens(request.options, len(prompt))),
        )
        if need > pool.num_pages:
            # only reachable with an explicit kv-pages override below the
            # per-slot worst case: deferring would hang forever, so fail
            # loudly with the sizing arithmetic
            request._finish(GenerationResult(
                tokens=[], finish_reason="error",
                prompt_tokens=len(prompt), ttft_s=0, total_s=0,
                error=ShedError(
                    f"request needs {need} KV pages but the pool has only "
                    f"{pool.num_pages}; raise kv-pages (or lower "
                    "max-new-tokens)"
                ),
            ))
            return -1  # handled — nothing reserved
        hit = None
        if index is not None and not getattr(request.options, "adapter", None):
            # adapter tenants never alias the shared base-prefix pages —
            # their prompt KV includes the wk/wv adapter deltas. Deepest
            # usable candidate wins; a HIBERNATED candidate (host tier,
            # no device pages) is restored in place — the whole point of
            # the tier: a radix hit on a spilled session is a DMA upload,
            # not a miss. A failed restore (checksum/fault/no room) falls
            # back to the next-shallower candidate, then to recompute.
            failed_restores = 0
            counted = getattr(request, "_tier_fallback_counted", False)
            for p_cand, cand in reversed(index.candidates(prompt)):
                if cand.dropped:
                    # a deeper candidate's _restore_entry can evict_for a
                    # SHALLOWER candidate out of this already-materialized
                    # list — the dropped entry is stale, not a hit
                    continue
                if cand.pages:
                    hit = (p_cand, cand)
                    break
                if self._restore_entry(
                    cand, p_cand, count_failures=not counted
                ):
                    hit = (p_cand, cand)
                    request._tier_restored = True
                    break
                failed_restores += 1
            if failed_restores:
                # failure gauges count once per REQUEST: a page-deferred
                # request re-runs this loop every engine iteration, and a
                # full-pool stall must not read as thousands of failed
                # restores. The recompute-fallback side of the health
                # gauge is decided at BIND time below — a deferral is not
                # a cold ending (its retry may restore and must not land
                # on both sides of the restore-vs-recompute split)
                request._tier_fallback_counted = True
            if hit is None and self._durable is not None:
                # third rung of the ladder (§23): nothing live covered the
                # prompt — resurrect from the durable store if a checkpoint
                # does. Any failure degrades to cold prefill right here.
                hit = self._durable_admit(request, prompt)
                if hit is not None:
                    request._tier_restored = True
        shared: tuple[int, ...] = ()
        cow_src = None
        p, entry = 0, None
        if hit is not None:
            p, entry = hit
            full = p // self.page_size
            shared = tuple(entry.pages[:full])
            if p % self.page_size:
                cow_src = entry.pages[full]
            index.acquire(entry)  # pinned: eviction below must not free it
        try:
            want_fresh = need - len(shared)
            if pool.free_pages < want_fresh and index is not None:
                # tiered KV: victims DEMOTE to their host copy when one is
                # secured (spill_cb) — the device pool is a cache over the
                # host tier, and eviction stops costing re-prefills
                index.evict_for(
                    pool, want_fresh,
                    spill_cb=self._ensure_spilled if self._spill_on else None,
                )
            cow_dst = pool.reserve(idx, need, shared)
            if cow_dst is None:
                return None
            # the restore-vs-recompute health gauge is decided HERE, at
            # bind time, once per request and on exactly one side: a
            # deferral is neither outcome (its retry decides), and a
            # full-pool restore/demote cycle across retries must not
            # count one admission as several restores
            if (
                hit is not None
                and getattr(request, "_tier_restored", False)
                and not getattr(request, "_tier_restored_counted", False)
            ):
                self.restored_hits_total += 1
                request._tier_restored_counted = True
            elif (
                hit is None
                and getattr(request, "_tier_fallback_counted", False)
                and not getattr(request, "_tier_recompute_counted", False)
            ):
                # binds COLD after ≥1 failed restore: a recompute
                # fallback — a shallower device-resident candidate
                # serving the hit warm is not one
                self.recompute_fallbacks_total += 1
                request._tier_recompute_counted = True
            if self._spmd is not None:
                # the reservation RESULT rides the wire: followers bind the
                # same physical pages to the same slot table (aliased
                # prefix pages included) and make the same COW copy — the
                # free list / refcounts / prefix index stay leader-only
                owned = pool.slot_pages(idx)
                self._spmd.announce(wire.ControlBlock(
                    op=wire.OP_PAGE_BIND, long_idx=idx, count=len(owned),
                    pages=np.asarray(owned, np.int32),
                    cow_src=cow_src if cow_src is not None else -1,
                    cow_dst=cow_dst if cow_src is not None else -1,
                ))
            if index is not None:
                index.record_lookup(entry)
            if entry is None:
                return 0
            if cow_src is not None:
                self._record_program("page-copy")
                pool.dev = _page_copy(
                    pool.dev,
                    jnp.asarray(cow_src, jnp.int32),
                    jnp.asarray(cow_dst, jnp.int32),
                )
            index.tokens_saved += p
            token_bytes = pool.bytes_per_page / self.page_size
            saved = int(p * token_bytes) - (
                pool.bytes_per_page if cow_src is not None else 0
            )
            index.copy_bytes_saved += max(saved, 0)
            return p
        finally:
            if entry is not None:
                index.release(entry)

    def _paged_admit_one(self, idx: int, request: GenerationRequest,
                         entries: list) -> str:
        """Reserve pages and route one short admission in paged mode.
        Returns "cold" (pages bound — join the batched group prefill),
        "warm" (prefix alias hit — dispatched here, fetch entry appended),
        or "deferred" (pool exhausted even after LRU prefix eviction — the
        request waits host-side; nothing was corrupted, nothing leaked)."""
        base = self._paged_bind(idx, request)
        if base is None:
            self._page_deferred.append(request)
            return "deferred"
        if base < 0:
            return "failed"  # can-never-fit: _paged_bind resolved it
        if base == 0:
            return "cold"
        self._paged_prefill_prefix(idx, request, base, entries)
        return "warm"

    def _paged_prefill_prefix(
        self, idx: int, request: GenerationRequest, p: int, entries: list,
    ) -> None:
        """Warm paged admission: the aliased pages are ALREADY in the slot's
        table (_paged_bind), so all that runs on device is ONE fused
        suffix-segment dispatch. Compare the dense warm path: pool-width
        gather + segment + insert + chain scatter — four dispatches and a
        pool-width row duplicated per hit."""
        pool = self._pagepool
        prompt = request.prompt_tokens
        suffix = prompt[p:]
        ws = self._bucket(len(suffix))
        tokens = np.zeros((1, ws), np.int32)
        tokens[0, : len(suffix)] = suffix
        opts = request.options
        started = time.monotonic()
        if self._spmd is not None:
            # one warm paged admission = one suffix segment against pages
            # the preceding OP_PAGE_BIND already aliased on every host
            self._spmd.announce(wire.ControlBlock(
                op=wire.OP_LONG_SEG, width=ws, n_rows=1, tokens=tokens,
                s0=p, seg_len=len(suffix), long_idx=idx,
                long_final=True, prompt_len=len(prompt),
                temps=np.asarray([opts.temperature], np.float32),
                top_ks=np.asarray([opts.top_k], np.int32),
                top_ps=np.asarray([opts.top_p], np.float32),
            ))
        try:
            first = self._dev_paged_segment(
                tokens, p, len(suffix), idx,
                opts.temperature, opts.top_k, opts.top_p,
                final=True, prompt_len=len(prompt),
                agentic_rows=request._agentic_rows,
            )
        except Exception as e:  # noqa: BLE001 — fail the request, not the engine
            if self._spmd is not None:
                raise  # multi-host: crash the replica (see _admit rationale)
            log.exception("paged prefix-reuse prefill failed (p=%d)", p)
            self._free_slot_pages(idx)
            request._finish(GenerationResult(
                tokens=[], finish_reason="error", prompt_tokens=0,
                ttft_s=0, total_s=0, error=e,
            ))
            return
        if self._obs.on:
            self._obs.record(
                "engine_prefill_dispatch_s", time.monotonic() - started
            )
        self._prefill_tokens_dispatched += len(suffix)
        slot = self._slots[idx]
        slot.request = request
        slot.position = len(prompt)
        slot.generated = []
        slot.started_at = started
        slot.first_token_at = 0.0
        slot.reset_obs("warm", 1)
        self._slot_bind_agentic(idx, request)
        with self._stats_lock:
            self.total_requests += 1
        self._note_tenant_admitted(request)
        self._spec_admit(idx, prompt)
        self._maybe_publish(idx, prompt)
        entries.append(("prefill", self._fetcher.submit(first), [(idx, request)]))

    def _dev_paged_segment(
        self, tokens, s0, seg_len, idx, temperature, top_k, top_p,
        *, final: bool, prompt_len: int, agentic_rows=None,
    ):
        """Device layer of one paged prefill segment (warm suffix OR one
        chunk of a long prompt): K/V scatter straight into the slot's
        pages, attention reads the prefix through the table. On ``final``
        the decode chain scatters — there is no insert/splice: the pages
        ARE the cache. The DFA state scatter only lands on ``final`` (the
        segment whose sampled first token actually seeds the chain)."""
        if self._injector is not None:
            self._injector.fire("segment")
        pool = self._pagepool
        table = np.full((1, pool.table_len), pool.oob, np.int32)
        if 0 <= idx < self.max_batch:
            table[0] = pool.tables[idx]
        self._record_program("paged-segment", tokens.shape[1])
        kw = self._segment_agentic_kwargs(
            agentic_rows, idx if final else self.max_batch
        )
        first, pool.dev, self._key, state_dev = _paged_segment_and_sample(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray([s0], jnp.int32),
            jnp.asarray([seg_len], jnp.int32),
            pool.dev,
            jnp.asarray(table),
            self._key,
            jnp.asarray([temperature], jnp.float32),
            jnp.asarray([top_k], jnp.int32),
            jnp.asarray([top_p], jnp.float32),
            self.config,
            self.page_size,
            **kw,
        )
        if state_dev is not None:
            self._dfa_state_dev = state_dev
        if final:
            self._record_program("chain-scatter")
            (
                self._tokens_dev, self._positions_dev, self._temp_dev,
                self._top_k_dev, self._top_p_dev,
            ) = _chain_scatter(
                self._tokens_dev, self._positions_dev, self._temp_dev,
                self._top_k_dev, self._top_p_dev,
                jnp.asarray(idx, jnp.int32), first, prompt_len,
                temperature, top_k, top_p,
            )
        return first

    def _active_mask(self) -> np.ndarray:
        """Per-slot liveness for a decode/verify dispatch (1 = active).
        Computed ONCE at dispatch and — under SPMD — shipped on the wire:
        followers cannot observe completions (those are discovered from
        fetched tokens on the leader), so the mask is part of the dispatch
        description, not derivable state."""
        return np.asarray(
            [1 if s.active else 0 for s in self._slots], np.int32
        )

    def _dispatch_tables(self, mask: Optional[np.ndarray] = None) -> np.ndarray:
        """Page tables for a decode/verify dispatch, with every non-ACTIVE
        slot's row masked to the out-of-bounds sentinel. A decode step
        computes (garbage) K/V for inactive rows too; on the dense layout
        those writes landed in the inactive slot's own cache row
        (harmless), but a paged table row may belong to a RESERVED
        long-prefill stream whose pages are mid-prefill — an unmasked
        dispatch would scribble stale-position garbage straight into them.
        Masked rows drop their writes and read clamped (masked) garbage,
        exactly like the warmup dispatches. ``mask`` (SPMD followers: the
        leader's wire-shipped liveness) overrides the local slot view."""
        pool = self._pagepool
        tables = pool.tables.copy()
        if mask is None:
            mask = self._active_mask()
        inactive = [i for i in range(self.max_batch) if not mask[i]]
        if inactive:
            tables[inactive] = pool.oob
        return tables

    def _page_integrity_check(self) -> None:
        """Validate every active slot's table row against the allocator's
        authoritative owned-page list before a decode/verify dispatch; a
        mismatch (the ``page`` fault site, host memory corruption, or a
        real bookkeeping bug) quarantines ONLY that slot — its request
        fails, its pages free through the owned list (no leak) and are
        zeroed — while every other slot keeps decoding untouched."""
        pool = self._pagepool
        if self._injector is not None:
            snapshot = [
                (i, s.request) for i, s in enumerate(self._slots) if s.active
            ]
            self._injector.corrupt_page_table(pool, snapshot)
        for i, slot in enumerate(self._slots):
            if not slot.active or pool.validate(i):
                continue
            with self._stats_lock:
                self.quarantined_slots_total += 1
            self._quarantine_pages(i)
            self._flight_dump("page-quarantine", extra={"slot": i})
            self._finish_slot(
                i, "error",
                error=RuntimeError(
                    f"page-table corruption detected for slot {i}; slot "
                    "quarantined, pages freed and zeroed"
                ),
            )

    def _quarantine_pages(self, idx: int) -> None:
        """Paged quarantine: evict any prefix entry sharing the victim's
        pages (poisoned KV must not be aliased into future admissions),
        free the slot's pages through the authoritative owned list, and
        queue the now-unreferenced ones for a coalesced zero dispatch
        (pages, not rows — ROADMAP item 1). SPMD followers see the free
        (OP_PAGE_FREE) and the zero (OP_PAGE_ZERO on the next flush)."""
        pool = self._pagepool
        pages = pool.slot_pages(idx)
        if not pages:
            return
        if self._prefix_index is not None:
            self._prefix_index.evict_touching(pool, pages)
        self._pending_page_zero.extend(self._free_slot_pages(idx))

    def _free_slot_pages(self, idx: int) -> list[int]:
        """Release slot ``idx``'s pages (completion, quarantine, abort, or
        a failed admission), announcing the table clear to SPMD followers
        FIRST — their dispatch tables must stop referencing the pages
        before any later OP_PAGE_BIND re-issues them. Returns the pages
        whose refcount hit zero (the quarantine path zeroes those). The
        single gateway every ``free_slot`` call goes through, so a call
        site can never silently skip the wire. A slot that owns nothing
        (already freed — e.g. _finish_slot after a quarantine) skips the
        announce: the follower's table is already clear, and a redundant
        broadcast per quarantine is pure wire noise."""
        if self._spmd is not None and self._pagepool.slot_pages(idx):
            self._spmd.announce(
                wire.ControlBlock(op=wire.OP_PAGE_FREE, long_idx=idx)
            )
        return self._pagepool.free_slot(idx)

    def _spmd_tick(self) -> None:
        """SPMD resilience bookkeeping at the iteration top (leader only,
        engine thread — docs/SERVING.md §20): fire the ``spmd-crash``
        drill site (a raise here IS an engine-loop crash, driving the
        coordinated OP_RECOVER path end to end), answer at most one
        pending divergence-resync request (throttled — the KV-store poll
        is a coordinator round trip), and keep follower watchdogs fed
        with OP_IDLE heartbeats when no dispatch has announced lately."""
        if self._injector is not None:
            self._injector.fire("spmd-crash")
        now = time.monotonic()
        # poll at the heartbeat cadence, never faster than 4 Hz: on a
        # real slice each poll is one coordinator KV round trip PER
        # follower, and a resync is rare + not latency-critical (the
        # follower keeps replaying while it waits)
        wd = getattr(self._spmd, "watchdog_s", 0)
        if now - self._spmd_div_checked_at >= max(0.25, wd / 4):
            self._spmd_div_checked_at = now
            try:
                req = self._spmd.poll_divergence()
            except Exception:  # noqa: BLE001 — side channel gone ≠ crash
                req = None
            if req is not None:
                self._spmd_resync(req)
        self._spmd_heartbeat()

    def _spmd_heartbeat(self) -> None:
        """Announce OP_IDLE when the wire has been quiet for a quarter of
        the watchdog bound — silence then cleanly separates 'idle replica'
        from 'dead leader' on the follower side. No-op with the watchdog
        off (watchdog_s == 0), so pre-round-19 channels see zero extra
        traffic."""
        ch = self._spmd
        wd = getattr(ch, "watchdog_s", 0)
        if ch is None or wd <= 0:
            return
        if time.monotonic() - ch.last_announce_t >= max(0.05, wd / 4):
            try:
                ch.announce(wire.ControlBlock(op=wire.OP_IDLE))
            except Exception:  # noqa: BLE001 — heartbeats are best-effort
                log.exception("SPMD idle heartbeat failed")

    def _spmd_resync(self, req: dict) -> None:
        """Answer a follower's divergence report with ONE coordinated
        OP_RESYNC: re-broadcast the authoritative per-slot page tables
        and device positions at a fresh epoch, then reset the seq chain
        to the epoch base. (The active-slot MASK is per-dispatch wire
        data — every decode/verify block ships it — so a resync has
        nothing persistent to re-broadcast for it.) The follower
        VERIFIES its own state against the snapshot and rejoins on a
        match; mismatch (or a repeat divergence inside its window) stays
        fatal on its side — the leader just answers, it never decides
        (§20)."""
        pool = self._pagepool
        b = self.max_batch
        tl = pool.table_len if pool is not None else 0
        parts = []
        if tl:
            parts.append(
                np.asarray(pool.tables[:b, :tl], np.int32).reshape(-1)
            )
        parts.append(np.asarray(
            jax.device_get(self._positions_dev), np.int32
        )[:b])
        payload = np.concatenate(parts)
        epoch = self._spmd_epoch + 1
        log.warning(
            "SPMD follower reported divergence (%s); answering with "
            "OP_RESYNC at epoch %d", req.get("why", "?"), epoch,
        )
        self._spmd.announce(wire.ControlBlock(
            op=wire.OP_RESYNC, long_idx=epoch, count=len(payload),
            n_rows=b, width=tl, echo=payload,
        ))
        self._spmd.reset_seq()
        self._spmd_epoch = epoch
        with self._stats_lock:
            self.spmd_resyncs_total += 1
        self._flight_dump(
            "spmd-recover",
            extra={"kind": "resync", "epoch": epoch, "requested": dict(req)},
        )

    def _spmd_echo(self, kind: int, host: np.ndarray) -> None:
        """Re-broadcast a processed chunk's fetched tokens to followers in
        echo (divergence-check) mode: the follower compares them against
        its own device result for the same dispatch and crashes with a
        flight dump on mismatch (docs/SERVING.md §14). One extra broadcast
        per processed chunk — off in production, on in the parity suite."""
        if self._spmd is None or not getattr(self._spmd, "echo", False):
            return
        flat = np.asarray(host, np.int32).reshape(-1)
        self._spmd.announce(wire.ControlBlock(
            op=wire.OP_ECHO, long_idx=kind, count=len(flat), echo=flat,
        ))

    def _spmd_apply_bind(
        self, idx: int, pages: list, cow_src: Optional[int],
        cow_dst: Optional[int],
    ) -> None:
        """Follower half of OP_PAGE_BIND: adopt the leader's reservation
        RESULT into this process's dispatch tables (tables are the only
        allocator state a follower keeps — parallel/spmd_serving.py) and
        make the same copy-on-write page copy, in the same stream order."""
        pool = self._pagepool
        if pages:
            pool.tables[idx, : len(pages)] = pages
            pool.tables[idx, len(pages):] = pool.oob
        if cow_src is not None and cow_dst is not None:
            self._record_program("page-copy")
            pool.dev = _page_copy(
                pool.dev,
                jnp.asarray(cow_src, jnp.int32),
                jnp.asarray(cow_dst, jnp.int32),
            )

    def _flush_page_zeros(self) -> None:
        """Zero quarantined pages, coalesced into table_len-wide dispatches
        (ONE compiled program; out-of-bounds padding drops). Runs at the top
        of the iteration, so the zero rides the in-order stream ahead of
        any admission that re-allocates the freed pages. SPMD: each zero
        dispatch rides the wire (OP_PAGE_ZERO) so followers scrub the same
        physical pages."""
        pool = self._pagepool
        pages = self._pending_page_zero
        self._pending_page_zero = []
        width = pool.table_len
        for i in range(0, len(pages), width):
            chunk = pages[i : i + width]
            if self._spmd is not None:
                self._spmd.announce(wire.ControlBlock(
                    op=wire.OP_PAGE_ZERO, count=len(chunk),
                    pages=np.asarray(chunk, np.int32),
                ))
            self._dev_page_zero(chunk)

    def _dev_page_zero(self, pages) -> None:
        """Device layer of one quarantine page-zero dispatch (leader + SPMD
        followers): fixed table_len-wide buffer, OOB padding drops."""
        pool = self._pagepool
        buf = np.full(pool.table_len, pool.oob, np.int32)
        buf[: len(pages)] = list(pages)
        self._record_program("page-zero")
        pool.dev = _page_zero(pool.dev, jnp.asarray(buf))

    # -- tiered KV: host-RAM spill + hibernation restore ---------------------

    def _drain_spills(self) -> None:
        """Fold completed spills in (engine thread, iteration top): attach
        the arena slots to their entry — or free them when the entry died
        mid-copy (cancelled/quarantined), the copy failed, or the handle
        predates a crash recovery (stale generation: the arena was already
        reset; its free list owns those slots again)."""
        tier = self._host_tier
        if tier is None:
            return
        while True:
            try:
                handle = self._spill_done.get_nowait()
            except queue.Empty:
                return
            if handle.gen != self._spill_gen:
                continue
            entry = handle.entry
            if handle.cancelled or entry.dropped:
                tier.free(handle.slots)
                continue
            entry.spilling = None
            if handle.error is not None:
                log.warning("page spill failed: %s", handle.error)
                tier.free(handle.slots)
                self.spill_failures_total += 1
                if not entry.pages:
                    # the entry was DEMOTED on the strength of this spill
                    # (evict_for trusts an in-flight handle): with the copy
                    # failed it holds neither device nor host pages — a
                    # zombie a later radix hit would "restore" with zero
                    # pages. Drop it; the session re-prefills next turn.
                    self._prefix_index._drop(self._pagepool, entry)
                continue
            entry.host = tuple(handle.slots)
            self._prefix_index._note_tier(entry)
            self.spill_pages_total += len(handle.slots)
            self.spill_bytes_total += len(handle.slots) * tier.bytes_per_page
            # durable tier (§23): a completed spill is the checkpoint
            # trigger — the arena bytes and their stamps are final now,
            # so the session can be made to survive THIS replica too
            self._maybe_checkpoint(entry)

    def _ensure_spilled(self, entry) -> bool:
        """Secure a host copy for ``entry`` (the demote-before-drop gate):
        True when one exists, is in flight, or was enqueued just now. The
        engine thread only dispatches the per-page snapshot program (async,
        independent buffers — the entry's device pages may be freed the
        moment this returns); the device→host bytes move on the spill
        worker, off the hot loop."""
        if not self._spill_on or self._spill_worker is None:
            return False
        if entry.host or entry.spilling is not None:
            return True
        if not entry.pages or entry.dropped:
            return False
        tier = self._host_tier
        slots = tier.alloc(len(entry.pages))
        if slots is None:
            self._evict_host_for(len(entry.pages), keep=entry)
            slots = tier.alloc(len(entry.pages))
            if slots is None:
                return False
        pool = self._pagepool
        self._record_program("page-snapshot")
        blocks = [
            _page_snapshot(pool.dev, jnp.asarray(p, jnp.int32))
            for p in entry.pages
        ]
        handle = _Spill(entry, slots, blocks, self._spill_gen)
        entry.spilling = handle
        self._spill_worker.submit(handle)
        return True

    def _evict_host_for(self, need: int, keep=None) -> None:
        """Make arena room: free host copies LRU-first (a ``both`` victim
        just loses its spare; a ``host``-only victim is dropped outright —
        its session will re-prefill). Never touches ``keep`` (the entry
        we're making room FOR) or pinned entries."""
        index, tier = self._prefix_index, self._host_tier
        while tier.free_slots < need:
            victims = [
                e for e in index._live
                if e.host and e.pins == 0 and e is not keep
            ]
            if not victims:
                return
            victim = min(victims, key=lambda e: e.last_used)
            if victim.pages:
                tier.free(victim.host)
                victim.host = ()
                index._note_tier(victim)
                # the entry reverted to device-only: make it a spill
                # candidate again so the idle sweep can re-hibernate it
                # once the arena has room (duplicates in the deque are
                # benign — the sweep's host/spilling checks skip them)
                self._spill_candidates.append(victim)
            else:
                # durable rescue (§23): a host-only victim is gone for
                # good after the drop — materialize its checkpoint job
                # FIRST (the worker holds its own byte copies, so the
                # drop below cannot race the disk write)
                self._maybe_checkpoint(victim)
                index._drop(self._pagepool, victim)
            index.host_evictions += 1

    def _spill_tick(self) -> None:
        """Hibernation sweep, once per engine iteration: drain completed
        spills, then start at most a couple of new ones for entries idle
        past ``spill_idle_s`` (oldest first). O(1) when there is nothing
        to do — the hot loop's cost is one deque truthiness check."""
        if not self._spill_on:
            return
        t0 = time.monotonic()
        self._drain_spills()
        started = 0
        now = time.monotonic()
        # the deque is PUBLISH-ordered, not idle-ordered (last_used_t is
        # refreshed on every hit): a hot entry at the front must not starve
        # idle entries behind it, so not-yet-idle candidates ROTATE to the
        # back and the scan is bounded per tick — the hot loop does at
        # most 8 deque hops
        scanned, limit = 0, min(len(self._spill_candidates), 8)
        while self._spill_candidates and started < 2 and scanned < limit:
            scanned += 1
            entry = self._spill_candidates.popleft()
            if entry.dropped or entry.host or entry.spilling is not None:
                continue
            if now - entry.last_used_t < self.spill_idle_s:
                self._spill_candidates.append(entry)  # not idle: revisit
                continue
            if self._ensure_spilled(entry):
                started += 1
            else:
                # arena full and unevictable THIS tick: rotate to the
                # back and retry on a later sweep — a live session's
                # prefix never re-publishes, so forgetting the candidate
                # would leave it pinning HBM through its whole idle
                # period. Stop the sweep: every further candidate hits
                # the same full arena this tick
                self._spill_candidates.append(entry)
                break
        self._spill_ms_iter += (time.monotonic() - t0) * 1e3

    def _restore_entry(
        self, entry, p: int, count_failures: bool = True,
    ) -> bool:
        """Hibernation restore (the admission's warm-hit path when the
        radix hit lives host-side): allocate device pages, upload the
        arena copy with the ONE warmed traced-index program, and re-attach
        them to the entry. False — with the entry either intact (no device
        room: caller falls back) or dropped (checksum mismatch / injected
        ``spill`` fault / spill never completed: poison must not be
        retried) — when the restore cannot serve the hit; the caller
        recomputes. Synchronous on the engine thread: the admission needs
        the pages before its suffix prefill, and the upload IS the win
        (DMA speed vs re-prefill FLOPs). ``count_failures=False`` keeps a
        page-deferred request's per-iteration retries off the failure
        gauges (each request counts its failures once)."""
        pool, index, tier = self._pagepool, self._prefix_index, self._host_tier
        if entry.dropped:
            return False
        fail = 1 if count_failures else 0
        t0 = time.monotonic()
        handle = entry.spilling
        if handle is not None:
            # hit raced the copy: give it a short grace (the common case
            # is a near-drained handle) bounded by the SAME threshold the
            # feature treats as a stall incident — this wait blocks every
            # active session's decode. On expiry fall back WITHOUT
            # dropping: the copy is healthy, merely queued behind other
            # handles; it completes off-thread and the next turn restores
            if not handle.event.wait(self._restore_stall_s):
                self.restore_failures_total += fail
                self._flight_dump("spill-stall", extra={
                    "restore-wait-ms": round((time.monotonic() - t0) * 1e3, 3),
                    "reuse-tokens": p,
                })
                return False
            self._drain_spills()
            if not entry.host or entry.dropped:
                self.restore_failures_total += fail
                if not entry.dropped:
                    index._drop(pool, entry)
                return False
        n = len(entry.host)
        if n == 0:
            # belt to _drain_spills' braces: an entry with neither device
            # nor host pages can't serve anything — a zero-page "restore"
            # would count a warm hit whose prefix KV was never written
            self.restore_failures_total += fail
            index._drop(pool, entry)
            return False
        # PIN across the eviction window below: evict_for's spill_cb can
        # cascade into _evict_host_for, whose LRU victim scan would
        # otherwise pick THIS entry (host-only and idle — the natural
        # minimum) and drop it out from under the restore
        index.acquire(entry)
        try:
            if pool.free_pages < n:
                index.evict_for(pool, n, spill_cb=self._ensure_spilled)
            pages = pool.alloc_pages(n)
        finally:
            index.release(entry)
        if pages is None:
            # no device room even after demotions — entry stays hibernated,
            # the admission recomputes (or defers on its own reservation)
            self.restore_failures_total += fail
            return False
        if entry.dropped or len(entry.host) != n:
            # paranoia (python -O strips the attach assertion): the entry
            # must still own exactly the arena slots we sized against
            pool.decref(pages)
            self.restore_failures_total += fail
            if not entry.dropped:
                index._drop(pool, entry)
            return False
        if self._injector is not None:
            self._injector.corrupt_host_page(tier, entry.host)
        ok = True
        self._record_program("page-restore")
        for slot, dst in zip(entry.host, pages):
            block = tier.read(slot)
            if block is None:
                ok = False  # checksum mismatch: host copy is poison
                break
            pool.dev = _page_restore(pool.dev, block, jnp.asarray(dst, jnp.int32))
        if not ok:
            pool.decref(pages)
            index._drop(pool, entry)  # frees the arena slots too
            self.restore_failures_total += fail
            log.warning(
                "host-tier restore failed checksum (%d pages) — falling "
                "back to re-prefill", n,
            )
            return False
        index.attach_device_pages(pool, entry, pages)
        self.restore_pages_total += n
        self.restore_bytes_total += n * tier.bytes_per_page
        took = time.monotonic() - t0
        self._restore_ms_iter += took * 1e3
        if self._obs.on:
            self._obs.record("engine_restore_s", took)
        if took > self._restore_stall_s:
            # a restore that stalls an admission past the bound is an
            # incident worth a postmortem ring (slow host RAM? checksum
            # thrash? arena contention?) — same debounce as every reason
            self._flight_dump("spill-stall", extra={
                "restore-ms": round(took * 1e3, 3),
                "restore-pages": n,
                "reuse-tokens": p,
            })
        return True

    # -- durable session tier (docs/SERVING.md §23) --------------------------

    def _durable_job(self, entry) -> Optional[dict]:
        """Materialize one entry's checkpoint job (engine thread): raw
        page byte images + their SPILL-TIME checksums. Host-resident
        entries read the arena and ship the stored stamps as-is;
        device-only entries (hibernation's device path) fetch their page
        snapshots and stamp here — for a page that never spilled, this
        first hash IS its spill-time stamp. None when the entry holds
        nothing checkpointable (in-flight spill, arena rot, no token
        path) — the caller skips, never fails."""
        from langstream_tpu.serving.pagepool import (
            join_page_bytes, page_checksum,
        )

        tier, pool, index = self._host_tier, self._pagepool, self._prefix_index
        if entry.dropped or not entry.digest or entry.length <= 0:
            return None
        tokens = index.entry_tokens(entry)
        if len(tokens) != entry.length:
            return None
        n = math.ceil(entry.length / self.page_size)
        pages_raw: list[bytes] = []
        sums: list[str] = []
        if (
            entry.host
            and entry.spilling is None
            and tier is not None
            and len(entry.host) >= n
        ):
            for slot in entry.host[:n]:
                block = tier.read(slot)
                if block is None:
                    return None  # arena rot: restore paths count it
                leaves = jax.tree.leaves(block)
                pages_raw.append(join_page_bytes(leaves))
                sums.append(tier.checksum(slot).hex())
        elif entry.pages and len(entry.pages) >= n:
            self._record_program("page-snapshot")
            for pg in entry.pages[:n]:
                block = _page_snapshot(pool.dev, jnp.asarray(pg, jnp.int32))
                leaves = [
                    np.asarray(jax.device_get(leaf))
                    for leaf in jax.tree.leaves(block)
                ]
                pages_raw.append(join_page_bytes(leaves))
                sums.append(page_checksum(leaves).hex())
        else:
            return None
        return {
            "digest": entry.digest, "length": int(entry.length),
            "tokens": tokens, "pages_raw": pages_raw, "checksums": sums,
            "page_size": self.page_size,
            "bytes_per_page": pool.bytes_per_page,
        }

    def _maybe_checkpoint(self, entry) -> None:
        """Enqueue a durable checkpoint for ``entry`` if the tier is on
        and no checkpoint exists yet (engine thread; the disk write runs
        on the durable worker). Failure-free by design: anything not
        checkpointable is simply skipped — the session keeps its
        host/device copy and a later trigger retries."""
        if self._durable is None or self._durable_worker is None:
            return
        if self._durable.contains(entry.digest):
            return
        job = self._durable_job(entry)
        if job is not None:
            self._durable_worker.submit(job)

    def _durable_admit(self, request, prompt) -> Optional[tuple]:
        """Admission-path resurrection: no live index candidate covered
        ``prompt``, so probe the durable store at the deepest boundary,
        restore + verify the checkpoint and bind it INLINE on the engine
        thread (_migrate_rpc would deadlock the loop against itself).
        Returns ``(length, entry)`` like a radix hit, or None with the
        request degrading to a cold prefill. EVERY failure — torn file,
        CRC/checksum mismatch, stale manifest, stalled volume, full pool
        — dumps ``durable-restore-failed`` (token-content-free) and the
        store marks its entry dead, so a failure fires once, never a
        retry loop on poison."""
        from langstream_tpu.serving.durable import DurableError
        from langstream_tpu.serving.migrate import MigrationError, _leaf_specs
        from langstream_tpu.serving.pagepool import (
            page_checksum, prefix_digest, split_page_bytes,
        )

        store, index = self._durable, self._prefix_index
        if store is None or getattr(request, "_durable_failed", False):
            return None
        digest, length = None, 0
        for b in reversed(index.boundaries):
            if b <= len(prompt) - 1:
                d = prefix_digest(prompt[:b])
                if store.contains(d):
                    digest, length = d, b
                    break
        if digest is None:
            return None
        t0 = time.monotonic()
        self._durable_restoring = True
        try:
            rec = store.restore(digest, timeout_s=self.durable_timeout_s)
            specs = _leaf_specs(self)
            blocks = []
            for i, raw in enumerate(rec["pages"]):
                leaves = split_page_bytes(raw, specs)
                if page_checksum(leaves).hex() != rec["checksums"][i]:
                    # the manifest stamp (spill-time, never re-hashed) is
                    # the authority: poison must not be retried
                    store.invalidate(
                        digest, f"page {i} failed its spill-time checksum"
                    )
                    raise DurableError(
                        f"page {i} failed its spill-time checksum"
                    )
                blocks.append(leaves)
            self._migrate_cmd("bind", {
                "tokens": list(prompt[:length]), "length": length,
                "blocks": blocks,
            })
        except (DurableError, MigrationError, ValueError) as e:
            # a full receiver pool is the ONE retryable failure (a later
            # iteration may have evicted room); everything else is a dead
            # entry and must degrade to cold prefill exactly once
            request._durable_failed = not isinstance(e, MigrationError)
            self._flight_dump("durable-restore-failed", extra={
                "error": str(e),
                "entry-digest": digest,
                "reuse-tokens": length,
                "total-ms": round((time.monotonic() - t0) * 1e3, 3),
                "fallback": "local-cold-prefill",
            }, force=True)
            log.warning(
                "durable restore of %s failed (%s); prefilling cold",
                digest, e,
            )
            return None
        finally:
            self._durable_restoring = False
        took = time.monotonic() - t0
        if self._obs.on:
            self._obs.record("engine_durable_restore_s", took)
        self.durable_restored_hits_total += 1
        self._restore_ms_iter += took * 1e3
        # the bind inserted a live entry: serve it like any radix hit
        for p_cand, cand in reversed(index.candidates(prompt)):
            if not cand.dropped and cand.pages:
                return p_cand, cand
        return None

    def _durable_snapshot(self, tokens) -> Optional[dict]:
        """Snapshot branch for prefixes that outlived their index entry
        (engine thread, under _migrate_cmd): a P2P fetch / migration can
        be served STRAIGHT from the durable checkpoint — the wire codec
        is the disk format, so the bytes just change transports. None
        when the store has no covering entry or the read fails (the
        caller's no-prefix error stands)."""
        from langstream_tpu.serving.durable import DurableError
        from langstream_tpu.serving.migrate import _leaf_specs
        from langstream_tpu.serving.pagepool import (
            prefix_digest, split_page_bytes,
        )

        store, index = self._durable, self._prefix_index
        if store is None:
            return None
        toks = list(tokens)
        for b in reversed(index.boundaries):
            if b > len(toks):
                continue
            digest = prefix_digest(toks[:b])
            if not store.contains(digest):
                continue
            try:
                rec = store.restore(digest, timeout_s=self.durable_timeout_s)
                specs = _leaf_specs(self)
                blocks = [
                    split_page_bytes(raw, specs) for raw in rec["pages"]
                ]
            except (DurableError, ValueError) as e:
                log.warning(
                    "durable snapshot of %s failed (%s)", digest, e
                )
                return None
            return {
                "tier": "durable", "length": b, "digest": digest,
                "blocks": blocks,
                "checksums": [bytes.fromhex(s) for s in rec["checksums"]],
                "page_size": int(rec["page_size"]),
                "bytes_per_page": int(rec["bytes_per_page"]),
            }
        return None

    def hibernate(self, replica_id: str = "", timeout_s: float = 60.0) -> dict:
        """Checkpoint EVERY live prefix entry to the durable tier and
        write the replica hibernation record — the drained-replica half
        of scale-to-zero (docs/SERVING.md §23). Call AFTER drain() and
        BEFORE stop() (the holder.begin_drain ordering): the engine loop
        must still be serving commands. Returns the ledger
        ``{"entries", "bytes", "failures"}``; ``{}`` with the tier off.
        Synchronous and deadline-bounded — a wedged disk fails the
        hibernation, never the shutdown."""
        from langstream_tpu.serving.migrate import MigrationError

        if self._durable is None:
            return {}
        if self._durable_worker is not None:
            # in-flight spill-triggered checkpoints first, so the walk
            # below sees them via store.contains and skips the re-write
            self._durable_worker.flush(timeout_s)
        try:
            return self._migrate_rpc(
                "hibernate", {"replica": str(replica_id)}, timeout_s
            )
        except MigrationError as e:
            log.warning("hibernation failed (%s) — sessions stay "
                        "restorable from earlier checkpoints only", e)
            return {"entries": 0, "bytes": 0, "failures": -1}

    @property
    def restoring(self) -> bool:
        """True while a durable-tier restore is serving an admission —
        the cheap accessor /healthz surfaces as resurrection-in-progress
        (readiness probes during scale-from-zero)."""
        return self._durable_restoring

    def prefill_tps_estimate(self) -> float:
        """Landed prefill throughput (tokens/s) off the prefill-dispatch
        histogram: tokens covered by landed dispatches over their summed
        wall time. The fleet beacon ships this for the router's
        fetch-vs-prefill cost model (docs/SERVING.md §21/§23); 0.0 until
        a dispatch lands (the router then falls back to its flat
        threshold)."""
        if not self._obs.on:
            return 0.0
        h = self._obs.hist.get("engine_prefill_dispatch_s")
        if h is None:
            return 0.0
        snap = h.snapshot()
        total_s = float(snap.get("sum", 0.0))
        if total_s <= 0.0:
            return 0.0
        return round(self._prefill_tokens_dispatched / total_s, 1)

    # -- KV-page migration (disaggregated serving, docs/SERVING.md §18) ------

    def _drain_migrations(self) -> None:
        """Serve queued migration commands (engine thread, iteration top).
        Each command replies on its own queue; a command that fails
        replies the exception instead of killing the loop — a broken
        migration degrades ONE transfer, never the engine."""
        from langstream_tpu.serving.migrate import MigrationError

        while True:
            try:
                kind, payload, reply = self._migrate_cmds.get_nowait()
            except queue.Empty:
                return
            try:
                reply.put(("ok", self._migrate_cmd(kind, payload)))
            except MigrationError as e:
                self.migrate_failures_total += 1
                reply.put(("err", e))
            except Exception as e:  # noqa: BLE001 — degrade the transfer only
                log.exception("migration command %s failed", kind)
                self.migrate_failures_total += 1
                reply.put(("err", MigrationError(f"{kind}: {e}")))

    def _migrate_cmd(self, kind: str, payload: dict) -> dict:
        from langstream_tpu.serving.migrate import MigrationError
        from langstream_tpu.serving.pagepool import prefix_digest

        pool, index = self._pagepool, self._prefix_index
        if pool is None or index is None:
            raise MigrationError(
                "KV-page migration needs the paged layout with a prefix "
                "index (kv-layout: paged, prefix-cache: auto)"
            )
        if kind == "snapshot":
            hit = index.deepest_entry(payload["tokens"])
            if hit is None:
                # the live index lost it, but a durable checkpoint may
                # still cover the prompt (§23): the wire codec is the
                # disk format, so serve the P2P fetch from disk directly
                durable = self._durable_snapshot(payload["tokens"])
                if durable is not None:
                    return durable
                raise MigrationError("no published prefix covers this prompt")
            length, entry = hit
            n = math.ceil(length / self.page_size)
            tier = self._host_tier
            # hibernated (and spilled-while-resident) sessions send
            # STRAIGHT from the host arena — no device restore, and the
            # stamped spill checksum ships as-is; a completed spill is
            # required (an in-flight handle's slots are the worker's)
            if entry.host and entry.spilling is None and tier is not None:
                slots = list(entry.host[:n])
                if len(slots) == n:
                    blocks, sums = [], []
                    for s in slots:
                        block = tier.read(s)
                        if block is None:
                            blocks = None  # checksum rot: fall to device
                            break
                        blocks.append(jax.tree.leaves(block))
                        sums.append(tier.checksum(s))
                    if blocks is not None:
                        return {
                            "tier": "host", "length": length,
                            "digest": prefix_digest(
                                list(payload["tokens"])[:length]
                            ),
                            "blocks": blocks, "checksums": sums,
                            "page_size": self.page_size,
                            "bytes_per_page": pool.bytes_per_page,
                        }
            if not entry.pages or len(entry.pages) < n:
                raise MigrationError(
                    "prefix entry holds no readable pages (host copy "
                    "failed verification and no device half exists)"
                )
            # device tier: slice each page into INDEPENDENT buffers (the
            # spill path's decoupling trick) — the caller's device→host
            # fetch can never race a later donating rewrite or a free
            self._record_program("page-snapshot")
            blocks = [
                _page_snapshot(pool.dev, jnp.asarray(p, jnp.int32))
                for p in entry.pages[:n]
            ]
            return {
                "tier": "device", "length": length,
                "digest": prefix_digest(list(payload["tokens"])[:length]),
                "blocks": blocks, "checksums": None,
                "page_size": self.page_size,
                "bytes_per_page": pool.bytes_per_page,
            }
        if kind == "bind":
            tokens, length = payload["tokens"], int(payload["length"])
            blocks = payload["blocks"]
            if length not in index.boundaries:
                raise MigrationError(
                    f"migrated length {length} is not a prefix boundary "
                    f"here (boundaries {index.boundaries}) — sender and "
                    "receiver disagree on bucket config"
                )
            if index.has(tokens, length):
                # idempotent re-migration (retry after a lost ACK): the
                # prefix is already resident — nothing to bind, ACK again
                return {"pages": 0, "bytes": 0, "already": True}
            n = math.ceil(length / self.page_size)
            if len(blocks) != n:
                raise MigrationError(
                    f"migration carries {len(blocks)} pages for a "
                    f"{length}-token prefix; expected {n}"
                )
            if pool.free_pages < n:
                index.evict_for(
                    pool, n,
                    spill_cb=self._ensure_spilled if self._spill_on else None,
                )
            pages = pool.alloc_pages(n)
            if pages is None:
                raise MigrationError(
                    f"receiver pool exhausted ({pool.free_pages} free, "
                    f"{n} needed) — nothing was bound"
                )
            treedef = jax.tree.structure(pool.dev)
            self._record_program("page-restore")
            try:
                for leaves, dst in zip(blocks, pages):
                    block = jax.tree.unflatten(treedef, leaves)
                    pool.dev = _page_restore(
                        pool.dev, block, jnp.asarray(dst, jnp.int32)
                    )
                entry = index.insert(pool, tokens, length, tuple(pages))
            except BaseException:
                pool.decref(pages)  # receiver frees on ANY abort — no leak
                raise
            pool.decref(pages)  # the index holds the one reference now
            if entry is None:
                # cap full and nothing evictable: insert declined (the
                # decref above already returned the pages — uploaded bytes
                # are garbage in free pages, same as any freed slot)
                raise MigrationError(
                    "receiver prefix index is at capacity with every "
                    "entry pinned — migration not bound"
                )
            if self._spill_on:
                # a migrated-in session hibernates like a published one
                self._spill_candidates.append(entry)
            self.migrate_pages_in_total += n
            self.migrate_bytes_in_total += n * pool.bytes_per_page
            return {"pages": n, "bytes": n * pool.bytes_per_page}
        if kind == "release":
            tokens, length = payload["tokens"], int(payload["length"])
            path = index._walk(tokens, limit=length)
            entry = path[-1].entry if path else None
            if entry is None or entry.length != length or entry.dropped:
                return {"released": False, "pages": 0}
            if entry.pins > 0:
                # an in-flight admission is reading it: retain (refcounts
                # keep the pages valid); LRU reclaims it once idle
                return {"released": False, "pages": 0}
            n = max(len(entry.pages), len(entry.host))
            index._drop(pool, entry)
            self.migrate_pages_out_total += n
            self.migrate_bytes_out_total += n * pool.bytes_per_page
            return {"released": True, "pages": n}
        if kind == "hibernate":
            # drained-replica shutdown (§23): checkpoint EVERY live entry
            # synchronously (the worker queue was flushed by hibernate()
            # before this RPC, so contains() skips already-durable ones),
            # then stamp the hibernation record — the resurrection beacon
            from langstream_tpu.serving.durable import DurableError

            store = self._durable
            if store is None:
                raise MigrationError("durable tier is off")
            done, failures, total_bytes, digests = 0, 0, 0, []
            for entry in list(index._live):
                if entry.dropped or not entry.digest:
                    continue
                if store.contains(entry.digest):
                    digests.append(entry.digest)
                    continue
                job = self._durable_job(entry)
                if job is None:
                    failures += 1
                    continue
                t0 = time.monotonic()
                try:
                    total_bytes += store.checkpoint(
                        job["digest"], job["length"], job["tokens"],
                        job["pages_raw"], job["checksums"],
                        job["page_size"], job["bytes_per_page"],
                    )
                except (DurableError, OSError) as e:
                    log.warning(
                        "hibernation checkpoint of %s failed: %s",
                        entry.digest, e,
                    )
                    failures += 1
                    continue
                if self._obs.on:
                    self._obs.record(
                        "engine_durable_checkpoint_s",
                        time.monotonic() - t0,
                    )
                done += 1
                digests.append(entry.digest)
            try:
                store.write_hibernation(
                    payload.get("replica") or "", digests,
                    compile_cache_dir=os.environ.get(
                        "JAX_COMPILATION_CACHE_DIR"
                    ),
                )
            except OSError as e:
                log.warning("hibernation record write failed: %s", e)
                failures += 1
            return {
                "entries": done, "bytes": total_bytes, "failures": failures,
            }
        raise MigrationError(f"unknown migration command {kind!r}")

    def _migrate_rpc(self, kind: str, payload: dict, timeout_s: float) -> dict:
        """Caller-thread half of a migration command: enqueue, wait, bound
        by ``timeout_s`` (the deadline-bounded-migrate contract — a wedged
        engine fails the MIGRATION, and the router falls back, rather than
        parking the hop forever)."""
        from langstream_tpu.serving.migrate import MigrationError

        if self._dead is not None:
            raise MigrationError("engine is stopped") from self._dead
        if not self._paged:
            raise MigrationError(
                "KV-page migration requires kv-layout: paged"
            )
        if self._spmd is not None:
            raise MigrationError(
                "KV-page migration is not on the SPMD wire yet (the bind/"
                "restore dispatches would need follower replay)"
            )
        reply: "queue.SimpleQueue" = queue.SimpleQueue()
        self._migrate_cmds.put((kind, payload, reply))
        try:
            status, out = reply.get(timeout=max(0.05, float(timeout_s)))
        except queue.Empty:
            raise MigrationError(
                f"engine did not serve the {kind} command within "
                f"{timeout_s:.1f}s"
            ) from None
        if status == "err":
            raise out
        return out

    def migrate_snapshot(self, tokens, timeout_s: float = 30.0) -> dict:
        """Serialize the deepest published prefix covering ``tokens`` for
        the migration wire (any thread): per-page host leaf blocks + the
        blake2b checksum stamped the same way the host spill tier stamps
        arena pages. Device-resident entries are sliced into independent
        buffers on the engine thread and fetched HERE (off the engine
        loop); hibernated entries ship their arena bytes + stored sums
        with no device work at all. Raises MigrationError on any failure
        (nothing is freed — the sender retains until ACK)."""
        from langstream_tpu.serving.migrate import MigrationError
        from langstream_tpu.serving.pagepool import page_checksum

        out = self._migrate_rpc(
            "snapshot", {"tokens": list(tokens)}, timeout_s
        )
        if out["tier"] == "device":
            try:
                fetched = [
                    [np.asarray(jax.device_get(leaf)) for leaf in
                     jax.tree.leaves(block)]
                    for block in out["blocks"]
                ]
            except Exception as e:  # noqa: BLE001 — device fetch failed
                raise MigrationError(f"page snapshot fetch failed: {e}") from e
            out["blocks"] = fetched
            out["checksums"] = [page_checksum(b) for b in fetched]
        return out

    def migrate_bind(
        self, tokens, length: int, blocks: list, timeout_s: float = 30.0,
    ) -> dict:
        """Bind already-checksum-VERIFIED migrated pages into this
        replica's pool + prefix index (any thread; the wire layer in
        serving/migrate.py owns the verification — this method trusts its
        caller exactly as far as one process boundary). On any failure
        nothing stays bound: allocated pages return to the free list
        before the error propagates (receiver frees on abort)."""
        return self._migrate_rpc(
            "bind",
            {"tokens": list(tokens), "length": int(length), "blocks": blocks},
            timeout_s,
        )

    def migrate_release(
        self, tokens, length: int, timeout_s: float = 10.0,
    ) -> dict:
        """Drop the migrated-out prefix entry (sender side, ONLY after the
        receiver's ACK): pages still aliased by active slots survive via
        refcounts; a pinned entry is retained for LRU to reclaim."""
        return self._migrate_rpc(
            "release", {"tokens": list(tokens), "length": int(length)},
            timeout_s,
        )

    def migrate_limits(self) -> dict:
        """Static pool geometry the migration RECEIVER uses to bound what
        it will read off the wire (runtime/http_server.py §21): page-bytes
        and total page count are fixed at pool construction, so any thread
        may read them lock-free. Empty dict when this engine has no paged
        pool (nothing can bind, so the receiver refuses early)."""
        pool = getattr(self, "_pagepool", None)
        if pool is None:
            return {}
        return {
            "bytes_per_page": int(pool.bytes_per_page),
            "pages_total": int(pool.num_pages),
        }

    def _spec_admit(self, idx: int, prompt: list[int]) -> None:
        """Create the slot's draft index at admission, seeded with the
        prompt (prompt-lookup: the prompt is where repeated spans live).
        Generated tokens join via _deliver_token as they are ACCEPTED —
        never from the verify chunk's written-but-rejected columns, so the
        index can only propose continuations of tokens that were actually
        emitted."""
        if self._spec_enabled:
            index = NGramIndex()
            index.extend(prompt)
            self._spec_index[idx] = index

    def _maybe_publish(self, idx: int, prompt: list[int]) -> None:
        """Copy-on-publish after a completed prefill: the slot's bucket-
        aligned prefix KV rows go into a pool row (one jitted gather-
        scatter), unless that prefix is already cached or every row is
        pinned by an in-flight admission (publish never blocks, never
        evicts a row being read).

        Speculation invariant: publish boundaries are PROMPT-prefix rows
        (p ≤ len(prompt)) written by prefill — never generated-region rows,
        where a verify chunk may have written past the ACCEPTED length and
        left stale rejected-draft K/V. Accepted-length, not written-length,
        is the only boundary the pool may ever see.

        Paged layout: publish is pure HOST bookkeeping — the slot's leading
        pages join the index with a refcount bump, no device copy at all
        (the dense path's copy-on-publish gather is gone).

        Adapter invariant: a tenant slot's prefix KV embeds its wk/wv
        adapter deltas — publishing it under the shared (base) trie would
        poison every later base admission that aliased it. Tenant slots
        never publish."""
        if self._adapters is not None and self._adapter_rows_auth[idx] != 0:
            return
        if self._paged:
            index = self._prefix_index
            if index is None:
                return
            p = index.publish_length(len(prompt))
            if p <= 0 or index.has(prompt, p):
                return
            import math as _math

            pool = self._pagepool
            n = _math.ceil(p / self.page_size)
            owned = pool.slot_pages(idx)
            if len(owned) < n:
                return  # reservation narrower than the boundary (can't
                # happen for a prompt that reached p; guard anyway)
            entry = index.insert(pool, prompt, p, tuple(owned[:n]))
            if entry is not None and self._spill_on:
                # hibernation candidate: once idle past spill-idle-s the
                # sweep spills its pages host-side (published prefix pages
                # are stable — positions only grow — so the copy is valid
                # even while the publisher keeps decoding)
                self._spill_candidates.append(entry)
            return
        pool = self._prefix_pool
        if pool is None:
            return
        p = pool.publish_length(len(prompt))
        if p <= 0 or pool.has(prompt, p):
            return
        row = pool.allocate()
        if row is None:
            return  # every row pinned — skip, don't stall admission
        if self._spmd is not None:
            # the allocate/evict decision above is leader-only host state;
            # only the device copy (slot row → pool row) needs the wire
            self._spmd.announce(wire.ControlBlock(
                op=wire.OP_PREFIX_PUBLISH, long_idx=idx, entry_row=row,
            ))
        self._dev_prefix_publish(idx, row)
        pool.insert(prompt, p, row)

    def _dev_prefix_publish(self, idx: int, row: int) -> None:
        """Device layer of the dense copy-on-publish (leader + SPMD
        followers): one jitted gather-scatter, slot cache rows → pool row."""
        from langstream_tpu.ops.kvcopy import publish_prefix_rows

        pool = self._prefix_pool
        self._record_program("prefix-publish")
        pool.dev = publish_prefix_rows(
            pool.dev, self._cache,
            jnp.asarray(idx, jnp.int32), jnp.asarray(row, jnp.int32),
        )

    def _chunk_steps(self) -> int:
        """Power-of-two chunk bounded by every active slot's cache headroom.

        Host positions lag the device by the one in-flight pipelined chunk
        (its results are fetched AFTER the next dispatch), so the bound
        subtracts that chunk's steps — otherwise the tail of a long request
        burns whole chunks on out-of-bounds scatters that XLA drops.

        TTFT lever (overlap OFF only): when admissible work is waiting
        (queued request + a free slot, or a chunked prefill in flight), the
        chunk shrinks so the next admit/segment runs within a few decode
        steps instead of a full chunk — at decode_chunk=64 and ~15ms/step a
        full chunk is ~1s of first-token latency for whoever just arrived.
        Full-size chunks resume once the queue drains (or all slots are
        busy, when admitting sooner is impossible anyway).

        With overlap ON the shrink is RETIRED: the fused scheduler already
        rides a budget of prefill on every iteration, so shrinking buys
        little — and the shrunk size is a whole extra compiled program
        whose first dispatch lands exactly when the first real burst does
        (measured here the same way r5b measured it on the chip: the CPU
        gateway bench's first burst sat ~1.6s behind ONE ('decode', 4, 0)
        compile; on the tunneled chip that stall is 15-23s). Full chunks
        only ⇒ the decode compile surface is the kv_bound ladder, period —
        tail/headroom overshoot lands on OOB scatters XLA drops, and the
        host stops delivering at max_new_tokens / cache end as always.
        The conscious cost: the legacy remaining-tokens clamp is gone too,
        so when EVERY active slot is within decode_chunk of its token
        budget, up to decode_chunk-1 steps of that final chunk are
        dropped-scatter waste — bounded per REQUEST, ≤6% of steps at the
        bench shapes (chunk=16, ≥128 new tokens; under continuous batching
        the max-remaining across slots rarely let the clamp bind anyway),
        but material for big-chunk/short-generation configs (chunk=64,
        max_new=8 wastes ~87% of its one chunk): size decode_chunk to the
        workload, or run overlap=False to get the clamp back."""
        if self.overlap:
            return self.decode_chunk
        want = self.decode_chunk
        if self._longs:
            want = min(want, 8)
        elif self._queue.qsize() > 0 and any(
            not s.active and i not in self._reserved
            for i, s in enumerate(self._slots)
        ):
            want = min(want, self.ttft_chunk_floor)
        # never dispatch (much) past the longest remaining token budget: a
        # full chunk for slots about to finish wastes its tail on device AND
        # sits in front of whatever arrives next (a burst admission right
        # after a lone request drains used to queue ~a full chunk behind it)
        remaining = max(
            (
                s.request.options.max_new_tokens - len(s.generated)
                for s in self._slots
                if s.active and s.request is not None
            ),
            default=1,
        )
        cap = 1
        while cap < remaining:
            cap *= 2
        want = min(want, cap)
        headroom = min(
            self.max_seq_len - 1 - s.position - self._inflight_steps
            for s in self._slots
            if s.active
        )
        # QUANTIZE to exactly two step counts: every distinct (steps,
        # kv_bound) pair is a separate XLA program, and on a tunneled chip
        # a decode compile is ~15-20s — a mid-traffic compile of a novel
        # shrunk size stalled every active stream (measured r5: the 96-
        # session gateway wave sat at 23s p50 TTFT behind ONE steps=4
        # compile). Tail/headroom overshoot is bounded by the floor and
        # lands on OOB scatters XLA drops.
        target = min(want, max(1, headroom))
        if target >= self.decode_chunk:
            return self.decode_chunk
        return min(self.ttft_chunk_floor, self.decode_chunk)

    # -- chunked prefill (long-context) -------------------------------------

    def _long_width(self, prompt_len: int) -> int:
        """Local-cache width for a long prompt: next power of two ≥ the
        prompt (128-aligned for the segment kernel), clamped to max_seq."""
        w = self.prefill_buckets[-1]
        while w < prompt_len:
            w *= 2
        return min(w, self.max_seq_len)

    def _long_step(self, budget: Optional[int] = None) -> tuple[list[tuple], int]:
        """Drive the chunked-prefill streams: start streams for queued long
        requests while slots and stream capacity allow, then dispatch ONE
        segment per active stream per iteration, round-robin, gated by the
        fused-iteration token ``budget`` (at least one segment always rides
        when a stream is active, so progress is guaranteed even with
        budget < segment width). Decode chunks interleave between segments,
        so active generations keep streaming while a 128k prompt prefills.
        Returns (deferred fetch entries, prefill tokens dispatched)."""
        entries: list[tuple] = []
        spent = 0
        width = self.prefill_buckets[-1]
        while self._long_queue and len(self._longs) < self.max_prefill_streams:
            free = next(
                (
                    i
                    for i, s in enumerate(self._slots)
                    if not s.active and i not in self._reserved
                ),
                None,
            )
            if free is None:
                break
            request = self._long_queue.pop(0)
            if not self._prequalify(request):
                continue  # resolved in the long backlog
            if self._agentic and not self._resolve_agentic(request):
                continue  # unknown adapter / pinned pool: request resolved
            if self._paged:
                # paged: reserve the whole prompt's pages up front, aliasing
                # ANY cached prefix boundary (segments write at global
                # offsets, so no full-segment-width alignment constraint —
                # the dense path's local-cache grid is gone). Exhaustion
                # defers the stream; the request keeps its backlog spot.
                base = self._paged_bind(free, request)
                if base is None:
                    self._long_queue.insert(0, request)
                    break
                if base < 0:
                    continue  # can-never-fit: _paged_bind resolved it
                self._reserved.add(free)
                self._longs[free] = {
                    "idx": free, "request": request, "seg": 0, "base": base,
                }
                continue
            # prefix reuse for long prompts (dense): a cached FULL-segment-
            # width prefix lets chunked prefill start at the reuse point
            # (the segment grid stays aligned). A hit prefers the segment
            # loop over the ring path — skipping a whole segment of prefill
            # saves more than the ring's single-dispatch latency win.
            prefix = None
            if self._prefix_pool is not None and not getattr(
                request.options, "adapter", None
            ):
                prefix = self._prefix_lookup(
                    request.prompt_tokens, full_width_only=True
                )
            if (
                prefix is None
                and self._ring_admit is not None
                # the ring admit's fused splice predates adapters/grammars
                # (no lora threading, no first-token mask): AGENTIC
                # requests take the segment loop — which threads both —
                # instead of growing a third ring variant; plain requests
                # keep the one-dispatch ring path unchanged
                and request._dfa is None
                and not getattr(request.options, "adapter", None)
                and self._ring_pad(len(request.prompt_tokens)) is not None
            ):
                # ring path: the whole prompt in ONE sequence-sharded
                # dispatch — it never becomes a stream, but its tokens
                # count against this iteration's prefill budget
                entries.extend(self._ring_step(free, request))
                spent += len(request.prompt_tokens)
                if budget is None or spent >= budget:
                    # overlap off keeps the pre-fusion one-ring-per-
                    # iteration cadence; with a budget, stop once spent
                    return entries, spent
                continue
            self._reserved.add(free)
            st: dict = {"idx": free, "request": request, "seg": 0, "base": 0}
            if prefix is not None:
                p, entry = prefix
                self._prefix_pool.acquire(entry)  # pinned until the gather
                st["base"] = p
                st["prefix"] = entry
            self._longs[free] = st
        if not self._longs:
            return entries, spent
        # round-robin so two concurrent streams alternate segments fairly
        # when the budget covers only one of them per iteration
        order = sorted(self._longs)
        start_at = next(
            (j for j, i in enumerate(order) if i > self._long_rr), 0
        )
        for idx in order[start_at:] + order[:start_at]:
            if budget is not None and (spent or entries) and spent >= budget:
                break
            self._long_rr = idx
            entries.extend(self._segment_step(self._longs[idx]))
            spent += width
        return entries, spent

    def _segment_step(self, st: dict) -> list[tuple]:
        """Dispatch one chunked-prefill segment for one stream; on the
        final segment, activate the slot host-side. A stream whose request
        was cancelled (or blew its deadline) mid-prefill aborts here, before
        spending another segment of prefill on it — host-side only, so SPMD
        followers simply stop receiving its segments."""
        request: GenerationRequest = st["request"]
        now = time.monotonic()
        deadline = request.deadline_at()
        if request.cancelled or (deadline is not None and now >= deadline):
            idx = st["idx"]
            entry = st.pop("prefix", None)
            if entry is not None and self._prefix_pool is not None:
                self._prefix_pool.release(entry)
            if self._paged:
                self._free_slot_pages(idx)
            self._reserved.discard(idx)
            self._longs.pop(idx, None)
            self._long_caches.pop(idx, None)
            if request.cancelled:
                with self._stats_lock:
                    self.cancelled_total += 1
                reason = "cancelled"
            else:
                # mid-PREFILL expiry: zero tokens generated, so this is
                # the waiting bucket (prefill backlog), not mid-decode —
                # the queue/decode split is what operators alert on
                with self._stats_lock:
                    self.deadline_queue_total += 1
                reason = "deadline"
            request._finish(GenerationResult(
                tokens=[], finish_reason=reason,
                prompt_tokens=len(request.prompt_tokens),
                ttft_s=0, total_s=now - request.submitted_at,
            ))
            if self._obs.on:
                emit_request_spans(
                    request.trace_id,
                    {"submitted": request.submitted_at, "finished": now},
                    {
                        "slot": idx,
                        "path": "long",
                        "prompt_len": len(request.prompt_tokens),
                        "generated_tokens": 0,
                        "finish_reason": reason,
                        "prefill_chunks": st["seg"],
                    },
                    status="ok" if reason == "cancelled" else f"error: {reason}",
                )
            return []
        prompt = request.prompt_tokens
        width = self.prefill_buckets[-1]
        # ``base``: prefix-reuse offset (a full segment width when warm) —
        # chunked prefill starts at the reuse point, segments stay aligned
        s0 = st.get("base", 0) + st["seg"] * width
        seg = prompt[s0 : s0 + width]
        tokens = np.zeros((1, width), np.int32)
        tokens[0, : len(seg)] = seg
        opts = request.options
        # static pow2 cap on readable cache columns: segment i never attends
        # past offset+W, so early segments skip streaming the whole cache
        t_long = self._long_width(len(prompt))
        kv_bound = width
        while kv_bound < min(s0 + width, t_long):
            kv_bound *= 2
        kv_bound = min(kv_bound, t_long)
        idx = st["idx"]
        start = st["seg"] == 0
        final = s0 + width >= len(prompt)
        prefix_entry = st.pop("prefix", None)  # only present on start
        if self._spmd is not None:
            self._spmd.announce(wire.ControlBlock(
                op=wire.OP_LONG_SEG, width=width, n_rows=1, tokens=tokens,
                s0=s0, seg_len=len(seg), kv_bound=kv_bound, t_long=t_long,
                long_start=start, long_final=final, long_idx=idx,
                prompt_len=len(prompt),
                # dense warm start: the follower seeds its local cache from
                # the same pool row (paged segments ignore this field)
                entry_row=(
                    prefix_entry.row if prefix_entry is not None else -1
                ),
                temps=np.asarray([opts.temperature], np.float32),
                top_ks=np.asarray([opts.top_k], np.int32),
                top_ps=np.asarray([opts.top_p], np.float32),
            ))
        t_disp = time.monotonic()
        try:
            if self._paged:
                # straight into the slot's pages: no local cache, no final
                # insert/splice — the chain scatter on ``final`` is the only
                # extra dispatch, and kv_bound/t_long do not exist here
                first = self._dev_paged_segment(
                    tokens, s0, len(seg), idx,
                    opts.temperature, opts.top_k, opts.top_p,
                    final=final, prompt_len=len(prompt),
                    agentic_rows=request._agentic_rows,
                )
            else:
                first = self._dev_long_segment(
                    tokens, s0, len(seg), kv_bound, t_long,
                    opts.temperature, opts.top_k, opts.top_p,
                    start=start, final=final, idx=idx, prompt_len=len(prompt),
                    prefix_row=(
                        prefix_entry.row if prefix_entry is not None else None
                    ),
                    agentic_rows=request._agentic_rows,
                )
        except Exception as e:  # noqa: BLE001 — fail the request, not the engine
            if self._spmd is not None:
                raise  # multi-host: crash the replica (see _admit rationale)
            log.exception("chunked prefill failed at segment %d", st["seg"])
            if self._paged:
                self._free_slot_pages(idx)
            self._reserved.discard(idx)
            self._longs.pop(idx, None)
            self._long_caches.pop(idx, None)
            request._finish(GenerationResult(
                tokens=[], finish_reason="error", prompt_tokens=0,
                ttft_s=0, total_s=0, error=e,
            ))
            return []
        finally:
            if prefix_entry is not None:
                self._prefix_pool.release(prefix_entry)
        if prefix_entry is not None:
            self._prefix_pool.tokens_saved += st.get("base", 0)
        st["seg"] += 1
        if self._obs.on:
            self._obs.record(
                "engine_prefill_dispatch_s", time.monotonic() - t_disp
            )
        self._prefill_tokens_dispatched += len(seg)
        if not final:
            return []  # more segments to go

        # final segment landed on device: activate the slot host-side
        self._longs.pop(idx, None)
        self._reserved.discard(idx)
        slot = self._slots[idx]
        slot.request = request
        slot.position = len(prompt)
        slot.generated = []
        slot.started_at = time.monotonic()
        slot.first_token_at = 0.0
        slot.reset_obs("long", st["seg"])
        self._slot_bind_agentic(idx, request)
        with self._stats_lock:
            self.total_requests += 1
        self._note_tenant_admitted(request)
        self._spec_admit(idx, prompt)
        self._maybe_publish(idx, prompt)
        return [("prefill", self._fetcher.submit(first), [(idx, request)])]

    def _ring_pad(self, prompt_len: int) -> Optional[int]:
        """Padded width for the ring path: |seq| pow2-sized blocks (O(log)
        compiled shapes). None when that padding cannot fit max_seq_len —
        the caller falls back to the single-dispatch-per-segment loop, which
        has no divisibility constraint."""
        n = self.mesh.shape["seq"]
        block = 128
        while block * n < prompt_len:
            block *= 2
        s_pad = block * n
        return s_pad if s_pad <= self.max_seq_len else None

    def _ring_step(self, idx: int, request: GenerationRequest) -> list[tuple]:
        """One-dispatch ring long-prefill: run the fused ring admit and
        activate the slot. Decode chunks for other slots resume next
        iteration. On a multi-host replica the leader first streams the
        padded prompt to the followers in fixed-shape chunks (OP_RING) so
        every process makes the identical dispatch."""
        prompt = request.prompt_tokens
        s_pad = self._ring_pad(len(prompt))
        assert s_pad is not None  # caller checked
        tokens = np.zeros((1, s_pad), np.int32)
        tokens[0, : len(prompt)] = prompt
        opts = request.options
        if self._spmd is not None:
            self._announce_ring(tokens, len(prompt), opts, idx)
        try:
            first = self._dev_ring(
                tokens, len(prompt),
                opts.temperature, opts.top_k, opts.top_p, idx,
            )
        except Exception as e:  # noqa: BLE001 — fail the request, not the engine
            if self._spmd is not None:
                raise  # multi-host: crash the replica (see _admit rationale)
            log.exception("ring prefill failed")
            request._finish(GenerationResult(
                tokens=[], finish_reason="error", prompt_tokens=0,
                ttft_s=0, total_s=0, error=e,
            ))
            return []
        slot = self._slots[idx]
        slot.request = request
        slot.position = len(prompt)
        slot.generated = []
        slot.started_at = time.monotonic()
        slot.first_token_at = 0.0
        slot.reset_obs("ring", 1)
        with self._stats_lock:
            self.total_requests += 1
        self._note_tenant_admitted(request)
        self._spec_admit(idx, prompt)
        self._maybe_publish(idx, prompt)
        return [("prefill", self._fetcher.submit(first), [(idx, request)])]

    def _announce_ring(self, tokens: np.ndarray, prompt_len: int, opts, idx: int) -> None:
        """Stream the PROMPT (not its pow2 padding — the follower derives
        the identical _ring_pad locally and zero-pads itself) over the
        fixed-shape SPMD channel in (prefill_batch × max_width)-token
        chunks; the final chunk carries the sampling params and fires the
        follower's _dev_ring."""
        flat = tokens.reshape(-1)[:prompt_len]
        chunk_cap = self._spmd.prefill_batch * self._spmd.max_width
        total = len(flat)
        for start in range(0, total, chunk_cap):
            piece = flat[start : start + chunk_cap]
            rows = -(-len(piece) // self._spmd.max_width)
            padded = np.zeros(rows * self._spmd.max_width, np.int32)
            padded[: len(piece)] = piece
            self._spmd.announce(wire.ControlBlock(
                op=wire.OP_RING,
                width=self._spmd.max_width,
                n_rows=rows,
                tokens=padded.reshape(rows, self._spmd.max_width),
                seg_len=len(piece),
                long_start=start == 0,
                long_final=start + chunk_cap >= total,
                long_idx=idx,
                prompt_len=prompt_len,
                temps=np.asarray([opts.temperature], np.float32),
                top_ks=np.asarray([opts.top_k], np.int32),
                top_ps=np.asarray([opts.top_p], np.float32),
            ))

    def _dev_ring(
        self, tokens: np.ndarray, prompt_len: int,
        temperature: float, top_k: int, top_p: float, idx: int,
    ):
        """Device layer of the ring admit (leader + SPMD followers): the
        fused sequence-sharded prefill + cache splice + decode-chain
        scatters, identical on every process."""
        self._record_program("ring", tokens.shape[1])
        meta = np.asarray(
            [[prompt_len], [temperature], [top_k], [top_p]], np.float32
        )
        (
            first,
            self._cache,
            self._tokens_dev,
            self._positions_dev,
            self._temp_dev,
            self._top_k_dev,
            self._top_p_dev,
            self._key,
        ) = self._ring_admit(
            self.params,
            self._cache,
            self._tokens_dev,
            self._positions_dev,
            self._temp_dev,
            self._top_k_dev,
            self._top_p_dev,
            self._key,
            jnp.asarray(tokens),
            jnp.asarray(meta),
            jnp.asarray(np.full(1, idx, np.int32)),
            self.config,
        )
        return first

    def _dev_long_segment(
        self, tokens, s0, seg_len, kv_bound, t_long, temperature, top_k, top_p,
        *, start: bool, final: bool, idx: int, prompt_len: int,
        prefix_row: Optional[int] = None, agentic_rows=None,
    ):
        """Device layer of one chunked-prefill segment (leader + SPMD
        followers): fresh local cache on ``start`` (seeded from pool row
        ``prefix_row`` on a warm start — the stream's first segment then
        begins at the reuse offset), segment forward, and on ``final`` the
        splice into the big cache + decode-chain scatters."""
        if self._injector is not None:
            self._injector.fire("segment")
        if start:
            if prefix_row is not None:
                from langstream_tpu.ops.kvcopy import gather_prefix_local

                self._record_program("prefix-gather", t_long)
                local_cache = gather_prefix_local(
                    self._prefix_pool.dev,
                    jnp.asarray(prefix_row, jnp.int32),
                    self.config,
                    t_long,
                )
            else:
                local_cache = make_kv_cache(self.config, 1, t_long)
            if self.mesh is not None:
                from langstream_tpu.parallel.sharding import shard_serving_cache

                local_cache = shard_serving_cache(local_cache, self.mesh)
            self._long_caches[idx] = local_cache
        self._record_program("segment", tokens.shape[1], kv_bound, t_long)
        kw = self._segment_agentic_kwargs(
            agentic_rows, idx if final else self.max_batch
        )
        first, self._long_caches[idx], self._key, state_dev = (
            _prefill_segment_and_sample(
                self.params,
                jnp.asarray(tokens),
                jnp.asarray([s0], jnp.int32),
                jnp.asarray([seg_len], jnp.int32),
                self._long_caches[idx],
                self._key,
                jnp.asarray([temperature], jnp.float32),
                jnp.asarray([top_k], jnp.int32),
                jnp.asarray([top_p], jnp.float32),
                self.config,
                kv_bound,
                **kw,
            )
        )
        if state_dev is not None:
            self._dfa_state_dev = state_dev
        if final:
            slots_dev = jnp.asarray(np.full(1, idx, np.int32))
            self._record_program("insert", t_long)
            self._cache = self._insert_group(
                self._cache, self._long_caches.pop(idx), slots_dev
            )
            self._record_program("chain-scatter")
            (
                self._tokens_dev, self._positions_dev, self._temp_dev,
                self._top_k_dev, self._top_p_dev,
            ) = _chain_scatter(
                self._tokens_dev, self._positions_dev, self._temp_dev,
                self._top_k_dev, self._top_p_dev,
                jnp.asarray(idx, jnp.int32), first, prompt_len,
                temperature, top_k, top_p,
            )
        return first

    def _dispatch_chunk(self, clean: bool = True, pipelined: bool = False) -> tuple:
        """Dispatch one multi-step decode; returns (device tokens,
        per-slot request snapshot, steps, dispatch time, clean, pipelined)
        for deferred host processing. ``clean``: no prefill dispatch rode
        the in-order stream ahead of this chunk in the same iteration —
        only clean chunks feed the step-time EMA, else the gauge charges
        prefill wall-time to decode and under-reports achieved bandwidth
        exactly when prefill overlaps. ``pipelined``: earlier chunks were
        still in flight at dispatch, so the EMA samples the
        inter-completion interval instead of dispatch→ready wall (which
        would read ~2× at steady state, the predecessor's remaining
        execution counted into this chunk's)."""
        if self._paged:
            # validate BEFORE the announce: a quarantine here frees pages
            # (announced as OP_PAGE_FREE) and deactivates the slot, and the
            # mask announced below must already reflect both
            self._page_integrity_check()
        self._adapter_integrity_check()
        steps = self._chunk_steps()
        # shrunk (non-full) chunks run UNBOUNDED: pairing the occasional
        # short chunk with the kv_bound ladder would multiply the compiled-
        # program count (steps × bounds); a few full-width steps cost ~10ms
        # extra read, a novel program costs a ~15-20s compile stall.
        # Paged layout: no bound at all — the page table is the bound, and
        # the decode surface is ONE program per step count.
        kv_bound = (
            None
            if self._paged
            else self._decode_kv_bound(steps)
            if steps == self.decode_chunk
            else None
        )
        stale = self._collect_stale()
        mask = self._active_mask()
        if self._spmd is not None:
            self._spmd.announce(wire.ControlBlock(
                op=wire.OP_DECODE, steps=steps, n_rows=len(stale),
                slots=np.asarray(stale, np.int32),
                # unbounded (shrunk) chunks ride as 0 — the int32 wire
                # header can't carry None; followers decode 0 back to None
                kv_bound=kv_bound or 0,
                # slot liveness is leader-only host state (completions are
                # discovered at fetch time): ship the mask so followers
                # sentinel the same page-table rows
                mask=mask,
            ))
        chunk = self._dev_decode(steps, stale, kv_bound, mask=mask)
        snapshot = [
            (i, slot.request) for i, slot in enumerate(self._slots) if slot.active
        ]
        with self._stats_lock:
            self._busy_steps += steps
        self._last_kv_bound = kv_bound or self.max_seq_len
        # hand the chunk to the fetch thread NOW: it blocks on the bytes
        # while this thread keeps dispatching — the ~100ms tunnel fetch is
        # hidden at every chunk size, not only when chunk compute covers it
        return (
            "chunk", self._fetcher.submit(chunk), snapshot, steps,
            time.monotonic(), clean, pipelined,
        )

    def _collect_stale(self) -> list[int]:
        """Slots freed since the last dispatch whose device temperature
        must be reset — skipping slots re-admitted meanwhile (admit runs
        before dispatch and already wrote their fresh params). ONE
        definition shared by the decode and verify dispatch paths so the
        re-admitted-slot rule cannot drift between them."""
        if not self._freed_slots:
            return []
        stale = [i for i in set(self._freed_slots) if not self._slots[i].active]
        self._freed_slots.clear()
        return stale

    def _reset_stale_temps(self, stale) -> None:
        """Fixed-size all-or-out-of-bounds temp-reset scatter (padding rows
        drop) — one compiled shape regardless of how many slots freed. The
        eager scatter is its own device program: recorded, because the
        compiled_programs guarantee must not have blind spots; the warmups
        dispatch one all-OOB reset so its first real use is never a
        mid-traffic compile. Shared by _dev_decode and _dev_verify."""
        self._record_program("temp-reset")
        idxs = np.full(self.max_batch, self.max_batch, np.int32)
        idxs[: len(stale)] = stale
        self._temp_dev = self._temp_dev.at[jnp.asarray(idxs)].set(0.0, mode="drop")

    def _decode_kv_bound(self, steps: int) -> int:
        """Static pow2 cap on readable cache columns for this chunk: decode
        is cache-READ-bandwidth-bound and the masked read otherwise streams
        the full max_seq_len width for every step (measured r5, llama-3-8b
        int8 B=96: 27.9ms/step at T=256 vs 61.8 at T=1024). Device
        positions lead host positions by the in-flight pipelined chunks, so
        the bound covers max host position + inflight + this chunk. The
        pow2 ladder (_kv_bound_ladder — the same rungs both warmups
        compile) keeps the compile count at O(log2 T)."""
        highest = max(
            (s.position for s in self._slots if s.active), default=0
        )
        needed = highest + self._inflight_steps + steps
        for bound in _kv_bound_ladder(self.max_seq_len):
            if bound >= needed:
                return bound
        return self.max_seq_len

    def _dev_decode(
        self, steps: int, stale, kv_bound: Optional[int] = None,
        mask: Optional[np.ndarray] = None,
    ) -> Any:
        """Device layer of one decode chunk (leader + SPMD followers).
        ``mask``: the dispatch's active-slot liveness (paged table
        masking); None derives it from the local slots — followers always
        pass the leader's wire-shipped mask."""
        if self._injector is not None:
            self._injector.fire("decode")  # crashes the loop → restart path
        lora, arows, dfa, g = self._agentic_args()
        dstate = self._dfa_state_dev
        if self._paged:
            self._record_program("paged-decode", steps)
            if len(stale):
                self._reset_stale_temps(stale)
            pool = self._pagepool
            (
                chunk,
                self._tokens_dev,
                self._positions_dev,
                pool.dev,
                self._key,
                dstate,
            ) = _paged_decode_chunk(
                self.params,
                self._tokens_dev,
                self._positions_dev,
                pool.dev,
                jnp.asarray(self._dispatch_tables(mask)),
                self._key,
                self._temp_dev,
                self._top_k_dev,
                self._top_p_dev,
                steps,
                self.config,
                self.page_size,
                lora,
                arows,
                dfa,
                g,
                dstate,
            )
            if dstate is not None:
                self._dfa_state_dev = dstate
            return chunk
        self._record_program("decode", steps, kv_bound or 0)
        if len(stale):
            self._reset_stale_temps(stale)
        (
            chunk, self._tokens_dev, self._positions_dev, self._cache,
            self._key, dstate,
        ) = _decode_chunk(
            self.params,
            self._tokens_dev,
            self._positions_dev,
            self._cache,
            self._key,
            self._temp_dev,
            self._top_k_dev,
            self._top_p_dev,
            steps,
            self.config,
            kv_bound,
            lora,
            arows,
            dfa,
            g,
            dstate,
        )
        if dstate is not None:
            self._dfa_state_dev = dstate
        return chunk

    def _dispatch_verify(self, clean: bool = True) -> tuple:
        """Dispatch one self-speculative verify iteration: collect up to k
        drafts per active slot from its n-gram index (host-side, free), run
        _verify_chunk, and return the deferred-fetch entry. Slots whose
        index has no proposal ride the fixed-shape dispatch with zero
        drafts — their verify degenerates to a 1-token decode step (the
        accept test compares against the model's own outputs, so a bad or
        empty draft can never change what is emitted)."""
        if self._paged:
            self._page_integrity_check()  # before the announce (see chunk)
        self._adapter_integrity_check()
        k = self.spec_tokens
        # brownout level 1 (spec-shrink) proposes fewer drafts — data,
        # not shape, so the compiled verify program never changes (§19)
        k_prop = (
            self._brownout.draft_k(k) if self._brownout is not None else k
        )
        kv_bound = 0 if self._paged else self._decode_kv_bound(k + 1)
        stale = self._collect_stale()
        drafts = np.zeros((self.max_batch, k), np.int32)
        proposed = np.zeros(self.max_batch, np.int32)
        for i, slot in enumerate(self._slots):
            if not slot.active:
                continue
            index = self._spec_index.get(i)
            if index is None:
                continue
            prop = index.propose(k_prop)
            with self._stats_lock:
                self.spec_draft_lookups_total += 1
                if prop:
                    self.spec_draft_hits_total += 1
                    self.spec_draft_tokens_total += len(prop)
            if prop:
                drafts[i, : len(prop)] = prop
                proposed[i] = len(prop)
        vstates = None
        if self._constrain_reg is not None:
            # per-position DFA states for the verify masks, from the HOST
            # mirror — spec mode drains the pipeline before proposing, so
            # the mirror is current at dispatch time (the invariant that
            # makes host-computed states legal here)
            t0 = time.monotonic()
            vstates = np.zeros((self.max_batch, k + 1), np.int32)
            from langstream_tpu.serving.constrain import verify_states

            for i, slot in enumerate(self._slots):
                dfa_i = self._slot_dfa.get(i)
                if not slot.active or dfa_i is None:
                    continue
                vstates[i] = verify_states(
                    dfa_i, self._dfa_host_state.get(i, 0), drafts[i]
                )
            self._note_constrain_host((time.monotonic() - t0) * 1e3)
        mask = self._active_mask()
        if self._spmd is not None:
            # speculation on the wire: ship the PROPOSALS (steps = k, the
            # drafts-per-slot width) — acceptance is computed on device,
            # identically on every host, so accepts need no forward wire
            self._spmd.announce(wire.ControlBlock(
                op=wire.OP_VERIFY, steps=k, n_rows=len(stale),
                slots=np.asarray(stale, np.int32), kv_bound=kv_bound,
                drafts=drafts, mask=mask,
            ))
        packed = self._dev_verify(
            drafts, stale, kv_bound, mask=mask, vstates=vstates
        )
        snapshot = [
            (i, slot.request) for i, slot in enumerate(self._slots) if slot.active
        ]
        with self._stats_lock:
            self._busy_steps += 1
            self.spec_dispatches_total += 1
        self._last_kv_bound = kv_bound
        return (
            "verify", self._fetcher.submit(packed), snapshot, proposed,
            time.monotonic(), clean,
        )

    def _dev_verify(
        self, drafts: np.ndarray, stale, kv_bound: int,
        mask: Optional[np.ndarray] = None,
        vstates: Optional[np.ndarray] = None,
    ) -> Any:
        """Device layer of one verify iteration — the speculative engine's
        only decode-phase dispatch, so the decode fault site fires here
        (crash/restart drills hold under speculation too; the corrupt-type
        ``verify`` site fires host-side at fetch processing instead, where
        it can target ONE slot). ``vstates``: host-computed per-position
        DFA states (None → all-zero table, what the warmups dispatch)."""
        if self._injector is not None:
            self._injector.fire("decode")
        lora, arows, dfa, g = self._agentic_args()
        vstates_dev = None
        if dfa is not None:
            if vstates is None:
                vstates = np.zeros(
                    (self.max_batch, drafts.shape[1] + 1), np.int32
                )
            vstates_dev = jnp.asarray(vstates)
        if self._paged:
            self._record_program("paged-verify", drafts.shape[1])
            if len(stale):
                self._reset_stale_temps(stale)
            pool = self._pagepool
            (
                packed,
                self._tokens_dev,
                self._positions_dev,
                pool.dev,
                self._key,
                dstate,
            ) = _paged_verify_chunk(
                self.params,
                self._tokens_dev,
                self._positions_dev,
                pool.dev,
                jnp.asarray(self._dispatch_tables(mask)),
                self._key,
                self._temp_dev,
                self._top_k_dev,
                self._top_p_dev,
                jnp.asarray(drafts),
                self.config,
                self.page_size,
                lora,
                arows,
                dfa,
                g,
                vstates_dev,
            )
            if dstate is not None:
                self._dfa_state_dev = dstate
            return packed
        self._record_program("verify", drafts.shape[1], kv_bound or 0)
        if len(stale):
            self._reset_stale_temps(stale)
        (
            packed,
            self._tokens_dev,
            self._positions_dev,
            self._cache,
            self._key,
            dstate,
        ) = _verify_chunk(
            self.params,
            self._tokens_dev,
            self._positions_dev,
            self._cache,
            self._key,
            self._temp_dev,
            self._top_k_dev,
            self._top_p_dev,
            jnp.asarray(drafts),
            self.config,
            kv_bound,
            lora,
            arows,
            dfa,
            g,
            vstates_dev,
        )
        if dstate is not None:
            self._dfa_state_dev = dstate
        return packed

    def _process_verify(self, entry: tuple) -> None:
        """Host half of a verify iteration: one packed fetch ([B, k+2] =
        emitted tokens ++ accepted count), then per-slot delivery of
        accepted+1 tokens through the same _deliver_token path as decode
        chunks (stop/length/cancel/deadline/NaN-sentinel all behave
        identically mid-verify)."""
        _, packed, snapshot, proposed, t_dispatch, clean = entry
        host = self._fetch_result(packed)
        # divergence echo BEFORE the injector's host-side corruption: the
        # echo is the DEVICE truth both sides must agree on — a leader-host
        # corruption drill must not read as an SPMD divergence
        self._spmd_echo(wire.ECHO_VERIFY, host)
        if self._injector is not None:
            host = self._injector.corrupt_verify(host, snapshot)
        # step-time gauge BEFORE delivery (same race rationale as
        # _sample_step_time): a verify iteration is ONE weight read (that
        # is the point), so it samples as one step; spec mode drains
        # before dispatching, so dispatch→ready wall is honest here
        now = time.monotonic()
        if snapshot and clean:
            step_s = now - t_dispatch
            self._step_time_ema_s = (
                step_s
                if self._step_time_ema_s == 0
                else 0.9 * self._step_time_ema_s + 0.1 * step_s
            )
            if self._obs.on:
                self._obs.record("engine_decode_step_s", step_s)
        self._last_chunk_ready_t = now
        out, accept = host[:, :-1], host[:, -1]
        for idx, request in snapshot:
            slot = self._slots[idx]
            if slot.request is not request:  # freed/reassigned meanwhile
                continue
            slot.verify_iters += 1
            t_prev = slot.last_token_at
            n_acc = int(accept[idx])
            with self._stats_lock:
                if proposed[idx] > 0:
                    # capped at the real proposal length: padding zeros that
                    # happen to match the model are luck, not draft quality,
                    # and would push the acceptance gauge past 1.0
                    self.spec_accepted_tokens_total += min(
                        n_acc, int(proposed[idx])
                    )
                self.spec_slot_steps_total += 1
            delivered = 0
            for j in range(n_acc + 1):
                slot.position += 1
                token = int(out[idx, j])
                if token >= 0:
                    # counted per token actually DELIVERED — a request that
                    # finishes mid-verify (length/stop/deadline) drops the
                    # rest, and the NaN sentinel is a quarantine, not a
                    # token; counting n_acc+1 up front overstated the
                    # amortization gauge exactly on short-generation,
                    # high-acceptance traffic
                    with self._stats_lock:
                        self.spec_emitted_tokens_total += 1
                    delivered += 1
                self._deliver_token(idx, token)
                if slot.request is not request:  # finished mid-verify
                    break
            if self._obs.on and delivered:
                self._obs.record("engine_accepted_tokens_per_step", delivered)
            self._record_intertoken(slot, request, t_prev, delivered)

    def _process_chunk(
        self, chunk, snapshot, steps: int, t_dispatch: float = 0.0,
        clean: bool = False, pipelined: bool = False,
    ) -> None:
        # [steps, B], fetched by the fetch thread (wait watchdog-bounded
        # under SPMD — see _fetch_result)
        host = self._fetch_result(chunk)
        # gauge BEFORE delivery: see _sample_step_time's rationale
        self._sample_step_time(snapshot, steps, t_dispatch, clean, pipelined)
        self._spmd_echo(wire.ECHO_DECODE, host)  # before host-side corruption
        if self._injector is not None:
            host, _ = self._injector.corrupt_tokens(host, snapshot)
        for idx, request in snapshot:
            slot = self._slots[idx]
            if slot.request is not request:  # freed/reassigned meanwhile
                continue
            slot.decode_iters += 1
            t_prev = slot.last_token_at
            delivered = 0
            for s in range(steps):
                slot.position += 1
                self._deliver_token(idx, int(host[s, idx]))
                delivered += 1
                if slot.request is not request:  # finished mid-chunk
                    break
            self._record_intertoken(slot, request, t_prev, delivered)

    def _record_intertoken(
        self, slot: _Slot, request: GenerationRequest, t_prev: float,
        delivered: int,
    ) -> None:
        """One inter-token sample per slot per processed chunk: the MEAN
        per-token gap across the chunk ((now - previous chunk's clock) /
        tokens delivered). Deliberately chunk-granular, not per-token —
        in-chunk host gaps are ~µs noise while the chunk boundary carries
        the real dispatch+fetch interval, and per-token monotonic+record
        was the single biggest hot-loop instrumentation cost (measured
        1.0µs/token ≈ 1.6% of a tiny-model CPU step — over the §12 ≤1%
        bound this code ships under)."""
        if not self._obs.on or not delivered:
            return
        now_t = time.monotonic()
        if t_prev:
            self._obs.record("engine_intertoken_s", (now_t - t_prev) / delivered)
        if slot.request is request:  # not freed mid-chunk
            slot.last_token_at = now_t

    def _note_tenant_admitted(self, request: GenerationRequest) -> None:
        """Tenant attribution + token-rate charge for one admission: the
        prompt's prefill tokens bill the tenant's quota bucket the moment
        the slot activates (generated tokens bill per delivery)."""
        self._tenants.note_admitted(
            getattr(request.options, "tenant", None) or DEFAULT_TENANT,
            len(request.prompt_tokens),
        )

    def _deliver_token(self, idx: int, token: int) -> None:
        slot = self._slots[idx]
        request = slot.request
        assert request is not None
        opts = request.options

        if token < 0:
            # sampling's NaN guard sentinel: this slot's logits went
            # non-finite. Quarantine ONLY this slot — fail its request,
            # zero its KV rows/pages (next iteration, one coalesced
            # dispatch) — while every other slot keeps decoding untouched.
            # SPMD replicas quarantine victim-only too since round 13: the
            # row-reset / page-free / page-zero dispatches ride the wire,
            # so a poisoned slot degrades one request, not the replica
            # (docs/SERVING.md §14).
            with self._stats_lock:
                self.nan_guard_total += 1
                self.quarantined_slots_total += 1
            if self._paged:
                # pages, not rows: evict prefix entries sharing the slot's
                # pages, free them through the owned list, zero next flush
                self._quarantine_pages(idx)
            else:
                self._pending_row_resets.append(idx)
            # the postmortem artifact: the last N iterations that LED here
            # (batch mix, pages, programs, injector firings) — the evidence
            # a counter bump discards
            self._flight_dump("nan-quarantine", extra={"slot": idx})
            self._finish_slot(
                idx, "error",
                error=LogitsNaNError(
                    f"non-finite logits for slot {idx}; slot quarantined and "
                    "its KV rows reset"
                ),
            )
            return
        if request.cancelled:
            # chunk-boundary cancellation: the slot frees NOW; tokens from
            # the rest of this (and any in-flight) chunk are dropped by the
            # snapshot identity check
            with self._stats_lock:
                self.cancelled_total += 1
            self._finish_slot(idx, "cancelled")
            return
        deadline = request.deadline_at()
        if deadline is not None and time.monotonic() >= deadline:
            with self._stats_lock:
                self.deadline_decode_total += 1
            self._tenants.note_deadline(
                getattr(opts, "tenant", None) or DEFAULT_TENANT
            )
            self._finish_slot(idx, "deadline")
            return
        if self._injector is not None:
            self._injector.stall("client")  # slow-client backpressure drill

        finished_reason = None
        is_stop = (self.eos_token_id is not None and token == self.eos_token_id) or (
            token in opts.stop_tokens
        )
        if is_stop:
            finished_reason = "stop"
        else:
            slot.generated.append(token)
            index = self._spec_index.get(idx)
            if index is not None:
                # the emitted token joins the slot's draft context — the
                # next iteration's proposals continue from it
                index.append(token)
            dfa = self._slot_dfa.get(idx)
            if dfa is not None:
                # the HOST half of constrained decoding: mirror the device's
                # DFA advance per delivered token (same table → lockstep),
                # and finish with "stop" the moment the grammar COMPLETES —
                # tokens the device's sink self-loop generates after this
                # point are dropped by the snapshot identity check, so the
                # delivered text is exactly one grammar derivation
                s = dfa.advance(self._dfa_host_state.get(idx, 0), token)
                if s < 0:
                    # unreachable while host and device share the table;
                    # reaching it means state corruption — off-grammar
                    # output must fail loudly, never stream on
                    self._finish_slot(
                        idx, "error",
                        error=RuntimeError(
                            f"constrained decode diverged at slot {idx}: "
                            f"token {token} is illegal in DFA state "
                            f"{self._dfa_host_state.get(idx, 0)}"
                        ),
                    )
                    return
                self._dfa_host_state[idx] = s
                # mirror onto the request BEFORE on_token below fires: a
                # stream callback reading dfa_state inside on_token sees
                # the state matching this token — what the fleet wire's
                # tokens frames carry for mid-derivation resume (§18)
                request.dfa_state = s
                if dfa.is_complete(s):
                    finished_reason = "stop"
            with self._stats_lock:
                self.total_generated += 1
            self._tenants.note_generated(
                getattr(opts, "tenant", None) or DEFAULT_TENANT
            )
            if request.on_token is not None:
                try:
                    request.on_token(token)
                except Exception:  # noqa: BLE001 — stream consumer must not kill the loop
                    log.exception("on_token callback failed")
            # the request's max_cost_tokens budget (prompt + generated)
            # caps the generation length alongside max_new_tokens (§19)
            if finished_reason is None and len(slot.generated) >= (
                effective_max_new_tokens(opts, len(request.prompt_tokens))
            ):
                finished_reason = "length"
            elif finished_reason is None and slot.position >= self.max_seq_len - 1:
                # cache full — scattering past the buffer would silently drop
                finished_reason = "length"

        if finished_reason is not None:
            self._finish_slot(idx, finished_reason)

    def _finish_slot(
        self, idx: int, reason: str, error: Optional[BaseException] = None
    ) -> None:
        """Resolve the slot's request and free the slot (temp reset rides
        the next dispatch via _freed_slots, as for natural completions)."""
        slot = self._slots[idx]
        request = slot.request
        assert request is not None
        now = time.monotonic()
        pages_held = (
            len(self._pagepool.slot_pages(idx)) if self._paged else 0
        )
        result = GenerationResult(
            tokens=list(slot.generated),
            finish_reason=reason,
            prompt_tokens=len(request.prompt_tokens),
            ttft_s=(
                slot.first_token_at - request.submitted_at
                if slot.first_token_at
                else 0.0
            ),
            total_s=now - request.submitted_at,
            error=error,
        )
        stamps = {
            "submitted": request.submitted_at,
            "admitted": slot.started_at or None,
            "first_token": slot.first_token_at or None,
            "finished": now,
        }
        attrs = {
            "slot": idx,
            "path": slot.path,
            "prompt_len": len(request.prompt_tokens),
            "generated_tokens": len(slot.generated),
            "finish_reason": reason,
            "prefill_chunks": slot.prefill_chunks,
            "decode_iterations": slot.decode_iters,
            "verify_dispatches": slot.verify_iters,
            "kv_pages": pages_held,
        }
        # release the slot and its pages BEFORE resolving the request: the
        # waiter wakes inside _finish, and anything it reads right away —
        # free-page counts, active-slot counts, stats() — must already
        # reflect the completion (sampled pool state mid-teardown is how
        # the page-leak test flaked when span emission sat in this gap)
        slot.request = None
        slot.generated = []
        slot.position = 0
        slot.last_token_at = 0.0
        self._spec_index.pop(idx, None)
        self._slot_clear_agentic(idx)
        self._freed_slots.append(idx)
        if self._paged:
            # slot reset = free its table (shared pages survive through the
            # prefix index's refcounts; exclusive ones return to the pool)
            self._free_slot_pages(idx)
        request._finish(result)
        if self._obs.on:
            # the request's whole lifecycle becomes ONE span tree here —
            # a single emission per request, nothing on the token loop
            emit_request_spans(
                request.trace_id, stamps, attrs,
                status="ok" if error is None else f"error: {type(error).__name__}",
            )

    def _fail_all(self, error: BaseException) -> None:
        self._dead = error

        def dead_result() -> GenerationResult:
            return GenerationResult(
                tokens=[], finish_reason="error", prompt_tokens=0,
                ttft_s=0, total_s=0, error=error,
            )

        # collect every in-flight request, TEAR DOWN FIRST, resolve last:
        # _finish wakes waiters immediately, and a waiter sampling engine
        # state (active slots, stats(), long-stream dicts) must never see
        # its own request still wired into a half-torn slot (the same
        # ordering rule _finish_slot and _recover follow)
        doomed: list[GenerationRequest] = []
        if self._held_back is not None:
            doomed.append(self._held_back)
            self._held_back = None
        for st in self._longs.values():
            entry = st.pop("prefix", None)
            if entry is not None and self._prefix_pool is not None:
                self._prefix_pool.release(entry)
            doomed.append(st["request"])
        self._longs.clear()
        self._long_caches.clear()
        doomed.extend(self._long_queue)
        self._long_queue.clear()
        doomed.extend(self._page_deferred)
        self._page_deferred.clear()
        self._reserved.clear()
        self._spec_index.clear()
        for i, slot in enumerate(self._slots):
            if slot.request is not None:
                doomed.append(slot.request)
                slot.request = None
                slot.generated = []
                slot.position = 0
                self._slot_clear_agentic(i)
        while True:
            try:
                doomed.append(self._queue.get_nowait())
            except queue.Empty:
                break
        with self._waiting_lock:
            self._waiting.clear()
        for request in doomed:
            request._finish(dead_result())
