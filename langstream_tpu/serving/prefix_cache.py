"""Automatic prefix KV-cache reuse: radix index + device-resident pool.

Nearly every chat request opens with the same system prompt / few-shot
preamble, yet a plain admission re-prefills it from token zero every time.
This module gives the serving engine a cross-request prefix cache:

- **Host side** (`PrefixCachePool` + its radix trie): an index over token
  sequences keyed at *prefill-bucket-aligned* boundaries. Edges are the
  token runs between consecutive bucket widths (32, 64, 128, … — exactly
  the widths the admission programs already compile for), so a cached
  prefix is always a shape the engine can extend with existing programs:
  the suffix prefills as one `prefill_segment` starting at the reuse point.
- **Device side**: a KV pool in the SAME `[L, B_pool, Hkv, T_pool, D]`
  layout as the slot caches (bf16 and int8+scales variants both work —
  `make_kv_cache` builds it), `T_pool` = the largest prefill bucket. One
  pool row holds one cached prefix. Copies in/out are the two jitted
  helpers in `ops/kvcopy.py` (traced row indices: one program each).

Semantics that keep reuse EXACT (tested token-for-token vs cold runs):
prefix KV is a pure function of the prefix tokens (causal attention), so a
published row equals what a fresh prefill would write — including the int8
cache, where publish copies the already-quantized values untouched. Columns
past a prefix's true length carry garbage by design; the engine's masking
invariant (columns beyond the written frontier never enter an attention
mask until overwritten) makes that safe, the same way bucket padding is.

Eviction is LRU over unreferenced entries only: `acquire`/`release`
refcounts pin entries for the span of the admission dispatch that reads
them, and `allocate` never evicts a pinned row. All methods run on the
engine thread — no locking.

Cross-request reuse papers this follows: DeepServe (arxiv 2501.14417) and
STREAM (arxiv 2606.13968) both lean on prefix KV reuse to hold TTFT under
shared-preamble load; the bucket-aligned twist here is what keeps the
compile surface identical to the engine's existing ladder.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Optional

import jax

from langstream_tpu.serving.pagepool import prefix_digest


def pool_entries_for_fraction(
    max_batch: int, max_seq_len: int, pool_width: int, fraction: float,
    *, cap: int = 512,
) -> int:
    """Pool rows whose total token capacity ≈ ``fraction`` of the decode
    cache's (max_batch × max_seq_len tokens) — cache bytes scale linearly
    with token capacity, so this is the `prefix-cache-fraction` knob's
    arithmetic. Floored at 2 (a 1-row pool thrashes on its first eviction),
    capped so tiny-bucket configs don't index thousands of rows."""
    if fraction <= 0 or pool_width <= 0:
        return 0
    want = int(fraction * max_batch * max_seq_len) // pool_width
    return max(2, min(want, cap))


class _Node:
    """Radix-trie node; one level per bucket boundary. ``edge`` is the
    token run from the parent's boundary to this node's (kept for pruning)."""

    __slots__ = ("parent", "edge", "children", "entry")

    def __init__(self, parent: Optional["_Node"] = None, edge: tuple = ()):
        self.parent = parent
        self.edge = edge
        self.children: dict[tuple, _Node] = {}
        self.entry: Optional[PrefixEntry] = None


@dataclass
class PrefixEntry:
    row: int  # pool row holding the KV
    length: int  # bucket-aligned token count (a boundary width)
    refs: int = 0  # admissions currently reading this row
    last_used: int = 0  # LRU tick
    node: Any = field(default=None, repr=False)
    digest: str = ""  # prefix_digest(tokens[:length]) — beacon advertisement


class PrefixCachePool:
    """Radix-indexed, refcounted, LRU-evicted device KV pool."""

    def __init__(
        self,
        config: Any,
        entries: int,
        width: int,
        boundaries: tuple[int, ...],
        mesh: Optional[Any] = None,
    ) -> None:
        from langstream_tpu.models.transformer import make_kv_cache

        self.config = config
        self.entries = int(entries)
        self.width = int(width)
        self._mesh = mesh
        # bucket-aligned publish/lookup lengths, ascending, bounded by the
        # pool width (a prefix wider than a pool row can't be cached)
        self.boundaries = tuple(
            sorted({int(b) for b in boundaries if 0 < b <= self.width})
        )
        if self.entries < 1 or not self.boundaries:
            raise ValueError("prefix pool needs ≥1 entry and ≥1 boundary")
        self.dev = make_kv_cache(config, self.entries, self.width)
        if mesh is not None:
            from langstream_tpu.parallel.sharding import shard_serving_cache

            self.dev = shard_serving_cache(self.dev, mesh)
        self.bytes_total = sum(
            leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(self.dev)
        )
        self._bytes_per_row = self.bytes_total // self.entries
        self._root = _Node()
        self._live: dict[int, PrefixEntry] = {}  # row → entry
        self._free = list(range(self.entries - 1, -1, -1))
        self._tick = 0
        # beacon advertisement: digest → [length, recency tick] — the one
        # surface read off-thread (the /state endpoint), mirroring
        # pagepool.PrefixPageIndex
        self._ads: dict[str, list] = {}
        self._ad_lock = threading.Lock()
        # stats (cumulative since engine start)
        self.lookups = 0
        self.hits = 0
        self.tokens_saved = 0
        self.evictions = 0

    def reset(self) -> None:
        """Drop every cached prefix and rebuild the device pool — the
        engine's crash-recovery path (serving/engine.py _recover): pool rows
        may hold KV published from a poisoned cache, and the pool buffer
        itself may be donation-invalidated by a publish that crashed
        mid-dispatch. Hit/eviction counters survive (they are cumulative
        since engine start); pins do not — every pinned admission was
        already failed by the recovery that called this."""
        from langstream_tpu.models.transformer import make_kv_cache

        self.dev = make_kv_cache(self.config, self.entries, self.width)
        if self._mesh is not None:
            from langstream_tpu.parallel.sharding import shard_serving_cache

            self.dev = shard_serving_cache(self.dev, self._mesh)
        self._root = _Node()
        self._live = {}
        self._free = list(range(self.entries - 1, -1, -1))
        with self._ad_lock:
            self._ads = {}
        self._tick = 0

    # -- index ---------------------------------------------------------------

    def _walk(self, tokens, limit: int, create: bool = False) -> list[_Node]:
        """Nodes along the bucket-aligned path of ``tokens``, root excluded,
        stopping at the first missing edge (or creating edges down to the
        deepest boundary ≤ limit when ``create``)."""
        path: list[_Node] = []
        node, prev = self._root, 0
        for b in self.boundaries:
            if b > limit:
                break
            seg = tuple(tokens[prev:b])
            child = node.children.get(seg)
            if child is None:
                if not create:
                    break
                child = _Node(parent=node, edge=seg)
                node.children[seg] = child
            path.append(child)
            node, prev = child, b
        return path

    def candidates(self, tokens) -> list[tuple[int, PrefixEntry]]:
        """Usable ``(reuse_length, entry)`` pairs for this prompt, ascending
        by length. The limit is ``len(tokens) - 1``: at least one suffix
        token must prefill, since the first sampled token needs last-token
        logits. A pair may reuse only the FIRST ``reuse_length`` columns of
        a DEEPER entry (a preamble cached as part of a longer prompt still
        serves shorter prompts sharing it — the row's leading columns ARE
        that prefix's KV). No stats side effects; callers report the final
        decision through ``record_lookup``."""
        out: list[tuple[int, PrefixEntry]] = []
        path = self._walk(tokens, limit=len(tokens) - 1)
        depth = 0
        for node, b in zip(path, self.boundaries):
            if node.entry is not None:
                out.append((b, node.entry))
            depth = b
        if path and (not out or out[-1][0] < depth):
            # the deepest matched node has no entry of its own, but any
            # descendant's row carries this prefix in its leading columns
            sub = self._subtree_entry(path[-1])
            if sub is not None:
                out.append((depth, sub))
        return out

    @staticmethod
    def _subtree_entry(node: _Node) -> Optional[PrefixEntry]:
        stack = list(node.children.values())
        while stack:
            n = stack.pop()
            if n.entry is not None:
                return n.entry
            stack.extend(n.children.values())
        return None

    def record_lookup(self, used: Optional[PrefixEntry]) -> None:
        """Count one admission lookup; ``used`` is the entry the engine
        actually reused (None = miss / no usable candidate)."""
        self.lookups += 1
        if used is not None:
            self.hits += 1
            self._tick += 1
            used.last_used = self._tick
            if used.digest:
                with self._ad_lock:
                    ad = self._ads.get(used.digest)
                    if ad is not None:
                        ad[1] = self._tick

    def match_len(self, tokens) -> int:
        """Non-mutating probe: longest cached prefix length usable for
        ``tokens``, or 0. Touches neither LRU recency nor hit counters —
        see pagepool.PrefixPageIndex.match_len for why that matters."""
        cands = self.candidates(tokens)
        return cands[-1][0] if cands else 0

    def advertised(self, top_k: int = 32) -> list[tuple[str, int, str]]:
        """Most-recently-used ``top_k`` ``(digest, length, tier)`` triples
        for the fleet beacon; thread-safe. The dense pool has no host
        tier, so every entry advertises ``device`` (the paged index is
        where ``host`` hibernation appears — pagepool.advertised)."""
        with self._ad_lock:
            items = sorted(
                self._ads.items(), key=lambda kv: kv[1][1], reverse=True
            )[: max(0, top_k)]
        return [(digest, ad[0], "device") for digest, ad in items]

    def has(self, tokens, length: int) -> bool:
        path = self._walk(tokens, limit=length)
        return bool(path) and path[-1].entry is not None and (
            path[-1].entry.length == length
        )

    def publish_length(self, prompt_len: int) -> int:
        """Largest bucket-aligned prefix length coverable by a pool row for
        a prompt of ``prompt_len`` tokens, or 0 when none fits."""
        best = 0
        for b in self.boundaries:
            if b <= prompt_len:
                best = b
        return best

    # -- refcounts / eviction ------------------------------------------------

    def acquire(self, entry: PrefixEntry) -> None:
        entry.refs += 1

    def release(self, entry: PrefixEntry) -> None:
        assert entry.refs > 0
        entry.refs -= 1

    def allocate(self) -> Optional[int]:
        """A free pool row, evicting the least-recently-used UNREFERENCED
        entry when full. None when every row is pinned by an in-flight
        admission — the caller skips the publish (never blocks, never
        evicts a row a dispatch is reading)."""
        if self._free:
            return self._free.pop()
        victims = [e for e in self._live.values() if e.refs == 0]
        if not victims:
            return None
        victim = min(victims, key=lambda e: e.last_used)
        self._evict(victim)
        return self._free.pop()

    def _evict(self, entry: PrefixEntry) -> None:
        node = entry.node
        node.entry = None
        # prune entry-less leaf chains so the trie stays bounded by the pool
        while (
            node is not None
            and node.parent is not None
            and node.entry is None
            and not node.children
        ):
            parent = node.parent
            del parent.children[node.edge]
            node = parent
        del self._live[entry.row]
        self._free.append(entry.row)
        if entry.digest:
            with self._ad_lock:
                self._ads.pop(entry.digest, None)
        self.evictions += 1

    def insert(self, tokens, length: int, row: int) -> PrefixEntry:
        """Index pool row ``row`` as the prefix ``tokens[:length]`` (the
        device copy has already been dispatched; in-order streams make the
        row readable by any later gather)."""
        assert length in self.boundaries, (length, self.boundaries)
        node = self._walk(tokens, limit=length, create=True)[-1]
        self._tick += 1
        entry = PrefixEntry(
            row=row, length=length, last_used=self._tick, node=node,
            digest=prefix_digest(tokens[:length]),
        )
        node.entry = entry
        self._live[row] = entry
        with self._ad_lock:
            self._ads[entry.digest] = [entry.length, entry.last_used]
        return entry

    # -- stats ---------------------------------------------------------------

    @property
    def live_entries(self) -> int:
        return len(self._live)

    def bytes_in_use(self) -> int:
        return len(self._live) * self._bytes_per_row

    def hit_rate(self) -> float:
        return round(self.hits / self.lookups, 4) if self.lookups else 0.0
