"""Fleet router: radix-prefix-affinity routing + cache-aware load balancing
across N serving-engine replicas (ROADMAP item 3).

One engine is fast (BENCH_r05), but a second replica placed blindly HALVES
the prefix hit rate: requests sharing a preamble land on whichever replica
the balancer felt like, each replica re-prefills the preamble cold, and the
paged pool's zero-copy aliasing (PR 5) never fires. This module is the tier
that millions of users actually hit — the piece between the gateway and the
engines:

- **Beacons** (`beacon_from_engine`, served at ``GET /state`` by the
  runtime HTTP server): each replica periodically advertises a compact
  state document — its ``load_score`` (queue-wait p90 + occupancy + page
  pressure, serving/observability.py), queue-wait EMA, free KV pages,
  drain/quarantine flags, and the top-K prefix DIGESTS its radix index
  holds (``pagepool.prefix_digest`` — 8-byte hashes, never token content;
  the same redaction stance as the flight recorder). The non-mutating
  ``match_len`` probes exist so beacon building and router probing never
  touch LRU recency: advertising a prefix must not pin it.

- **Router** (`FleetRouter`): dispatches each request by *prefix affinity
  first, load second*. It hashes the incoming prompt at every advertised
  boundary length and scores each replica

      score(r) = expected_match_tokens(r) − λ · load_score(r)

  routing to the argmax; when no replica holds a usable prefix the request
  goes to the least-loaded replica instead. λ (tokens per load-score unit,
  default 256) is the knob that decides when a hot replica is TOO hot to be
  worth its warm cache — see docs/SERVING.md §13 for tuning. Sticky
  sessions (``langstream-client-session-id`` → replica) keep multi-turn
  chats on the replica whose pages they aliased. Overload sheds against
  the replicas' EXPORTED signals (every routable replica's admission queue
  full, or every queue-wait EMA past the bound) rather than a blind
  request cap, and a replica that dies mid-burst is quarantined and its
  requests re-routed — in-flight work fails over COLD to a survivor
  (DeepServe's affinity-and-load dispatch, PAPERS.md).

- **Autoscale hint** (`FleetRouter.desired_replicas`): the k8s planner's
  scale signal, derived from the fleet-wide queue-wait EMA (scale-up) and
  occupancy (scale-down) — surfaced as the ``langstream.ai/desired-replicas``
  annotation k8s/resources.py honors on the agent StatefulSet.

The routing tier is deliberately ABOVE the engines and programmable
(PAPERS.md "Software-Defined Agentic Serving"): transports are duck-typed
(`InProcessReplica` for tests/embedded runners, `HttpReplica` over the
runtime HTTP server for real pods), and the policy is a constructor knob
(``affinity`` | ``round-robin`` | ``least-loaded`` — round-robin exists as
the bench control arm, not a production mode).

Run ``python -m langstream_tpu.serving.fleet --config '<json>'`` to serve
one replica (engine + /state + /fleet/generate) as a standalone process —
the multi-process CPU fleet bench (bench.py bench_fleet) and the failure
drills are built on this.
"""

from __future__ import annotations

import http.client
import json
import logging
import math
import os
import queue
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional

from langstream_tpu.api.metrics import Histogram, log_buckets
from langstream_tpu.serving.observability import (
    FLEET_HISTOGRAMS,
    FlightRecorder,
)
from langstream_tpu.serving.pagepool import prefix_digest

log = logging.getLogger(__name__)

BEACON_SCHEMA = "lstpu-beacon-v1"
STATE_SCHEMA = "lstpu-state-v1"

# the fleet hop's streaming frame protocol (docs/SERVING.md §17):
# newline-delimited JSON frames over chunked transfer-encoding, one
# monotone per-request ``seq`` per frame starting at 0. Frame kinds:
#   tokens     {"seq", "kind": "tokens", "tokens": [ids]} — a token chunk
#   heartbeat  {"seq", "kind": "heartbeat"} — idle keepalive, so the
#              client can tell slow-decode (heartbeats flow) from a dead
#              peer (the wire goes silent past its idle timeout)
#   end        terminal: finish_reason + usage + ttft_s/total_s — a stream
#              that closes WITHOUT one is a failed hop, never a success
#   error      terminal: the engine failed after streaming began (token
#              content already delivered stays valid for failover resume)
FRAME_SCHEMA = "lstpu-frames-v1"

# hop budget when the request carries no deadline of its own; with one,
# the hop is bounded by the REMAINING deadline + slack (hop_timeout_s) —
# a 10s-deadline request must never hold a connection for 10 minutes
DEFAULT_HOP_TIMEOUT_S = 600.0
HOP_DEADLINE_SLACK_S = 5.0


def hop_timeout_s(
    options: Optional[dict], default: float = DEFAULT_HOP_TIMEOUT_S,
) -> float:
    """Total wall budget for one fleet hop, derived from the request's own
    ``deadline`` option (plus transport/queue slack) when it has one. The
    deadline ALSO rides the hop payload, so the peer's engine enforces it
    server-side; this bound is the client's backstop for a wedged peer."""
    from langstream_tpu.models.configs import GenerationOptions

    # GenerationOptions.from_dict owns the option-key spellings: parsing
    # them here again would let the engine enforce a deadline the hop
    # doesn't see. A malformed options dict falls back to the default —
    # the peer's own parse will reject it properly.
    try:
        d = GenerationOptions.from_dict(options or {}).deadline_s
    except (TypeError, ValueError, KeyError):
        return float(default)
    if d is None or d <= 0:
        return float(default)
    return min(float(default), d + HOP_DEADLINE_SLACK_S)


# ---------------------------------------------------------------------------
# Wire fault injector (docs/SERVING.md §17): ONE process-wide injector for
# the net-* sites, consulted by the HttpReplica transport (net-connect) and
# the /fleet/generate streaming handler (net-stall / net-cut / net-corrupt).
# Separate from the engine's injector — the wire is a different failure
# domain — but activated the same two ways: set_wire_injector() in tests /
# the replica worker config, or the LSTPU_FAULTS env spec.
# ---------------------------------------------------------------------------

_WIRE_LOCK = threading.Lock()
_WIRE_INJECTOR: Optional[Any] = None
_WIRE_ENV_CHECKED = False


def set_wire_injector(injector: Optional[Any]) -> None:
    """Install (or, with None, clear) the process-wide wire injector."""
    global _WIRE_INJECTOR, _WIRE_ENV_CHECKED
    with _WIRE_LOCK:
        _WIRE_INJECTOR = injector
        _WIRE_ENV_CHECKED = True


def wire_injector() -> Optional[Any]:
    global _WIRE_INJECTOR, _WIRE_ENV_CHECKED
    with _WIRE_LOCK:
        if not _WIRE_ENV_CHECKED:
            from langstream_tpu.serving.faultinject import FaultInjector

            _WIRE_INJECTOR = FaultInjector.from_env()
            _WIRE_ENV_CHECKED = True
        return _WIRE_INJECTOR


def result_frames(out: dict[str, Any], prompt_len: int = 0) -> Iterator[dict]:
    """Wrap an already-computed one-shot ``generate()`` result dict into
    the §17 frame shapes — the single adapter behind every transport /
    registration / legacy peer that doesn't stream natively."""
    toks = [int(t) for t in out.get("tokens") or []]
    seq = 0
    if toks:
        yield {
            "v": FRAME_SCHEMA, "seq": 0, "kind": "tokens",
            "tokens": toks,
        }
        seq = 1
    yield {
        "seq": seq, "kind": "end",
        "finish_reason": str(out.get("finish_reason", "stop")),
        "prompt_tokens": int(out.get("prompt_tokens", prompt_len)),
        "ttft_s": float(out.get("ttft_s", 0.0)),
        "total_s": float(out.get("total_s", 0.0)),
        "usage": {
            "prompt_tokens": int(out.get("prompt_tokens", prompt_len)),
            "completion_tokens": len(toks),
        },
    }


def close_frames(frames: Any) -> None:
    """Close a frame iterator that may STILL be executing a ``next()`` on
    an executor thread (the async consumer was cancelled mid-fetch): try
    now, and if the generator is mid-step, retire it from a daemon thread
    once the in-flight step returns. Closing is what cancels the
    underlying engine request / hop socket, so best-effort-now is not
    enough."""
    close = getattr(frames, "close", None)
    if close is None:
        return
    try:
        close()
        return
    except ValueError:  # "generator already executing" — executor race
        pass

    def _later() -> None:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            time.sleep(0.05)
            try:
                close()
                return
            except ValueError:
                continue
        log.warning("frame stream still executing after 30s; leaking it")

    threading.Thread(
        target=_later, name="fleet-frame-close", daemon=True
    ).start()

# λ default: tokens of expected prefix match one unit of load score is
# worth. load_score ≈ queue-wait p90 seconds + occupancy (0..1) + page
# pressure (0..1); at λ=256 a fully-busy replica (occupancy+pages ≈ 2)
# still wins the route when it holds ≥512 more warm prefix tokens than an
# idle one, but one second of queue wait erases a 256-token advantage.
DEFAULT_LAMBDA = 256.0


class FleetShedError(RuntimeError):
    """The fleet cannot place this request right now (every routable
    replica is saturated, or none is routable). Callers surface it exactly
    like the engine's ShedError — HTTP 429 with Retry-After."""

    def __init__(self, reason: str, retry_after_s: float = 1.0) -> None:
        super().__init__(reason)
        self.retry_after_s = retry_after_s


class ReplicaError(RuntimeError):
    """A dispatch to one replica failed (process died, HTTP unreachable,
    engine stopped). The router quarantines the replica and fails the
    request over to a survivor — this error type is what separates
    'replica is broken' from 'replica said no' (FleetShedError)."""


# ---------------------------------------------------------------------------
# Beacon
# ---------------------------------------------------------------------------


def beacon_from_engine(
    replica_id: str, engine: Any, url: str = "", top_k: int = 32,
    role: str = "mixed",
) -> dict[str, Any]:
    """Build the compact state beacon one replica advertises. Token content
    never appears — prefixes travel as (digest, length) pairs. Safe to call
    from any thread (engine.stats() and the advertisement registries take
    their own locks). ``role`` is the disaggregated-serving tag
    (``prefill`` | ``decode`` | ``mixed`` — the `fleet-role` knob, §18):
    the router steers long-prompt admissions at prefill-tagged replicas
    and migrates their KV to decode-tagged ones."""
    if role not in ("prefill", "decode", "mixed"):
        raise ValueError(
            f"unknown fleet role {role!r}; supported: prefill, decode, mixed"
        )
    stats = engine.stats()
    adv = getattr(engine, "prefix_advertisement", None)
    boundaries, prefixes = adv(top_k) if adv is not None else ((), [])
    hist = stats.get("histograms") or {}
    ttft = hist.get("engine_ttft_s") or {}
    thread = getattr(engine, "_thread", None)
    dead = getattr(engine, "_dead", None) is not None or (
        thread is None or not thread.is_alive()
    )
    pages_total = stats.get("kv-pages-total", 0)
    return {
        "schema": BEACON_SCHEMA,
        "id": str(replica_id),
        "url": url,
        "role": role,
        "at": round(time.time(), 3),
        "load_score": stats.get("load-score", 0.0),
        "queue_wait_ema_s": stats.get("queue-wait-ema-s", 0.0),
        "active_slots": stats.get("active-slots", 0),
        "max_batch": stats.get("max-batch", 0),
        "queued": stats.get("queued", 0),
        "queue_depth": int(getattr(engine, "_queue", None).maxsize or 0)
        if getattr(engine, "_queue", None) is not None
        else 0,
        "shed_policy": getattr(engine, "shed_policy", "block"),
        "shed_total": stats.get("shed-total", 0),
        "kv_pages_total": pages_total,
        "kv_pages_free": max(0, pages_total - stats.get("kv-pages-in-use", 0)),
        "draining": bool(stats.get("draining", False)),
        "quarantined": bool(dead),
        # SPMD slice resilience (§20): True through the replica's
        # crash→rebuild→backoff window. Routers EXCLUDE a recovering
        # replica without quarantining it — recovery is seconds, the
        # fail_cooldown_s quarantine is not — and HOLD its sticky
        # sessions so they resume on their owner when it returns.
        "recovering": bool(stats.get("recovering", False)),
        "prefix_hit_rate": stats.get("prefix-cache-hit-rate", 0.0),
        "prefill_tokens_saved_total": stats.get("prefill-tokens-saved-total", 0),
        "ttft_p50_ms": round(float(ttft.get("p50", 0.0)) * 1e3, 3),
        "ttft_p99_ms": round(float(ttft.get("p99", 0.0)) * 1e3, 3),
        "boundaries": [int(b) for b in boundaries],
        # device-resident prefixes vs hibernated ones (tiered KV, §16):
        # a spilled session's digest keeps advertising so sticky routing
        # survives hibernation — the router scores it at a discount (the
        # restore is cheap but not free). Advertisement triples may come
        # from the dense pool too, where everything is device-resident.
        "prefixes": [
            [d, int(n)]
            for d, n, tier in prefixes
            if tier not in ("host", "durable")
        ],
        # "host" AND "durable" tiers beacon here (§16/§23): both serve a
        # sticky hit without device residency — host via arena restore,
        # durable via disk restore (or a P2P fetch from this replica's
        # checkpoint). Routers score both at the same discount.
        "spilled_prefixes": [
            [d, int(n)]
            for d, n, tier in prefixes
            if tier in ("host", "durable")
        ],
        # resident LoRA adapters (NAMES only, never factors): the router's
        # adapter-affinity signal — landing a tenant's request on a replica
        # already holding its adapter skips a hot-swap dispatch (§15)
        "adapters": [
            str(a)
            for a in (
                engine.adapter_advertisement()
                if hasattr(engine, "adapter_advertisement")
                else ()
            )
        ],
        # per-tenant queue pressure (docs/SERVING.md §19): the router's
        # tenant-aware shed/route signal — an aggressor's backlog on THIS
        # replica must not get its overflow balanced onto the replica
        # serving the victim. Tenant IDS only (they already ride HTTP
        # headers), never token content; bounded to the busiest 16.
        "tenants": _beacon_tenants(stats.get("tenants") or {}),
        # brownout ladder level (0 = normal): routers prefer un-browned
        # replicas at equal affinity, and operators see degradation
        # fleet-wide
        "brownout_level": int(stats.get("brownout-level", 0) or 0),
        # wire capabilities (§18/§21): what this replica's VERSION
        # understands. "kvmig" = binds inbound KV-page migrations;
        # "dfa-resume" = honors grammar-resume-state; "kvmig2"/"frames2"
        # = speaks the v2 binary codecs (lstpu-kvmig-v2 /
        # lstpu-frames-v2); "p2p" = serves and fetches pages
        # peer-to-peer on radix miss. The router refuses to migrate to —
        # or resume a constrained stream on — a peer that does not
        # advertise the capability: a legacy peer would silently drop the
        # option and restart the DFA at state 0 (invalid output dressed
        # as valid), the exact class the §17 refusal existed to prevent.
        # Version negotiation for the binary wire rides this same field:
        # senders emit v2 only toward peers that advertise it, so a
        # mixed-version fleet keeps exchanging byte-identical v1 NDJSON
        # with legacy members (rolling-upgrade safe).
        "caps": ["kvmig", "kvmig2", "dfa-resume", "p2p", "frames2"]
        + (
            # "durable" = crash-safe disk checkpoints (§23): the replica
            # can hibernate, serve P2P fetches from disk, and resurrect
            # sessions after a restart. Scale-to-zero requires EVERY live
            # replica to advertise it (sessions must survive the drain).
            ["durable"]
            if getattr(engine, "_durable", None) is not None
            else []
        ),
        # landed prefill throughput (tokens/s) for the router's
        # fetch-vs-prefill cost model (§23): what recomputing a prefix
        # locally costs, measured, not configured. 0.0 until a dispatch
        # lands — the router then falls back to its flat page threshold.
        "prefill_tps": (
            engine.prefill_tps_estimate()
            if hasattr(engine, "prefill_tps_estimate")
            else 0.0
        ),
        # page geometry so a router can turn "pages" into "bytes" for the
        # same cost model without a second RPC
        "bytes_per_page": int(stats.get("kv-bytes-per-page", 0) or 0),
        "page_size": int(stats.get("page-size", 0) or 0),
    }


def _beacon_tenants(tenants: dict[str, Any]) -> dict[str, dict[str, Any]]:
    """Compact per-tenant pressure block for the beacon: queue depth,
    wait EMA, quota state and cumulative sheds — the fields the router's
    tenant-aware decisions read. Bounded to the 16 busiest tenants so a
    many-tenant replica cannot bloat every beacon fetch."""
    busiest = sorted(
        tenants.items(),
        key=lambda kv: (
            -int(kv[1].get("queued", 0)),
            -float(kv[1].get("queue-wait-ema-s", 0.0)),
        ),
    )[:16]
    return {
        str(name): {
            "queued": int(t.get("queued", 0)),
            "queue_wait_ema_s": float(t.get("queue-wait-ema-s", 0.0)),
            "over_quota": bool(t.get("over-quota", False)),
            "shed_total": int(t.get("shed-total", 0)),
            "active_slots": int(t.get("active-slots", 0)),
        }
        for name, t in busiest
    }


def validate_beacon(doc: dict[str, Any]) -> bool:
    """Schema check for one beacon (docs/SERVING.md §13): raises ValueError
    on the first violation. Enforces the redaction contract — a beacon
    carries digests, never tokens."""
    if not isinstance(doc, dict):
        raise ValueError("beacon must be a JSON object")
    if doc.get("schema") != BEACON_SCHEMA:
        raise ValueError(f"unknown beacon schema {doc.get('schema')!r}")
    for key in (
        "id", "at", "load_score", "queue_wait_ema_s", "draining",
        "quarantined", "prefixes",
    ):
        if key not in doc:
            raise ValueError(f"beacon missing field {key!r}")
    for key in ("prefixes", "spilled_prefixes"):
        for j, pair in enumerate(doc.get(key) or []):
            if (
                not isinstance(pair, (list, tuple))
                or len(pair) != 2
                or not isinstance(pair[0], str)
                or not isinstance(pair[1], int)
            ):
                raise ValueError(
                    f"{key} advertisement {j} is not [digest, length]"
                )
    for j, name in enumerate(doc.get("adapters") or []):
        if not isinstance(name, str):
            raise ValueError(f"adapter advertisement {j} is not a name string")
    role = doc.get("role", "mixed")
    if role not in ("prefill", "decode", "mixed"):
        raise ValueError(f"unknown beacon role {role!r}")
    for j, cap in enumerate(doc.get("caps") or []):
        if not isinstance(cap, str):
            raise ValueError(f"capability advertisement {j} is not a string")
    tenants = doc.get("tenants")
    if tenants is not None:
        if not isinstance(tenants, dict):
            raise ValueError("beacon tenants must be an object")
        for name, t in tenants.items():
            if not isinstance(name, str) or not isinstance(t, dict):
                raise ValueError(
                    f"tenant advertisement {name!r} is not name -> object"
                )
            for key in ("queued", "queue_wait_ema_s", "over_quota"):
                if key not in t:
                    raise ValueError(
                        f"tenant advertisement {name!r} missing {key!r}"
                    )
    for forbidden in ("tokens", "prompt", "text", "prompt_tokens"):
        if forbidden in doc:
            raise ValueError(f"beacon carries token-content key {forbidden!r}")
    json.dumps(doc)
    return True


# ---------------------------------------------------------------------------
# Local replica registry (the runtime HTTP server's /state + /fleet/generate
# read this — same process-global pattern as observability.RECENT_DUMPS, so
# the server never holds an engine reference)
# ---------------------------------------------------------------------------

_LOCAL_LOCK = threading.Lock()
_LOCAL: dict[str, dict[str, Callable]] = {}


def register_local(
    replica_id: str,
    beacon_fn: Callable[[], dict],
    generate_fn: Optional[Callable[[dict], dict]] = None,
    reset_fn: Optional[Callable[[], None]] = None,
    generate_stream_fn: Optional[Callable[[dict], Iterator[dict]]] = None,
    migrate_bind_fn: Optional[Callable[..., dict]] = None,
    migrate_out_fn: Optional[Callable[[dict], dict]] = None,
    recovering_fn: Optional[Callable[[], bool]] = None,
    migrate_pages_fn: Optional[Callable[[dict], Iterator[dict]]] = None,
    p2p_fetch_fn: Optional[Callable[[dict], dict]] = None,
    migrate_limits_fn: Optional[Callable[[], dict]] = None,
    restoring_fn: Optional[Callable[[], bool]] = None,
) -> None:
    """Expose this process's engine on the runtime HTTP server: ``GET
    /state`` serves ``beacon_fn``, ``POST /fleet/generate`` runs
    ``generate_fn`` (fleet-internal dispatch; with ``stream: true`` in the
    payload it prefers ``generate_stream_fn`` — frames per §17 — and falls
    back to wrapping ``generate_fn``'s one-shot result), ``POST
    /fleet/reset`` runs ``reset_fn`` (bench warmup hygiene), ``POST
    /fleet/migrate`` binds an inbound KV-page migration through
    ``migrate_bind_fn`` and ``POST /fleet/migrate-out`` commands this
    replica to push one through ``migrate_out_fn`` (docs/SERVING.md §18).
    The §21 P2P surface: ``POST /fleet/pages`` serves migration frames
    covering a prefix WITHOUT releasing them through
    ``migrate_pages_fn`` (a fetch copies, a migration moves), ``POST
    /fleet/fetch`` commands this replica to pull pages from a named
    owner through ``p2p_fetch_fn``, and ``migrate_limits_fn`` reports
    the pool geometry the migrate receiver uses to bound wire reads."""
    with _LOCAL_LOCK:
        _LOCAL[str(replica_id)] = {
            "beacon": beacon_fn, "generate": generate_fn, "reset": reset_fn,
            "generate_stream": generate_stream_fn,
            "migrate_bind": migrate_bind_fn,
            "migrate_out": migrate_out_fn,
            "recovering": recovering_fn,
            "migrate_pages": migrate_pages_fn,
            "p2p_fetch": p2p_fetch_fn,
            "migrate_limits": migrate_limits_fn,
            "restoring": restoring_fn,
        }


def local_recovering() -> bool:
    """True while ANY engine registered in this process is inside its
    crash→rebuild→backoff recovery window (§20). Reads one attribute per
    engine (never stats()), cheap enough for /healthz — k8s readiness can
    hold traffic through a recovery without killing the pod."""
    with _LOCAL_LOCK:
        fns = [e.get("recovering") for e in _LOCAL.values()]
    out = False
    for fn in fns:
        if fn is None:
            continue
        try:
            out = out or bool(fn())
        except Exception:  # noqa: BLE001 — health probes must not raise
            log.exception("recovering probe failed")
    return out


def local_restoring() -> bool:
    """True while ANY engine registered in this process is serving a
    durable-tier restore (§23) — the resurrection-in-progress signal
    /healthz surfaces so scale-from-zero readiness probes can tell "still
    rehydrating sessions" from "wedged". Same cheap-attribute discipline
    as local_recovering()."""
    with _LOCAL_LOCK:
        fns = [e.get("restoring") for e in _LOCAL.values()]
    out = False
    for fn in fns:
        if fn is None:
            continue
        try:
            out = out or bool(fn())
        except Exception:  # noqa: BLE001 — health probes must not raise
            log.exception("restoring probe failed")
    return out


def unregister_local(replica_id: str) -> None:
    with _LOCAL_LOCK:
        _LOCAL.pop(str(replica_id), None)


def local_state() -> dict[str, Any]:
    """The /state document: every engine registered in this process (one,
    for every real topology)."""
    with _LOCAL_LOCK:
        entries = list(_LOCAL.items())
    replicas = []
    for replica_id, fns in entries:
        try:
            replicas.append(fns["beacon"]())
        except Exception:  # noqa: BLE001 — a crashed engine still beacons
            log.exception("beacon build failed for %s", replica_id)
            replicas.append(
                {
                    "schema": BEACON_SCHEMA, "id": replica_id, "url": "",
                    "at": round(time.time(), 3), "load_score": 1e9,
                    "queue_wait_ema_s": 0.0, "draining": False,
                    "quarantined": True, "prefixes": [],
                }
            )
    return {"schema": STATE_SCHEMA, "replicas": replicas}


def local_generate(payload: dict[str, Any]) -> dict[str, Any]:
    """Fleet-internal dispatch into this process's engine (the POST
    /fleet/generate body). Blocking — the HTTP server runs it in an
    executor. Raises ReplicaError when no engine is registered (the
    router treats that as a dead replica and fails over)."""
    with _LOCAL_LOCK:
        if not _LOCAL:
            raise ReplicaError("no serving engine registered in this process")
        fns = next(iter(_LOCAL.values()))
    gen = fns.get("generate")
    if gen is None:
        raise ReplicaError("registered engine does not accept fleet dispatch")
    return gen(payload)


def local_generate_stream(payload: dict[str, Any]) -> Iterator[dict]:
    """Streaming fleet-internal dispatch into this process's engine (the
    POST /fleet/generate ``stream: true`` body). Returns the frame
    iterator EAGERLY-submitted (docs/SERVING.md §17): pre-stream failures
    — shed, bad request, dead engine — raise HERE, before the HTTP layer
    has committed to a chunked response, so they still map to real status
    codes. Registrations without a stream fn degrade to one final tokens
    frame wrapped around the blocking ``generate`` result."""
    with _LOCAL_LOCK:
        if not _LOCAL:
            raise ReplicaError("no serving engine registered in this process")
        fns = next(iter(_LOCAL.values()))
    stream = fns.get("generate_stream")
    if stream is not None:
        return stream(payload)
    gen = fns.get("generate")
    if gen is None:
        raise ReplicaError("registered engine does not accept fleet dispatch")
    return result_frames(gen(payload))


def local_migrate_bind(frames: Iterator[dict], timeout_s: float = 30.0) -> dict:
    """Inbound KV-page migration into this process's engine (the POST
    /fleet/migrate body, §18). Blocking — the HTTP server runs it in an
    executor. Raises ReplicaError when no engine is registered."""
    with _LOCAL_LOCK:
        if not _LOCAL:
            raise ReplicaError("no serving engine registered in this process")
        fns = next(iter(_LOCAL.values()))
    bind = fns.get("migrate_bind")
    if bind is None:
        raise ReplicaError(
            "registered engine does not accept KV-page migrations"
        )
    return bind(frames, timeout_s)


def local_migrate_out(payload: dict) -> dict:
    """Outbound migration command (the POST /fleet/migrate-out body): this
    process's engine exports the prefix and pushes it to ``dest``."""
    with _LOCAL_LOCK:
        if not _LOCAL:
            raise ReplicaError("no serving engine registered in this process")
        fns = next(iter(_LOCAL.values()))
    out = fns.get("migrate_out")
    if out is None:
        raise ReplicaError(
            "registered engine does not accept KV-page migrations"
        )
    return out(payload)


def local_migrate_pages(payload: dict) -> Iterator[dict]:
    """P2P page serve (the POST /fleet/pages body, §21): export migration
    frames covering the deepest published prefix of ``prompt_tokens``
    WITHOUT releasing anything — the owner keeps its copy. Pre-stream
    failures (no engine, no published prefix) raise here so the HTTP
    layer can still answer a JSON error instead of a broken stream."""
    with _LOCAL_LOCK:
        if not _LOCAL:
            raise ReplicaError("no serving engine registered in this process")
        fns = next(iter(_LOCAL.values()))
    pages = fns.get("migrate_pages")
    if pages is None:
        raise ReplicaError("registered engine does not serve P2P page fetch")
    return pages(payload)


def local_p2p_fetch(payload: dict) -> dict:
    """Inbound P2P fetch command (the POST /fleet/fetch body, §21): this
    process's engine pulls pages from the ``source`` peer and admits the
    prefix warm. Blocking — the HTTP server runs it in an executor."""
    with _LOCAL_LOCK:
        if not _LOCAL:
            raise ReplicaError("no serving engine registered in this process")
        fns = next(iter(_LOCAL.values()))
    fetch = fns.get("p2p_fetch")
    if fetch is None:
        raise ReplicaError("registered engine does not serve P2P page fetch")
    return fetch(payload)


_LOCAL_ROUTER: Optional[Any] = None


def register_local_router(router: Any) -> None:
    """Expose this process's FleetRouter for the HTTP prefetch surface
    (POST /fleet/prefetch, §23). One router per process — latest wins,
    matching the _EngineHolder singleton that builds it."""
    global _LOCAL_ROUTER
    with _LOCAL_LOCK:
        _LOCAL_ROUTER = router


def unregister_local_router() -> None:
    global _LOCAL_ROUTER
    with _LOCAL_LOCK:
        _LOCAL_ROUTER = None


def local_prefetch(payload: dict) -> dict:
    """Prefetch-on-hint command (the POST /fleet/prefetch body, §23): a
    gateway that KNOWS a session's next turn is coming (client typing, a
    scheduled agent step, a resurrection hint for a hibernated replica)
    posts the session's token prefix here, and the router pulls the
    pages to the replica the request WILL route to — before the request
    exists. Blocking — the HTTP server runs it in an executor."""
    with _LOCAL_LOCK:
        router = _LOCAL_ROUTER
    if router is None:
        raise ReplicaError("no fleet router in this process")
    tokens = payload.get("prompt_tokens")
    if not isinstance(tokens, list) or not all(
        isinstance(t, int) for t in tokens
    ):
        raise ValueError("prompt_tokens must be a list of token ids")
    session = payload.get("session")
    adapter = payload.get("adapter")
    tenant = payload.get("tenant")
    return router.prefetch(
        tokens,
        session_id=str(session) if session else None,
        adapter=str(adapter) if adapter else None,
        tenant=str(tenant) if tenant else None,
    )


def local_migrate_limits() -> dict:
    """Static pool geometry for the migrate receiver's wire bounds (§21
    hardening): ``{"bytes_per_page", "pages_total"}``, or ``{}`` when no
    engine (or a non-paged one) is registered — the receiver then falls
    back to flat caps."""
    with _LOCAL_LOCK:
        if not _LOCAL:
            return {}
        fns = next(iter(_LOCAL.values()))
    limits = fns.get("migrate_limits")
    if limits is None:
        return {}
    try:
        return dict(limits() or {})
    except Exception:  # noqa: BLE001 — bounds probe must not kill a bind
        log.exception("migrate limits probe failed")
        return {}


def engine_migrate_bind(
    engine: Any, frames: Iterator[dict], timeout_s: float = 30.0,
) -> dict:
    """The canonical ``migrate_bind_fn`` for ``register_local``: verify
    and bind one inbound migration into the local engine."""
    from langstream_tpu.serving import migrate as migrate_mod

    return migrate_mod.bind_frames(engine, frames, timeout_s=timeout_s)


def engine_migrate_out(engine: Any, payload: dict) -> dict:
    """The canonical ``migrate_out_fn`` for ``register_local``: export the
    prefix covering ``prompt_tokens`` from the local engine, push it to
    the ``dest`` replica's ``POST /fleet/migrate``, and release the local
    copy on its ACK (never before). Returns the receiver's ACK augmented
    with sender-side phase timings."""
    from langstream_tpu.serving import migrate as migrate_mod

    tokens = [int(t) for t in payload.get("prompt_tokens") or []]
    if not tokens:
        raise ValueError("migrate-out payload carries no prompt_tokens")
    dest = str(payload.get("dest") or "")
    if not dest:
        raise ValueError("migrate-out payload carries no dest url")
    timeout_s = float(payload.get("timeout-s") or 30.0)
    wire = "v2" if payload.get("wire") == "v2" else "v1"
    phases: dict[str, Any] = {}
    frames = migrate_mod.export_frames(
        engine, tokens, timeout_s=timeout_s,
        state=payload.get("state") or {}, phases=phases,
        raw=wire == "v2",
    )
    t0 = time.monotonic()
    ack = migrate_mod.push_migration(dest, frames, timeout_s, wire=wire)
    phases["transfer_ms"] = round((time.monotonic() - t0) * 1e3, 3)
    migrate_mod._release_on_ack(engine, tokens, ack)  # noqa: SLF001
    ack["phases"] = dict(phases, **(ack.get("phases") or {}))
    return ack


def engine_migrate_pages(engine: Any, payload: dict) -> Iterator[dict]:
    """The canonical ``migrate_pages_fn`` for ``register_local``: export
    the prefix covering ``prompt_tokens`` for a P2P fetch (§21) — same
    frames as a migration but the owner RELEASES NOTHING; the fetcher
    gets a copy and both replicas keep serving the prefix. ``wire: v2``
    asks for raw leaf-byte payloads (the binary codec's data plane);
    hibernated entries ship straight from the host arena either way."""
    from langstream_tpu.serving import migrate as migrate_mod

    tokens = [int(t) for t in payload.get("prompt_tokens") or []]
    if not tokens:
        raise ValueError("page-fetch payload carries no prompt_tokens")
    return migrate_mod.export_frames(
        engine, tokens,
        timeout_s=float(payload.get("timeout-s") or 30.0),
        raw=payload.get("wire") == "v2",
    )


def engine_p2p_fetch(engine: Any, payload: dict) -> dict:
    """The canonical ``p2p_fetch_fn`` for ``register_local``: pull the
    prefix covering ``prompt_tokens`` from the ``source`` peer's ``POST
    /fleet/pages`` and bind it into the local engine (§21). Failures
    propagate as MigrationError — the commanding router degrades to the
    local cold path; nothing here retries."""
    from langstream_tpu.serving import migrate as migrate_mod

    tokens = [int(t) for t in payload.get("prompt_tokens") or []]
    if not tokens:
        raise ValueError("p2p-fetch payload carries no prompt_tokens")
    source = str(payload.get("source") or "")
    if not source:
        raise ValueError("p2p-fetch payload carries no source url")
    timeout_s = float(payload.get("timeout-s") or 30.0)
    frames = migrate_mod.fetch_pages(
        source, tokens, timeout_s,
        wire="v2" if payload.get("wire") == "v2" else "v1",
    )
    return migrate_mod.bind_frames(engine, frames, timeout_s=timeout_s)


def local_reset() -> None:
    with _LOCAL_LOCK:
        entries = list(_LOCAL.values())
    for fns in entries:
        reset = fns.get("reset")
        if reset is not None:
            reset()


def engine_generate(
    engine: Any, payload: dict[str, Any],
    timeout_s: float = DEFAULT_HOP_TIMEOUT_S,
) -> dict[str, Any]:
    """The canonical ``generate_fn`` for ``register_local``: run one
    completion on the local engine from a fleet-dispatch payload
    (``{"prompt_tokens": [...], "options": {...}}``) and return a plain
    JSON-able result. Engine sheds propagate as FleetShedError so the HTTP
    layer can answer 429 + Retry-After.

    Cross-process cancel (ROADMAP 3b): when the options carry a
    ``cancel-key`` (the client session id the dispatching gateway routes
    disconnects by), the in-flight request registers in THIS process's
    lifecycle registry, so a forwarded ``POST /fleet/cancel`` from the
    gateway frees the slot at the next chunk boundary."""
    from langstream_tpu.models.configs import GenerationOptions
    from langstream_tpu.serving import lifecycle
    from langstream_tpu.serving.engine import GenerationRequest, ShedError

    tokens = [int(t) for t in payload.get("prompt_tokens") or []]
    if not tokens:
        raise ValueError("fleet dispatch payload carries no prompt_tokens")
    options = payload.get("options") or {}
    opts = GenerationOptions.from_dict(options)
    # deadline discipline (§17): the forwarded deadline bounds the server-
    # side wait too — a 10s-deadline request must not park an executor
    # thread here for the full default hop budget on a wedged engine
    timeout_s = min(timeout_s, hop_timeout_s(options, timeout_s))
    cancel_key = str(options.get("cancel-key") or "")
    # pre-built so it can register for cross-process cancel BEFORE the
    # submit; engine.generate keeps the submit/wait/cancel-on-timeout
    # contract in one place
    request = GenerationRequest(prompt_tokens=tokens, options=opts)
    if cancel_key:
        lifecycle.register(cancel_key, request)
    try:
        try:
            result = engine.generate(request=request, timeout=timeout_s)
        except ShedError as e:
            raise FleetShedError(str(e), retry_after_s=e.retry_after_s) from e
    finally:
        if cancel_key:
            lifecycle.unregister(cancel_key, request)
    return {
        "tokens": [int(t) for t in result.tokens],
        "finish_reason": result.finish_reason,
        "prompt_tokens": result.prompt_tokens,
        "ttft_s": round(result.ttft_s, 6),
        "total_s": round(result.total_s, 6),
    }


class _EngineFrameStream:
    """Frame iterator whose ``close()`` is safe BEFORE the first
    ``next()``: the consumer may abandon the hop between the eager submit
    and iteration (response prepare failed, handler cancelled), and the
    engine request must still be cancelled + unregistered — a generator's
    ``finally`` only runs once its body has started."""

    def __init__(self, request: Any, cancel_key: str, gen: Iterator[dict]):
        self._request = request
        self._cancel_key = cancel_key
        self._gen = gen

    def __iter__(self) -> "_EngineFrameStream":
        return self

    def __next__(self) -> dict:
        return next(self._gen)

    def close(self) -> None:
        try:
            self._gen.close()
        finally:
            # idempotent with the generator's own finally (cancel() and
            # unregister() both tolerate repeats): this leg covers the
            # pre-start abandon, where the generator body never ran
            if not self._request._done.is_set():  # noqa: SLF001
                self._request.cancel()
            if self._cancel_key:
                from langstream_tpu.serving import lifecycle

                lifecycle.unregister(self._cancel_key, self._request)


def engine_generate_stream(
    engine: Any,
    payload: dict[str, Any],
    timeout_s: float = DEFAULT_HOP_TIMEOUT_S,
    heartbeat_s: Optional[float] = None,
) -> Iterator[dict]:
    """The streaming twin of ``engine_generate`` (docs/SERVING.md §17):
    submit one completion on the local engine and return an iterator of
    ``lstpu-frames-v1`` frames — token chunks as the engine delivers them
    (so a remote route keeps local TTFT semantics), heartbeats while the
    stream idles, ONE terminal ``end``/``error`` frame.

    The SUBMIT happens eagerly, before the iterator is returned: shed /
    bad-request / dead-engine failures raise here, while the HTTP layer
    can still answer with a status code instead of a broken stream.
    Closing the iterator mid-stream (client disconnected, net-cut drill)
    cancels the in-flight request — a vanished consumer must not burn the
    slot to max_new_tokens.

    Token-delivery contract: every generated token rides a ``tokens``
    frame (the engine calls on_token exactly once per kept token), so the
    client-accumulated list IS result.tokens — what makes failover resume
    (prompt + delivered) token-exact. The ``end`` frame carries counts and
    usage, never token content the client doesn't already have."""
    from langstream_tpu.models.configs import GenerationOptions
    from langstream_tpu.serving import lifecycle
    from langstream_tpu.serving.engine import GenerationRequest, ShedError

    tokens = [int(t) for t in payload.get("prompt_tokens") or []]
    if not tokens:
        raise ValueError("fleet dispatch payload carries no prompt_tokens")
    options = payload.get("options") or {}
    opts = GenerationOptions.from_dict(options)
    timeout_s = min(timeout_s, hop_timeout_s(options, timeout_s))
    hb = float(payload.get("heartbeat-s") or heartbeat_s or 2.0)
    hb = max(0.05, hb)
    cancel_key = str(options.get("cancel-key") or "")
    q: "queue.Queue[tuple[str, Any]]" = queue.Queue()
    request = GenerationRequest(
        prompt_tokens=tokens,
        options=opts,
        on_done=lambda res: q.put(("done", res)),
    )
    # on_token runs on the ENGINE thread, which writes request.dfa_state
    # strictly before invoking it — pairing token and state here is what
    # lets a constrained stream's tokens frames carry the host-mirrored
    # DFA state, so a survivor can resume mid-derivation (§18) instead of
    # refusing. None for unconstrained requests (and legacy peers).
    request.on_token = lambda t: q.put(("tok", (int(t), request.dfa_state)))
    if cancel_key:
        lifecycle.register(cancel_key, request)
    try:
        try:
            engine.submit(request)
        except ShedError as e:
            raise FleetShedError(str(e), retry_after_s=e.retry_after_s) from e
    except BaseException:
        if cancel_key:
            lifecycle.unregister(cancel_key, request)
        raise

    def frames() -> Iterator[dict]:
        seq = 0
        result = None
        hard_stop = time.monotonic() + timeout_s
        try:
            while result is None:
                try:
                    item = q.get(timeout=hb)
                except queue.Empty:
                    if time.monotonic() >= hard_stop:
                        # wedged engine / blown hop budget: cancel and fail
                        # the hop — the deadline already rode the options,
                        # so this fires only when the engine ignores it
                        request.cancel()
                        yield {
                            "seq": seq, "kind": "error",
                            "error": f"hop budget ({timeout_s:.1f}s) "
                                     "exhausted mid-stream",
                        }
                        return
                    beat = {"seq": seq, "kind": "heartbeat"}
                    if seq == 0:
                        beat["v"] = FRAME_SCHEMA
                    yield beat
                    seq += 1
                    continue
                batch = [item]
                while True:
                    try:
                        batch.append(q.get_nowait())
                    except queue.Empty:
                        break
                toks = [v[0] for k, v in batch if k == "tok"]
                dfa_state = None
                for kind, value in batch:
                    if kind == "done":
                        result = value
                    elif kind == "tok" and value[1] is not None:
                        # the state matching the LAST token of this frame
                        # (per-token states are monotone within a batch)
                        dfa_state = int(value[1])
                if toks:
                    frame = {"seq": seq, "kind": "tokens", "tokens": toks}
                    if dfa_state is not None:
                        frame["dfa_state"] = dfa_state
                    if seq == 0:
                        frame["v"] = FRAME_SCHEMA
                    yield frame
                    seq += 1
            if result.error is not None:
                yield {
                    "seq": seq, "kind": "error", "error": str(result.error),
                }
                return
            end = {
                "seq": seq, "kind": "end",
                "finish_reason": result.finish_reason,
                "prompt_tokens": result.prompt_tokens,
                "ttft_s": round(result.ttft_s, 6),
                "total_s": round(result.total_s, 6),
                "usage": {
                    "prompt_tokens": result.prompt_tokens,
                    "completion_tokens": len(result.tokens),
                },
            }
            if seq == 0:
                end["v"] = FRAME_SCHEMA
            yield end
        finally:
            if result is None:
                # consumer walked away mid-stream (disconnect, failover
                # cut): free the slot at the next chunk boundary
                request.cancel()
            if cancel_key:
                lifecycle.unregister(cancel_key, request)

    return _EngineFrameStream(request, cancel_key, frames())


# ---------------------------------------------------------------------------
# Replica transports (duck-typed: .replica_id, .fetch_beacon(), .generate())
# ---------------------------------------------------------------------------


class InProcessReplica:
    """A replica living in this process — the unit-test / embedded-runner
    transport, and the 'self' handle when the completions service fronts
    its own engine plus remote peers."""

    is_local = True

    def __init__(
        self, replica_id: str, engine: Any, url: str = "",
        role: str = "mixed",
    ) -> None:
        self.replica_id = str(replica_id)
        self.engine = engine
        self.url = url or f"local:{replica_id}"
        self.role = str(role)

    def fetch_beacon(self) -> dict[str, Any]:
        return beacon_from_engine(
            self.replica_id, self.engine, url=self.url, role=self.role
        )

    def generate(
        self, tokens, options: Optional[dict] = None, timeout_s: float = 600.0,
    ) -> dict[str, Any]:
        try:
            return engine_generate(
                self.engine,
                {"prompt_tokens": list(tokens), "options": options or {}},
                timeout_s=timeout_s,
            )
        except (FleetShedError, ValueError):
            # sheds re-route; a BAD REQUEST is the caller's bug — neither
            # may quarantine the replica (a malformed request retried
            # across the fleet would mark every replica failed)
            raise
        except Exception as e:  # noqa: BLE001 — stopped/crashed engine
            raise ReplicaError(f"replica {self.replica_id}: {e}") from e

    def generate_stream(
        self, tokens, options: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ) -> Iterator[dict]:
        """Streaming dispatch into the in-process engine: the same §17
        frame iterator the HTTP transport yields, so the router's warm-
        failover path treats local and remote replicas identically."""
        options = dict(options or {})
        try:
            frames = engine_generate_stream(
                self.engine,
                {"prompt_tokens": list(tokens), "options": options},
                timeout_s=(
                    timeout_s if timeout_s is not None
                    else hop_timeout_s(options)
                ),
            )
        except (FleetShedError, ValueError):
            raise  # sheds re-route; a bad REQUEST never quarantines
        except Exception as e:  # noqa: BLE001 — stopped/crashed engine
            raise ReplicaError(f"replica {self.replica_id}: {e}") from e
        return self._guard_frames(frames)

    def _guard_frames(self, frames: Iterator[dict]) -> Iterator[dict]:
        # mid-stream engine failures surface as ReplicaError so failover
        # handling is one code path across transports; error frames are
        # consumed here (the router never sees transport-internal kinds)
        try:
            for frame in frames:
                if frame.get("kind") == "error":
                    raise ReplicaError(
                        f"replica {self.replica_id}: {frame.get('error')}"
                    )
                yield frame
        except (FleetShedError, ReplicaError, ValueError):
            raise
        except Exception as e:  # noqa: BLE001 — engine died mid-stream
            raise ReplicaError(f"replica {self.replica_id}: {e}") from e
        finally:
            close = getattr(frames, "close", None)
            if close is not None:
                close()  # cancels the engine request if the consumer left

    def reset_histograms(self) -> None:
        self.engine.reset_histograms()


class HttpReplica:
    """A replica behind its runtime HTTP server (entrypoint pods, the
    bench's subprocess fleet). Uses stdlib urllib — these calls run on the
    router's refresher thread and dispatch executors, never an event loop."""

    is_local = False

    def __init__(
        self, replica_id: str, base_url: str,
        beacon_timeout_s: float = 2.0,
        generate_timeout_s: float = DEFAULT_HOP_TIMEOUT_S,
        stream_idle_timeout_s: float = 20.0,
    ) -> None:
        self.replica_id = str(replica_id)
        self.url = base_url.rstrip("/")
        self.beacon_timeout_s = beacon_timeout_s
        self.generate_timeout_s = generate_timeout_s
        # dead-peer detection on an OPEN stream (§17): the peer heartbeats
        # every ~idle/4 while decoding slowly, so a wire silent past this
        # bound is a dead/stalled peer, not a slow one — the hop fails and
        # the router's warm failover takes over. The request's deadline
        # (when tighter) bounds the whole hop regardless.
        self.stream_idle_timeout_s = float(stream_idle_timeout_s)
        # wire capabilities from the peer's last beacon (§21 negotiation):
        # dispatch asks for the v2 binary stream only once the peer has
        # PROVEN it speaks it — before the first beacon lands (or toward
        # a legacy peer) every hop stays v1 NDJSON
        self.caps: frozenset = frozenset()

    def _get(self, path: str, timeout_s: float) -> dict[str, Any]:
        with urllib.request.urlopen(self.url + path, timeout=timeout_s) as r:
            return json.loads(r.read().decode("utf-8"))

    @staticmethod
    def _tighten_read_timeout(resp: Any, timeout_s: float) -> None:
        """Once the response HEADERS have arrived, drop the socket timeout
        from the hop budget to the idle bound: from here on, silence
        between frames longer than the heartbeat cadence means a dead
        peer. Best-effort over stdlib internals (no public accessor for
        the response's socket) — on failure the hop budget remains the
        only bound, i.e. the pre-§17 behavior."""
        try:
            resp.fp.raw._sock.settimeout(  # noqa: SLF001
                max(0.1, float(timeout_s))
            )
        except (AttributeError, OSError):
            pass

    def fetch_beacon(self) -> dict[str, Any]:
        try:
            doc = self._get("/state", self.beacon_timeout_s)
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ReplicaError(f"replica {self.replica_id}: {e}") from e
        replicas = doc.get("replicas") or []
        for b in replicas:
            if b.get("id") == self.replica_id:
                self.caps = frozenset(str(c) for c in b.get("caps") or ())
                return b
        if replicas:
            self.caps = frozenset(
                str(c) for c in replicas[0].get("caps") or ()
            )
            return replicas[0]
        raise ReplicaError(f"replica {self.replica_id}: empty /state")

    def generate(
        self, tokens, options: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ) -> dict[str, Any]:
        """Blocking dispatch: drain the streaming hop into the one-shot
        result shape (back-compat surface for callers that want the whole
        completion — the wire underneath always streams, §17)."""
        out_tokens: list[int] = []
        end: Optional[dict] = None
        for frame in self.generate_stream(tokens, options, timeout_s=timeout_s):
            kind = frame.get("kind")
            if kind == "tokens":
                out_tokens.extend(int(t) for t in frame.get("tokens") or [])
            elif kind == "end":
                end = frame
        if end is None:  # generate_stream raises first; belt and braces
            raise ReplicaError(
                f"replica {self.replica_id}: stream ended without a "
                "terminal frame"
            )
        return {
            "tokens": out_tokens,
            "finish_reason": str(end.get("finish_reason", "stop")),
            "prompt_tokens": int(end.get("prompt_tokens", 0)),
            "ttft_s": float(end.get("ttft_s", 0.0)),
            "total_s": float(end.get("total_s", 0.0)),
        }

    def generate_stream(
        self, tokens, options: Optional[dict] = None,
        timeout_s: Optional[float] = None,
        idle_timeout_s: Optional[float] = None,
    ) -> Iterator[dict]:
        """One streaming fleet hop (docs/SERVING.md §17): POST the request
        with ``stream: true`` and yield validated frames as they arrive.
        The request's deadline bounds CONNECT and every READ (hop budget =
        remaining deadline + slack, never the flat default); the idle
        timeout catches a silent peer between heartbeats. Frame validation
        — contiguous seq, parseable JSON, terminal frame present — fails
        the hop as ReplicaError, which is the router's failover signal;
        tokens already yielded stay valid for a warm resume."""
        options = dict(options or {})
        injector = wire_injector()
        if injector is not None and injector.fires("net-connect"):
            raise ReplicaError(
                f"replica {self.replica_id}: injected net-connect fault"
            )
        total_s = (
            float(timeout_s) if timeout_s is not None
            else hop_timeout_s(options, self.generate_timeout_s)
        )
        idle_s = float(
            idle_timeout_s if idle_timeout_s is not None
            else self.stream_idle_timeout_s
        )
        # urlopen's timeout is the SOCKET timeout: it bounds the connect
        # and then every individual recv — exactly the per-read bound we
        # want between frames
        read_timeout = max(0.1, min(total_s, idle_s))
        payload: dict[str, Any] = {
            "prompt_tokens": list(map(int, tokens)),
            "options": options,
            "stream": True,
            # ask the peer to heartbeat well inside our idle timeout
            "heartbeat-s": round(max(0.05, read_timeout / 4.0), 3),
        }
        if "frames2" in self.caps:
            # §21 negotiation: the peer's beacon advertised the binary
            # token-stream codec — ask for it; its answer's Content-Type
            # is authoritative (a restarted-as-v1 peer still answers
            # NDJSON and the hop just reads v1)
            payload["wire"] = "v2"
        body = json.dumps(payload).encode("utf-8")
        req = urllib.request.Request(
            self.url + "/fleet/generate", data=body,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        hard_stop = time.monotonic() + total_s
        try:
            # the HOP BUDGET (not the idle bound) governs connect + time-
            # to-headers: the peer's eager submit may legitimately block
            # on admission backpressure (shed-policy "block") with no
            # bytes flowing yet — quarantining a merely-busy replica
            # after idle_s would flap the whole fleet under load. Once
            # the stream opens, the socket timeout tightens to the idle
            # bound below.
            resp = urllib.request.urlopen(req, timeout=max(0.1, total_s))
        except urllib.error.HTTPError as e:
            if e.code == 429:
                retry = float(e.headers.get("Retry-After") or 1.0)
                raise FleetShedError(
                    f"replica {self.replica_id} shed", retry_after_s=retry
                ) from e
            if 400 <= e.code < 500:
                # the REQUEST is bad, not the replica: retrying it on the
                # rest of the fleet would brown out every replica
                raise ValueError(
                    f"replica {self.replica_id} rejected request: "
                    f"HTTP {e.code} {e.reason}"
                ) from e
            raise ReplicaError(
                f"replica {self.replica_id}: HTTP {e.code}"
            ) from e
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ReplicaError(f"replica {self.replica_id}: {e}") from e
        self._tighten_read_timeout(resp, read_timeout)
        ctype = str(resp.headers.get("Content-Type") or "")
        if "lstpu-frames2" in ctype:
            try:
                with resp:
                    yield from self._v2_frames(resp, hard_stop, total_s)
            except GeneratorExit:
                resp.close()
                raise
            return
        expected_seq = 0
        try:
            with resp:
                while True:
                    if time.monotonic() >= hard_stop:
                        raise ReplicaError(
                            f"replica {self.replica_id}: hop budget "
                            f"({total_s:.1f}s) exhausted mid-stream"
                        )
                    try:
                        line = resp.readline()
                    except (OSError, http.client.HTTPException, ValueError) as e:
                        # socket timeout (idle peer), connection reset
                        # (net-cut), chunked-decode garbage — all one
                        # verdict: this hop is dead
                        raise ReplicaError(
                            f"replica {self.replica_id}: stream read failed "
                            f"({e or type(e).__name__})"
                        ) from e
                    if not line:
                        raise ReplicaError(
                            f"replica {self.replica_id}: stream closed "
                            "before terminal frame"
                        )
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        frame = json.loads(line.decode("utf-8"))
                    except (ValueError, UnicodeDecodeError) as e:
                        raise ReplicaError(
                            f"replica {self.replica_id}: corrupt stream "
                            f"frame ({e})"
                        ) from e
                    if (
                        expected_seq == 0
                        and isinstance(frame, dict)
                        and "seq" not in frame
                        and ("tokens" in frame or "finish_reason" in frame)
                    ):
                        # a NOT-YET-UPGRADED peer ignored `stream: true`
                        # and answered the legacy one-shot JSON body:
                        # adapt it instead of quarantining a healthy
                        # replica mid-rolling-upgrade
                        try:
                            adapted = list(result_frames(
                                frame, prompt_len=len(list(tokens))
                            ))
                        except (TypeError, ValueError) as e:
                            raise ReplicaError(
                                f"replica {self.replica_id}: corrupt "
                                f"legacy response body ({e})"
                            ) from e
                        for a in adapted:
                            yield a
                        return
                    if (
                        not isinstance(frame, dict)
                        or frame.get("seq") != expected_seq
                    ):
                        got = (
                            frame.get("seq") if isinstance(frame, dict)
                            else None
                        )
                        raise ReplicaError(
                            f"replica {self.replica_id}: stream sequence "
                            f"broken (got {got!r}, want {expected_seq})"
                        )
                    expected_seq += 1
                    kind = frame.get("kind")
                    if kind == "error":
                        raise ReplicaError(
                            f"replica {self.replica_id}: "
                            f"{frame.get('error')}"
                        )
                    if kind == "tokens":
                        # the wire is untrusted: a parseable frame whose
                        # token VALUES are garbage must fail the hop (the
                        # failover signal), never leak a ValueError the
                        # router would misread as a bad client request
                        try:
                            frame["tokens"] = [
                                int(t) for t in frame.get("tokens") or []
                            ]
                        except (TypeError, ValueError) as e:
                            raise ReplicaError(
                                f"replica {self.replica_id}: corrupt "
                                f"tokens frame ({e})"
                            ) from e
                    yield frame
                    if kind == "end":
                        return
        except GeneratorExit:
            # consumer abandoned the stream (local shortcut, failover of
            # ANOTHER hop): close the socket so the peer's handler sees
            # the disconnect and cancels its engine request
            resp.close()
            raise

    def _v2_frames(
        self, resp: Any, hard_stop: float, total_s: float,
    ) -> Iterator[dict]:
        """Read one ``lstpu-frames-v2`` binary stream body (§21) and yield
        the same validated §17 frame dicts the NDJSON path yields — seq
        contiguity, error→ReplicaError, terminal-frame-required and the
        hop budget all enforced identically; only the bytes differ. Any
        codec violation (truncated prelude, CRC mismatch, bad magic) is a
        dead hop: ReplicaError, the router's failover signal, never a
        hang (the socket timeout bounds every read underneath)."""
        from langstream_tpu.serving import wire as wire_mod

        def read(n: int) -> bytes:
            try:
                return resp.read(n)
            except (OSError, http.client.HTTPException, ValueError) as e:
                raise ReplicaError(
                    f"replica {self.replica_id}: stream read failed "
                    f"({e or type(e).__name__})"
                ) from e

        expected_seq = 0
        ended = False
        try:
            preamble = wire_mod.read_exact(
                read, len(wire_mod.FRAMES2_PREAMBLE)
            )
            if preamble != wire_mod.FRAMES2_PREAMBLE:
                raise wire_mod.WireError(
                    f"bad frames2 preamble {preamble!r}"
                )
            for frame in wire_mod.decode_stream_frames(read):
                if time.monotonic() >= hard_stop:
                    raise ReplicaError(
                        f"replica {self.replica_id}: hop budget "
                        f"({total_s:.1f}s) exhausted mid-stream"
                    )
                if frame.get("seq") != expected_seq:
                    raise ReplicaError(
                        f"replica {self.replica_id}: stream sequence "
                        f"broken (got {frame.get('seq')!r}, "
                        f"want {expected_seq})"
                    )
                expected_seq += 1
                kind = frame.get("kind")
                if kind == "error":
                    raise ReplicaError(
                        f"replica {self.replica_id}: {frame.get('error')}"
                    )
                yield frame
                if kind == "end":
                    ended = True
                    break
        except wire_mod.WireError as e:
            raise ReplicaError(
                f"replica {self.replica_id}: corrupt v2 stream ({e})"
            ) from e
        if not ended:
            raise ReplicaError(
                f"replica {self.replica_id}: stream closed before "
                "terminal frame"
            )

    def migrate_out(
        self, tokens, dest_url: str, state: Optional[dict],
        timeout_s: float, wire: str = "v1",
    ) -> dict:
        """Command this (remote) replica to push a KV-page migration to
        ``dest_url``'s ``POST /fleet/migrate`` (§18). ``wire="v2"`` asks
        the source to ship the binary codec — set only when the DEST
        advertises ``kvmig2`` (the source falls back to v1 if its own
        version predates the key). Returns the receiver's ACK as relayed
        by the source. Failures raise MigrationError — the source retains
        its pages (it frees only on the ACK it relays here)."""
        from langstream_tpu.serving.migrate import MigrationError

        body = json.dumps({
            "prompt_tokens": [int(t) for t in tokens],
            "dest": str(dest_url),
            "state": dict(state or {}),
            "timeout-s": float(timeout_s),
            "wire": "v2" if wire == "v2" else "v1",
        }).encode("utf-8")
        req = urllib.request.Request(
            self.url + "/fleet/migrate-out", data=body,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=max(0.1, float(timeout_s) + 2.0)
            ) as r:
                ack = json.loads(r.read().decode("utf-8"))
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise MigrationError(
                f"replica {self.replica_id} migrate-out failed: {e}"
            ) from e
        if not ack.get("ok"):
            raise MigrationError(
                f"replica {self.replica_id} migrate-out rejected: "
                f"{ack.get('error')!r}"
            )
        return ack

    def p2p_fetch(
        self, tokens, source_url: str, timeout_s: float, wire: str = "v1",
    ) -> dict:
        """Command this (remote) replica to pull the pages covering
        ``tokens`` from ``source_url``'s ``POST /fleet/pages`` and bind
        them (§21). Returns the bind ACK. Failures raise MigrationError —
        the commanding router falls back to the cold path; the owner
        never released anything (a fetch copies)."""
        from langstream_tpu.serving.migrate import MigrationError

        body = json.dumps({
            "prompt_tokens": [int(t) for t in tokens],
            "source": str(source_url),
            "timeout-s": float(timeout_s),
            "wire": "v2" if wire == "v2" else "v1",
        }).encode("utf-8")
        req = urllib.request.Request(
            self.url + "/fleet/fetch", data=body,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=max(0.1, float(timeout_s) + 2.0)
            ) as r:
                ack = json.loads(r.read().decode("utf-8"))
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise MigrationError(
                f"replica {self.replica_id} p2p fetch failed: {e}"
            ) from e
        if not ack.get("ok"):
            raise MigrationError(
                f"replica {self.replica_id} p2p fetch rejected: "
                f"{ack.get('error')!r}"
            )
        return ack

    def reset_histograms(self) -> None:
        try:
            urllib.request.urlopen(
                urllib.request.Request(
                    self.url + "/fleet/reset", data=b"{}", method="POST",
                    headers={"Content-Type": "application/json"},
                ),
                timeout=self.beacon_timeout_s,
            ).read()
        except (urllib.error.URLError, OSError) as e:
            raise ReplicaError(f"replica {self.replica_id}: {e}") from e


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------


@dataclass
class _ReplicaState:
    handle: Any
    beacon: dict[str, Any] = field(default_factory=dict)
    beacon_at: float = -1e18  # monotonic of last SUCCESSFUL refresh
    failed_at: float = -1e18  # monotonic of last mark_failed
    digests: dict[str, int] = field(default_factory=dict)  # digest → length
    # hibernated (host-tier) prefix digests: the session's KV survives on
    # the replica but needs a restore — scored at spill_discount
    spilled_digests: dict[str, int] = field(default_factory=dict)
    adapters: frozenset = frozenset()  # resident LoRA adapter names
    # disaggregated serving (§18): the replica's advertised phase role —
    # prefill replicas absorb long-prompt bursts, decode replicas hold the
    # steady state, mixed (the default) serves both
    role: str = "mixed"
    # advertised wire capabilities ("kvmig", "dfa-resume", ...): empty for
    # legacy peers — the router only migrates to / resumes constrained
    # streams on replicas that prove they understand the payload
    caps: frozenset = frozenset()
    # per-tenant queue pressure (docs/SERVING.md §19): tenant id →
    # {queued, queue_wait_ema_s, over_quota, ...} from the beacon; empty
    # for legacy peers (tenant-aware routing simply has no signal then)
    tenants: dict[str, dict] = field(default_factory=dict)
    # the replica's brownout ladder level (0 = normal)
    brownout_level: int = 0
    # circuit breaker (docs/SERVING.md §17): consecutive beacon-fetch +
    # dispatch failures drive an exponential probe backoff — the refresh
    # loop stops hammering a dead peer's /state every interval, and the
    # backoff expiry IS the half-open probe slot (one beacon fetch; a
    # fresh beacon closes the circuit, a failure doubles the backoff)
    fails: int = 0
    backoff_until: float = -1e18
    circuit_open: bool = False


@dataclass
class RouteDecision:
    replica_id: str
    handle: Any
    kind: str  # affinity | sticky | balanced | prefill | migrated
    expected_match: int
    score: float
    # disaggregated handoff (§18): True when this route lands the PREFILL
    # phase on a prefill-tagged replica and the router intends to migrate
    # the KV to a decode replica once the first token lands — the
    # completions fast path must NOT short-circuit such a route even when
    # it is local (the router owns the orchestration)
    disagg: bool = False
    # P2P page fetch hint (§21): the live peer whose advertised prefix
    # beats this replica's own match by ≥ p2p_threshold tokens — the
    # router pulls the pages from it before dispatch so the prefix admits
    # warm; None when nobody qualifies. Best-effort: every fetch failure
    # degrades to the local cold path.
    p2p_source: Optional[str] = None
    p2p_match: int = 0


class FleetRouter:
    """Prefix-affinity-first, load-second dispatch across replicas.

    ``route()`` is pure host bookkeeping under one lock — no I/O, no
    hashing beyond one digest per advertised boundary length (<1 ms p50,
    histogram-enforced by the bench). Beacons refresh on a background
    thread (``start()``); a replica whose beacon goes stale, whose process
    stops answering, or that advertises drain/quarantine simply drops out
    of the routable set — requests re-route, nothing hangs."""

    POLICIES = ("affinity", "round-robin", "least-loaded")

    # lock discipline registry (analysis pass `locks`, docs/ANALYSIS.md):
    # routing state and every counter stats() snapshots live under _lock;
    # histograms record under their own _hist_lock so a slow percentile
    # read never blocks route().
    _GUARDED = {
        "_lock": (
            "_replicas", "_sticky", "_rr", "_last_demand_t", "_p2p_bw_ema",
            "routed_affinity_total", "routed_sticky_total",
            "sticky_held_total", "routed_balanced_total",
            "routed_adapter_total", "shed_total", "failover_total",
            "stream_failover_total", "beacon_failures_total",
            "circuit_open_total", "tenant_shed_total",
            "routed_tenant_affinity_total", "routed_prefill_total",
            "migrations_total", "migrate_pages_total",
            "migrate_bytes_total", "migrate_fallbacks_total",
            "p2p_fetch_total", "p2p_fetch_fallback_total",
            "p2p_bytes_in_total", "p2p_cost_routed_total",
            "prefetch_total", "prefetch_fetch_total",
        ),
    }

    def __init__(
        self,
        replicas: list[Any],
        *,
        lam: float = DEFAULT_LAMBDA,
        policy: str = "affinity",
        beacon_ttl_s: float = 10.0,
        refresh_interval_s: float = 0.5,
        sticky_ttl_s: float = 600.0,
        fail_cooldown_s: float = 5.0,
        shed_queue_wait_s: float = 30.0,
        adapter_affinity_tokens: float = 512.0,
        tenant_affinity_tokens: float = 256.0,
        brownout_penalty_tokens: float = 128.0,
        spill_discount: float = 0.5,
        beacon_backoff_max_s: float = 30.0,
        circuit_failures: int = 3,
        prefill_route_threshold: int = 2048,
        migrate: bool = True,
        migrate_timeout_s: float = 30.0,
        p2p: bool = True,
        p2p_threshold: int = 256,
        p2p_min_gap: int = 0,
    ) -> None:
        if policy not in self.POLICIES:
            raise ValueError(
                f"unknown fleet policy {policy!r}; supported: {self.POLICIES}"
            )
        if not replicas:
            raise ValueError("fleet router needs >= 1 replica")
        self.lam = float(lam)
        self.policy = policy
        self.beacon_ttl_s = float(beacon_ttl_s)
        self.refresh_interval_s = float(refresh_interval_s)
        self.sticky_ttl_s = float(sticky_ttl_s)
        self.fail_cooldown_s = float(fail_cooldown_s)
        self.shed_queue_wait_s = float(shed_queue_wait_s)
        # adapter affinity in PREFIX-TOKEN units: routing a tenant to a
        # replica already holding its adapter is scored as worth this many
        # warm prefix tokens (a hot-swap dispatch ≈ re-prefilling that
        # much prompt on the engines measured; tune alongside λ — §15)
        self.adapter_affinity_tokens = float(adapter_affinity_tokens)
        # tenant-aware routing (§19): a tenant's queued backlog on a
        # replica scores its NEXT request toward that same replica (in
        # prefix-token units) — aggressor overflow concentrates where the
        # aggressor already queues, away from the victim's replica; a
        # browned-out replica is penalized per ladder level
        self.tenant_affinity_tokens = float(tenant_affinity_tokens)
        self.brownout_penalty_tokens = float(brownout_penalty_tokens)
        # a HIBERNATED prefix match (the owner spilled the session's pages
        # to host RAM) is worth this fraction of a device-resident match:
        # the restore is a DMA upload, cheaper than re-prefilling but not
        # free — and it says nothing about the replica being otherwise
        # idle. 0 ignores spilled advertisements; 1 scores them at par.
        self.spill_discount = min(1.0, max(0.0, float(spill_discount)))
        # probe backoff cap + the consecutive-failure count at which the
        # breaker is DECLARED open (routability is already gated by beacon
        # freshness from the first failure; the threshold only decides
        # when the state — and the circuit_open_total transition counter —
        # reads "open" rather than "blip")
        self.beacon_backoff_max_s = float(beacon_backoff_max_s)
        self.circuit_failures = max(1, int(circuit_failures))
        # disaggregated prefill/decode (§18): an admission whose ESTIMATED
        # prefill (prompt minus the best advertised prefix match) reaches
        # the threshold routes to a prefill-tagged replica, prefills + its
        # first token there, then its KV pages MIGRATE to a decode replica
        # where the stream finishes — one 32k prompt never camps on the
        # replicas holding 95 steady decode streams. Takes effect only
        # when both roles are present and routable; `migrate=False` keeps
        # role-aware routing but decodes in place (no transfer).
        self.prefill_route_threshold = max(1, int(prefill_route_threshold))
        self.migrate_enabled = bool(migrate)
        self.migrate_timeout_s = float(migrate_timeout_s)
        # peer-to-peer page fetch on radix miss (§21, ROADMAP 2a): when
        # the chosen replica's own best match trails another live peer's
        # advertised (resident or spilled) prefix by at least
        # p2p_threshold tokens, the router commands a page fetch from the
        # owner over the migration wire before dispatch — the prefix
        # admits warm instead of re-prefilling, and every failure
        # (checksum, net-cut, deadline, no capable peer) degrades to the
        # local cold path. Both sides must advertise the "p2p" cap.
        self.p2p_enabled = bool(p2p)
        self.p2p_threshold = max(1, int(p2p_threshold))
        # fetch-vs-prefill cost model (§23): once both sides publish the
        # inputs — the owner's page geometry, the destination's measured
        # prefill tokens/s, and this router's observed fetch bandwidth —
        # the P2P decision compares ESTIMATED seconds (bytes moved over
        # the wire vs the gap re-prefilled locally) instead of the flat
        # token threshold. The flat threshold stays as the fallback when
        # any input is missing (legacy beacons, cold router), and
        # p2p_min_gap is the compat FLOOR either way: a gap below it
        # never fetches, however favorable the estimate — pulling 3
        # pages' worth of tokens is never worth a wire round-trip. 0
        # derives the floor from the threshold.
        self.p2p_min_gap = (
            max(1, int(p2p_min_gap))
            if p2p_min_gap
            else min(64, self.p2p_threshold)
        )
        # observed P2P fetch bandwidth (bytes/s, EMA over landed fetches):
        # the cost model's wire-speed input — measured, like the beacon's
        # prefill_tps, so the estimate tracks the actual deployment
        self._p2p_bw_ema = 0.0
        self._lock = threading.Lock()
        self._replicas: dict[str, _ReplicaState] = {}
        for r in replicas:
            if r.replica_id in self._replicas:
                raise ValueError(f"duplicate replica id {r.replica_id!r}")
            self._replicas[r.replica_id] = _ReplicaState(handle=r)
        self._sticky: dict[str, tuple[str, float]] = {}
        self._rr = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters (under _lock) + the dispatch-overhead histogram the
        # acceptance criterion reads
        self.routed_affinity_total = 0
        self.routed_sticky_total = 0
        # sticky pins held through an owner's recovery window (§20): the
        # session served elsewhere WITHOUT repointing, so it lands back on
        # its owner after the backoff
        self.sticky_held_total = 0
        self.routed_balanced_total = 0
        self.routed_adapter_total = 0
        self.shed_total = 0
        self.failover_total = 0
        # wire hardening (docs/SERVING.md §17): mid-STREAM warm failovers
        # (a cold failover before the first frame counts only in
        # failover_total), beacon-fetch failures, and circuit-open
        # transitions
        self.stream_failover_total = 0
        self.beacon_failures_total = 0
        self.circuit_open_total = 0
        # multi-tenant overload control (§19): router-level tenant sheds
        # (over-quota fleet-wide — counted inside shed_total too) and
        # tenant-pressure-affinity routes (the aggressor's overflow kept
        # on its own replica instead of balanced onto the victim's)
        self.tenant_shed_total = 0
        self.routed_tenant_affinity_total = 0
        # disaggregated serving (§18): prefill-handoff routes, completed
        # migrations (pages/bytes by receiver ACK), and fallbacks (the
        # migration failed and the stream decoded in place / re-prefilled)
        self.routed_prefill_total = 0
        self.migrations_total = 0
        self.migrate_pages_total = 0
        self.migrate_bytes_total = 0
        self.migrate_fallbacks_total = 0
        # P2P page fetch (§21): completed fetches (with bytes pulled in,
        # by receiver ACK) and fallbacks — a failed fetch costs one
        # counter bump and a flight dump, then the request prefills cold
        self.p2p_fetch_total = 0
        self.p2p_fetch_fallback_total = 0
        self.p2p_bytes_in_total = 0
        # fetch-vs-prefill cost model + prefetch-on-hint (§23): hints
        # admitted by the ESTIMATE (not the flat threshold), prefetch
        # calls taken, and prefetches that actually moved pages
        self.p2p_cost_routed_total = 0
        self.prefetch_total = 0
        self.prefetch_fetch_total = 0
        # scale-to-zero (§23): monotonic stamp of the last routed demand —
        # desired_replicas() returns 0 only once demand has been quiet for
        # a full target window AND every live replica checkpoints durably
        self._last_demand_t = time.monotonic()
        self._hist_lock = threading.Lock()
        self.dispatch_hist = Histogram(
            "fleet_dispatch_s",
            "router route() host wall time per dispatch (s)",
            log_buckets(1e-7, 1.0, 4),
        )
        self.hop_hist = Histogram(
            "fleet_hop_s",
            FLEET_HISTOGRAMS["fleet_hop_s"]["help"],
            FLEET_HISTOGRAMS["fleet_hop_s"]["buckets"],
        )
        self.migrate_hist = Histogram(
            "fleet_migrate_s",
            FLEET_HISTOGRAMS["fleet_migrate_s"]["help"],
            FLEET_HISTOGRAMS["fleet_migrate_s"]["buckets"],
        )
        # the router's own flight recorder: its ring stays empty (no
        # engine loop here) — fleet-failover dumps carry the hop's frame
        # TRACE in extra instead, token-content-free like every dump
        self._flight = FlightRecorder(
            capacity=8,
            dump_dir=os.environ.get("LSTPU_FLIGHT_DIR") or None,
        )

    # -- beacon refresh -----------------------------------------------------

    def refresh_all(self, force: bool = True) -> int:
        """Fetch every replica's beacon once (synchronously). Returns how
        many refreshed successfully. Failures just leave the old beacon to
        age out — route() treats stale as unroutable — and feed the
        per-replica circuit breaker (§17): consecutive failures back the
        probe off exponentially (capped at ``beacon_backoff_max_s``), so
        the refresh loop stops hitting a dead peer's /state every interval
        forever. ``force=False`` (the background loop) honors the backoff
        — a skipped replica is simply not yet due for its half-open probe;
        the default probes everything (manual refresh, tests, start())."""
        ok = 0
        for state in list(self._replicas.values()):
            if not force:
                with self._lock:
                    if time.monotonic() < state.backoff_until:
                        continue  # circuit open: not due for the probe
            try:
                beacon = state.handle.fetch_beacon()
            except ReplicaError as e:
                log.debug("beacon refresh failed: %s", e)
                with self._lock:
                    self._note_failure_locked(state, beacon_fetch=True)
                continue
            except Exception:  # noqa: BLE001 — refresher must never die
                log.exception(
                    "beacon refresh crashed for %s", state.handle.replica_id
                )
                with self._lock:
                    self._note_failure_locked(state, beacon_fetch=True)
                continue
            with self._lock:
                state.beacon = beacon
                state.beacon_at = time.monotonic()
                state.digests = {
                    d: int(n) for d, n in (beacon.get("prefixes") or [])
                }
                state.spilled_digests = {
                    d: int(n)
                    for d, n in (beacon.get("spilled_prefixes") or [])
                }
                state.adapters = frozenset(
                    str(a) for a in (beacon.get("adapters") or [])
                )
                role = str(beacon.get("role") or "mixed")
                state.role = (
                    role if role in ("prefill", "decode", "mixed")
                    else "mixed"
                )
                state.caps = frozenset(
                    str(c) for c in (beacon.get("caps") or [])
                )
                state.tenants = {
                    str(name): dict(t)
                    for name, t in (beacon.get("tenants") or {}).items()
                    if isinstance(t, dict)
                }
                state.brownout_level = int(
                    beacon.get("brownout_level", 0) or 0
                )
                # a fresh beacon is the half-open probe SUCCEEDING: close
                # the circuit and forget the backoff
                if state.circuit_open:
                    log.info(
                        "circuit closed for replica %s (fresh beacon after "
                        "%d failure(s))", state.handle.replica_id, state.fails,
                    )
                state.fails = 0
                state.backoff_until = -1e18
                state.circuit_open = False
            ok += 1
        return ok

    def _note_failure_locked(
        self, state: _ReplicaState, beacon_fetch: bool
    ) -> None:
        """One beacon-fetch or dispatch failure (caller holds ``_lock``):
        advance the breaker — exponential probe backoff from the first
        failure, the OPEN transition (counted once) at the threshold."""
        state.fails += 1
        if beacon_fetch:
            self.beacon_failures_total += 1
        base = max(self.refresh_interval_s, 0.1)
        state.backoff_until = time.monotonic() + min(
            base * (2 ** min(state.fails - 1, 16)), self.beacon_backoff_max_s
        )
        if state.fails >= self.circuit_failures and not state.circuit_open:
            state.circuit_open = True
            self.circuit_open_total += 1
            log.warning(
                "circuit OPEN for replica %s after %d consecutive "
                "failure(s); half-open probe in <= %.1fs",
                state.handle.replica_id, state.fails,
                max(0.0, state.backoff_until - time.monotonic()),
            )

    def start(self, initial_refresh: bool = True) -> None:
        if initial_refresh:
            self.refresh_all()
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._refresh_loop, name="fleet-beacons", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self.refresh_interval_s):
            # the loop honors per-replica backoff: a dead peer is probed
            # on the circuit's half-open schedule, not every interval
            self.refresh_all(force=False)

    # -- health -------------------------------------------------------------

    @property
    def replica_count(self) -> int:
        return len(self._replicas)

    def note_failover(self, replica_id: str) -> None:
        """A caller-observed mid-dispatch death: quarantine the replica AND
        count the failover — the completions path's failover loop must show
        up in fleet stats exactly like router.generate's own."""
        self.mark_failed(replica_id)
        with self._lock:
            self.failover_total += 1

    def mark_failed(self, replica_id: str) -> None:
        """A dispatch to this replica failed: quarantine it for
        ``fail_cooldown_s`` (and until a FRESH beacon proves it back). Its
        sticky sessions fail over cold at their next request. Dispatch
        failures feed the same circuit breaker as beacon-fetch failures —
        readmission is always through the half-open beacon probe."""
        with self._lock:
            state = self._replicas.get(replica_id)
            if state is None:
                return
            now = time.monotonic()
            state.failed_at = now
            # the beacon that routed us here predates the failure — drop it
            # so recovery requires a refresh newer than the incident
            state.beacon_at = -1e18
            self._note_failure_locked(state, beacon_fetch=False)

    def _routable(self, state: _ReplicaState, now: float) -> bool:
        if now - state.failed_at < self.fail_cooldown_s:
            return False
        if now - state.beacon_at > self.beacon_ttl_s:
            return False
        b = state.beacon
        # `recovering` excludes WITHOUT quarantining (§20): no failed_at
        # stamp, no circuit-breaker count — the replica readmits itself
        # with its first post-recovery beacon instead of serving a
        # fail_cooldown_s sentence for a recovery that took seconds
        return not (
            b.get("draining") or b.get("quarantined") or b.get("recovering")
        )

    def _recovering_hold(self, state: Optional["_ReplicaState"], now: float) -> bool:
        """True when a sticky session's replica is out of rotation ONLY
        because its fresh beacon says `recovering`: the pin is HELD (not
        popped, not repointed) so the session resumes on its owner after
        the backoff window instead of migrating cold elsewhere (§20)."""
        return (
            state is not None
            and now - state.beacon_at <= self.beacon_ttl_s
            and now - state.failed_at >= self.fail_cooldown_s
            and bool(state.beacon.get("recovering"))
            and not state.beacon.get("quarantined")
            and not state.beacon.get("draining")
        )

    # -- routing ------------------------------------------------------------

    @staticmethod
    def _load(beacon: dict[str, Any]) -> float:
        return float(beacon.get("load_score", 0.0) or 0.0)

    def route(
        self,
        tokens,
        session_id: Optional[str] = None,
        exclude: Optional[set] = None,
        adapter: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> RouteDecision:
        """Pick the replica for one request. Raises FleetShedError when no
        replica is routable or every routable replica is saturated (full
        admission queue, or queue-wait EMA past ``shed_queue_wait_s``).
        ``adapter``: the request's LoRA adapter name — replicas advertising
        it resident score an ``adapter_affinity_tokens`` bonus alongside
        prefix affinity. ``tenant``: the request's tenant id — drives the
        tenant-aware shed (over-quota anywhere → 429, never balanced onto
        another replica) and the pressure-affinity term that keeps an
        aggressor's overflow off the replica serving the victim (§19)."""
        t0 = time.perf_counter()
        try:
            return self._route(
                list(tokens), session_id, exclude or set(), adapter, tenant
            )
        finally:
            # Histogram.record is single-writer by contract (the engine's
            # histograms have exactly one writer thread); route() runs on
            # many dispatch threads, so the router serializes its own
            # recording
            with self._hist_lock:
                self.dispatch_hist.record(time.perf_counter() - t0)

    def _route(
        self, tokens: list, session_id: Optional[str], exclude: set,
        adapter: Optional[str] = None, tenant: Optional[str] = None,
    ) -> RouteDecision:
        now = time.monotonic()
        with self._lock:
            # scale-to-zero demand clock (§23): EVERY route attempt is
            # demand, even one that sheds — the autoscaler must not scale
            # to zero under a backlog it happens to be rejecting
            self._last_demand_t = now
            live = [
                s
                for rid, s in self._replicas.items()
                if rid not in exclude and self._routable(s, now)
            ]
            if not live:
                self.shed_total += 1
                raise FleetShedError(
                    "no routable replica (all stale, draining, quarantined "
                    "or excluded)",
                    retry_after_s=max(self.refresh_interval_s, 0.5),
                )
            # tenant-aware shed (docs/SERVING.md §19): a tenant over its
            # token-rate quota on any routable replica is shed AT THE
            # ROUTER — its overflow must never be balanced onto the
            # replica serving a within-quota victim. Retry-After comes
            # from the tenant's own worst queue-wait EMA, not the fleet's.
            if tenant:
                pressured = [
                    s.tenants[tenant] for s in live if tenant in s.tenants
                ]
                if any(t.get("over_quota") for t in pressured):
                    self.shed_total += 1
                    self.tenant_shed_total += 1
                    raise FleetShedError(
                        f"tenant {tenant!r} is over its token-rate quota "
                        "fleet-wide",
                        retry_after_s=max(
                            (
                                float(t.get("queue_wait_ema_s", 0.0))
                                for t in pressured
                            ),
                            default=0.0,
                        ) or 1.0,
                    )
            # fleet-level shed: every routable replica says it cannot take
            # more — the replicas' OWN exported signals, not a blind bound
            saturated = [
                s
                for s in live
                if (
                    s.beacon.get("queue_depth", 0) > 0
                    and s.beacon.get("queued", 0)
                    >= s.beacon.get("queue_depth", 0)
                )
                or float(s.beacon.get("queue_wait_ema_s", 0.0))
                >= self.shed_queue_wait_s
            ]
            if len(saturated) == len(live):
                self.shed_total += 1
                retry = min(
                    max(float(s.beacon.get("queue_wait_ema_s", 0.0)), 0.1)
                    for s in live
                )
                raise FleetShedError(
                    f"all {len(live)} routable replicas saturated",
                    retry_after_s=retry,
                )
            if self.policy == "round-robin":
                state = live[self._rr % len(live)]
                self._rr += 1
                self.routed_balanced_total += 1
                return self._decide_locked(state, "balanced", 0, session_id, now)
            # sticky: same session stays on its replica while that replica
            # stays routable (its aliased pages are live there)
            pin_session = session_id
            if session_id:
                self._prune_sticky_locked(now)
                held = self._sticky.get(session_id)
                if held is not None:
                    rid, last_used = held
                    state = self._replicas.get(rid)
                    if (
                        now - last_used <= self.sticky_ttl_s
                        and state is not None
                        and state in live
                    ):
                        self.routed_sticky_total += 1
                        return self._decide_locked(state, "sticky", 0, session_id, now)
                    if (
                        now - last_used <= self.sticky_ttl_s
                        and self._recovering_hold(state, now)
                    ):
                        # the owner is merely RECOVERING (§20): serve this
                        # request elsewhere but HOLD the pin — no pop, no
                        # repoint — so the session lands back on its owner
                        # once its post-recovery beacon readmits it
                        self.sticky_held_total += 1
                        pin_session = None
                    else:
                        # replica gone or the session idled past its TTL
                        # (its pages are likely evicted by now): fall
                        # through — the session re-routes cold to whatever
                        # wins below
                        self._sticky.pop(session_id, None)
            if self.policy == "least-loaded":
                state = min(live, key=lambda s: self._load(s.beacon))
                self.routed_balanced_total += 1
                return self._decide_locked(state, "balanced", 0, pin_session, now)
            # affinity scoring: hash the prompt once per advertised length
            # (device-resident AND hibernated advertisements both probe)
            lengths = sorted(
                {
                    n
                    for s in live
                    for src in (s.digests, s.spilled_digests)
                    for n in src.values()
                    if n <= len(tokens) - 1
                }
            )
            probe = {n: prefix_digest(tokens[:n]) for n in lengths}
            scored: list[tuple[_ReplicaState, int, bool, int]] = []
            for s in live:
                match, spilled_match = 0, 0
                for n in lengths:
                    if s.digests.get(probe[n]) == n and n > match:
                        match = n
                    if (
                        s.spilled_digests.get(probe[n]) == n
                        and n > spilled_match
                    ):
                        spilled_match = n
                # a hibernated session's KV still lives on its owner — a
                # restore beats a cold re-prefill anywhere else, so the
                # spilled match competes, discounted (tiered KV, §16)
                effective = max(
                    match, int(spilled_match * self.spill_discount)
                )
                adapter_hit = bool(adapter) and adapter in s.adapters
                # the UNDISCOUNTED depth this replica can SERVE pages for
                # (resident or hibernated — a P2P fetch reads the host
                # arena either way, §21): the owner-selection signal
                raw = max(match, spilled_match)
                scored.append((s, effective, adapter_hit, raw))
            # role-aware candidate set (disaggregated serving, §18): with
            # BOTH roles routable, a prefill-heavy admission (estimated
            # prefill = prompt minus the best warm match anywhere) lands
            # on a prefill-tagged replica — the handoff route the caller
            # migrates away from once the first token lands — and
            # everything else keeps the decode/mixed pool, so one 32k
            # prompt never stalls the steady decode streams
            disagg = False
            kind_override = None
            candidates = scored
            prefill_pool = [t for t in scored if t[0].role == "prefill"]
            decode_pool = [
                t for t in scored if t[0].role in ("decode", "mixed")
            ]
            if prefill_pool and decode_pool:
                best_anywhere = max(m for _, m, _, _ in scored)
                est_prefill = len(tokens) - best_anywhere
                if est_prefill >= self.prefill_route_threshold:
                    candidates = prefill_pool
                    kind_override = "prefill"
                    disagg = self.migrate_enabled
                    self.routed_prefill_total += 1
                else:
                    candidates = decode_pool
            # no role split (prefill-only or decode/mixed-only fleets):
            # candidates stays the full scored set
            best, best_score, best_match = None, None, 0
            best_raw = 0
            best_adapter_hit = False
            best_tenant_hit = False
            for s, effective, adapter_hit, raw in candidates:
                # tenant pressure affinity (§19): a tenant with queued
                # work on a replica scores a bonus THERE — the burster's
                # overflow concentrates where its backlog (and its sheds)
                # already live instead of spilling onto the replica
                # serving a quiet victim. A replica deep into brownout is
                # penalized one backlog-unit per ladder level.
                tenant_hit = bool(
                    tenant
                    and int(
                        s.tenants.get(tenant, {}).get("queued", 0)
                    ) > 0
                )
                score = (
                    effective
                    + (self.adapter_affinity_tokens if adapter_hit else 0.0)
                    + (self.tenant_affinity_tokens if tenant_hit else 0.0)
                    - self.lam * self._load(s.beacon)
                    - self.brownout_penalty_tokens * s.brownout_level
                )
                if best_score is None or score > best_score:
                    best, best_score, best_match = s, score, effective
                    best_raw = raw
                    best_adapter_hit = adapter_hit
                    best_tenant_hit = tenant_hit
            assert best is not None
            if best_adapter_hit:
                self.routed_adapter_total += 1
            if best_tenant_hit:
                self.routed_tenant_affinity_total += 1
            if kind_override is not None:
                kind = kind_override
            elif best_match > 0 or best_adapter_hit:
                self.routed_affinity_total += 1
                kind = "affinity"
            else:
                # nobody holds a usable prefix: least-loaded fallback (the
                # scored argmax already IS least-loaded when match==0 for
                # everyone, since score reduces to −λ·load)
                self.routed_balanced_total += 1
                kind = "balanced"
            # P2P page fetch hint (§21, ROADMAP 2a): the chosen replica's
            # trie misses (or matches shallow) while another LIVE peer
            # advertises the prefix ≥ p2p_threshold tokens deeper — pull
            # the pages from that owner over the migration wire before
            # dispatch and admit warm instead of re-prefilling. Both the
            # owner (serves /fleet/pages) and the destination (binds and,
            # when remote, runs the fetch) must advertise "p2p"; the
            # disaggregated prefill handoff keeps its own migration path.
            p2p_source, p2p_match = None, 0
            if (
                self.p2p_enabled
                and kind_override is None
                and "p2p" in best.caps
            ):
                owner, owner_raw = None, 0
                for s, _, _, raw in scored:
                    if s is best or "p2p" not in s.caps:
                        continue
                    if raw > owner_raw:
                        owner, owner_raw = s, raw
                if owner is not None and self._p2p_worth_it_locked(
                    best, owner, best_raw, owner_raw
                ):
                    p2p_source = owner.handle.replica_id
                    p2p_match = owner_raw
            return self._decide_locked(
                best, kind, best_match, pin_session, now, disagg=disagg,
                p2p_source=p2p_source, p2p_match=p2p_match,
            )

    def _p2p_worth_it_locked(
        self,
        best: _ReplicaState,
        owner: _ReplicaState,
        best_raw: int,
        owner_raw: int,
    ) -> bool:
        """Should the router pull ``owner``'s advertised prefix into
        ``best`` before dispatch? The fetch-vs-prefill cost model (§23):
        estimated wire seconds (pages moved at the observed fetch
        bandwidth) against estimated prefill seconds (the token gap at
        the destination's measured landed throughput). Falls back to the
        flat ``p2p_threshold`` when any estimate input is missing —
        legacy beacons without geometry/tps, or a router that has not
        landed a fetch yet. ``p2p_min_gap`` floors BOTH modes: a
        few-page gap never justifies a wire round-trip, whatever the
        arithmetic says (and it keeps the model from thrashing on
        near-tie advertisements). Caller holds ``_lock``."""
        gap = owner_raw - best_raw
        if gap < self.p2p_min_gap:
            return False
        tps = float(best.beacon.get("prefill_tps", 0.0) or 0.0)
        bw = self._p2p_bw_ema
        bpp = int(owner.beacon.get("bytes_per_page", 0) or 0)
        page = int(owner.beacon.get("page_size", 0) or 0)
        if tps > 0.0 and bw > 0.0 and bpp > 0 and page > 0:
            # the fetch moves the WHOLE advertised prefix (bind needs a
            # boundary-aligned entry), while prefilling only pays the gap
            # the fetch would have saved
            est_fetch_s = math.ceil(owner_raw / page) * bpp / bw
            est_prefill_s = gap / tps
            if est_fetch_s < est_prefill_s:
                self.p2p_cost_routed_total += 1
                return True
            return False
        return gap >= self.p2p_threshold

    def _decide_locked(
        self,
        state: _ReplicaState,
        kind: str,
        match: int,
        session_id: Optional[str],
        now: float,
        disagg: bool = False,
        p2p_source: Optional[str] = None,
        p2p_match: int = 0,
    ) -> RouteDecision:
        rid = state.handle.replica_id
        if session_id:
            self._sticky[session_id] = (rid, now)
        return RouteDecision(
            replica_id=rid,
            handle=state.handle,
            kind=kind,
            expected_match=match,
            score=match - self.lam * self._load(state.beacon),
            disagg=disagg,
            p2p_source=p2p_source,
            p2p_match=p2p_match,
        )

    def _prune_sticky_locked(self, now: float) -> None:
        if len(self._sticky) < 4096:
            return
        self._sticky = {
            k: v
            for k, v in self._sticky.items()
            if now - v[1] <= self.sticky_ttl_s
        }

    # -- dispatch with failover ----------------------------------------------

    @staticmethod
    def _oneshot_frames(
        handle: Any, prompt: list, opts: dict, timeout_s: float,
    ) -> Iterator[dict]:
        """Frame adapter for transports without ``generate_stream`` (test
        fakes, older peers): ONE blocking dispatch wrapped into the frame
        shapes. The blocking call runs EAGERLY so its shed/failure raises
        inside the caller's dispatch try-block."""
        return result_frames(
            handle.generate(prompt, opts, timeout_s), prompt_len=len(prompt)
        )

    # -- disaggregated handoff (docs/SERVING.md §18) --------------------------

    def _pick_decode_target(
        self, exclude: set, require_caps: tuple = (),
    ) -> Optional[RouteDecision]:
        """The decode replica a just-prefilled stream migrates to:
        least-loaded among decode-tagged routable replicas (mixed as the
        fallback pool) that advertise every capability in
        ``require_caps``. Prefix affinity is irrelevant here — the pages
        travel WITH the stream. Returns None when no survivor can decode
        (the caller decodes in place)."""
        now = time.monotonic()
        with self._lock:
            live = [
                s for rid, s in self._replicas.items()
                if rid not in exclude and self._routable(s, now)
                and all(c in s.caps for c in require_caps)
            ]
            pool = [s for s in live if s.role == "decode"] or [
                s for s in live if s.role == "mixed"
            ]
            if not pool:
                return None
            best = min(pool, key=lambda s: self._load(s.beacon))
            return self._decide_locked(best, "migrated", 0, None, now)

    def _handoff_target(
        self,
        decision: RouteDecision,
        tokens: list,
        delivered: list,
        parsed: Any,
        last_dfa_state: Optional[int],
        session_id: Optional[str],
        exclude: set,
    ) -> RouteDecision:
        """Prefill phase complete: migrate the stream's KV to a decode
        replica and return the decision the resume hop MUST use. Every
        failure path returns the PREFILL replica itself — decode-in-place,
        the fallback that is always correct (the pages are there, the
        resume is warm) — and counts/dumps the fallback."""
        prompt = tokens + delivered
        # the target must UNDERSTAND the transfer ("kvmig" — a legacy peer
        # would 404/garble the bind) and, for a constrained stream, the
        # carried DFA state ("dfa-resume" — a peer that silently dropped
        # it would restart the grammar at 0: invalid output)
        need = ("kvmig", "dfa-resume") if parsed.response_format else ("kvmig",)
        target = self._pick_decode_target(
            exclude | {decision.replica_id}, require_caps=need,
        )
        reason = None
        if target is None:
            reason = "no decode-capable replica routable"
        elif parsed.response_format and last_dfa_state is None:
            # the prefill hop's frames carried no DFA state (legacy peer):
            # migrating would strand a derivation the decode replica
            # cannot legally continue — decode where the grammar state is
            reason = "constrained stream carried no DFA state"
        if reason is None:
            state = {"sampling": {
                "temperature": parsed.temperature,
                "top-k": parsed.top_k, "top-p": parsed.top_p,
                "seed": parsed.seed,
            }}
            if parsed.response_format and last_dfa_state is not None:
                state["grammar_key"] = json.dumps(
                    parsed.response_format, sort_keys=True,
                    separators=(",", ":"),
                )
                state["dfa_state"] = int(last_dfa_state)
            ack = self._migrate(decision, target, prompt, state)
            if ack is not None:
                if session_id:
                    # sticky repoint (§18): the session's KV now LIVES on
                    # the decode replica — the next turn must route there,
                    # not back to the prefill replica for a pointless
                    # second migration
                    with self._lock:
                        self._sticky[session_id] = (
                            target.replica_id, time.monotonic()
                        )
                return target
            reason = "migration failed"
        else:
            with self._lock:
                self.migrate_fallbacks_total += 1
            self._flight.dump(
                "migrate-failed",
                counters={
                    "migrate_fallbacks_total": self.migrate_fallbacks_total,
                    "delivered": len(delivered),
                },
                extra={
                    "error": reason, "src": decision.replica_id,
                    "fallback": "decode-in-place",
                },
                force=True,
            )
        log.warning(
            "disagg handoff falling back to decode-in-place on %s: %s",
            decision.replica_id, reason,
        )
        # decode-in-place: same replica, full remaining budget, no disagg
        return RouteDecision(
            replica_id=decision.replica_id, handle=decision.handle,
            kind="prefill", expected_match=len(prompt), score=decision.score,
            disagg=False,
        )

    def _has_cap(self, replica_id: str, cap: str) -> bool:
        with self._lock:
            state = self._replicas.get(replica_id)
            return state is not None and cap in state.caps

    def _migrate(
        self, src: RouteDecision, dst: RouteDecision, prompt: list,
        state: dict,
    ) -> Optional[dict]:
        """Run one KV-page migration src → dst (§18). Returns the
        receiver's ACK, or None after counting + dumping the failure —
        the sender retains its pages on every failure path, so the caller
        can always decode in place."""
        t0 = time.perf_counter()
        phases: dict[str, Any] = {}
        try:
            # wire negotiation (§21): push the binary codec only toward a
            # receiver that advertises it — everything else stays v1
            # NDJSON, byte-identical to the pre-v2 wire
            wire = (
                "v2" if self._has_cap(dst.replica_id, "kvmig2") else "v1"
            )
            if getattr(src.handle, "is_local", False):
                from langstream_tpu.serving import migrate as migrate_mod

                if getattr(dst.handle, "is_local", False):
                    frames = migrate_mod.export_frames(
                        src.handle.engine, prompt,
                        timeout_s=self.migrate_timeout_s,
                        state=state, phases=phases,
                    )
                    ack = migrate_mod.bind_frames(
                        dst.handle.engine, frames,
                        timeout_s=self.migrate_timeout_s,
                    )
                else:
                    frames = migrate_mod.export_frames(
                        src.handle.engine, prompt,
                        timeout_s=self.migrate_timeout_s,
                        state=state, phases=phases,
                        raw=wire == "v2",
                    )
                    t1 = time.perf_counter()
                    ack = migrate_mod.push_migration(
                        str(getattr(dst.handle, "url", "")), frames,
                        self.migrate_timeout_s, wire=wire,
                    )
                    phases["transfer_ms"] = round(
                        (time.perf_counter() - t1) * 1e3, 3
                    )
                migrate_mod._release_on_ack(  # noqa: SLF001
                    src.handle.engine, prompt, ack
                )
            else:
                migrate_out = getattr(src.handle, "migrate_out", None)
                dst_url = str(getattr(dst.handle, "url", "") or "")
                if migrate_out is None or not dst_url.startswith("http"):
                    raise RuntimeError(
                        "source replica cannot push a migration to this "
                        "destination (no migrate-out transport / non-HTTP "
                        "receiver)"
                    )
                if wire == "v2":
                    try:
                        ack = migrate_out(
                            prompt, dst_url, state,
                            self.migrate_timeout_s, wire="v2",
                        )
                    except TypeError:
                        # a pre-v2 source handle: its NDJSON push is
                        # still valid toward a v2 receiver
                        ack = migrate_out(
                            prompt, dst_url, state, self.migrate_timeout_s
                        )
                else:
                    ack = migrate_out(
                        prompt, dst_url, state, self.migrate_timeout_s
                    )
                phases.update(ack.get("phases") or {})
            took = time.perf_counter() - t0
            with self._hist_lock:
                self.migrate_hist.record(took)
            with self._lock:
                self.migrations_total += 1
                self.migrate_pages_total += int(ack.get("pages", 0))
                self.migrate_bytes_total += int(ack.get("bytes", 0))
            log.info(
                "migrated %s pages (%s bytes) %s → %s in %.1f ms",
                ack.get("pages"), ack.get("bytes"),
                src.replica_id, dst.replica_id, took * 1e3,
            )
            return ack
        except Exception as e:  # noqa: BLE001 — every failure falls back
            took = time.perf_counter() - t0
            with self._hist_lock:
                # failed migrations land in the histogram too — the panel
                # must move during incidents
                self.migrate_hist.record(took)
            with self._lock:
                self.migrate_fallbacks_total += 1
                fallbacks = self.migrate_fallbacks_total
            self._flight.dump(
                "migrate-failed",
                counters={"migrate_fallbacks_total": fallbacks},
                extra={
                    "error": str(e), "src": src.replica_id,
                    "dst": dst.replica_id,
                    "phases": phases,
                    "total_ms": round(took * 1e3, 3),
                    "fallback": "decode-in-place",
                },
                force=True,
            )
            log.warning(
                "KV migration %s → %s failed after %.1f ms (%s); sender "
                "retains, stream decodes in place",
                src.replica_id, dst.replica_id, took * 1e3, e,
            )
            return None

    def _p2p_fetch(self, decision: RouteDecision, prompt: list) -> bool:
        """Pull the pages backing ``prompt``'s prefix from the owning
        peer (``decision.p2p_source``) into the routed replica BEFORE
        dispatch (§21, ROADMAP 2a) — the owner keeps its copy (a fetch
        copies, a migration moves) and the routed replica admits warm
        instead of re-prefilling. Returns True when the prefix bound;
        EVERY failure — checksum mismatch, net-cut, deadline, owner gone,
        no transport — counts one fallback, dumps a flight record and
        returns False: the request then prefills cold exactly as if no
        owner existed (same §17 ladder shape as a failed migration)."""
        from langstream_tpu.serving import migrate as migrate_mod

        src_id = str(decision.p2p_source)
        with self._lock:
            src_state = self._replicas.get(src_id)
        t0 = time.perf_counter()
        try:
            if src_state is None:
                raise migrate_mod.MigrationError(
                    f"p2p owner {src_id} is not a fleet member"
                )
            src = src_state.handle
            # codec negotiation rides the OWNER's caps here — it is the
            # sender of the page bytes
            wire = "v2" if "kvmig2" in src_state.caps else "v1"
            timeout_s = self.migrate_timeout_s
            if getattr(decision.handle, "is_local", False):
                if getattr(src, "is_local", False):
                    frames = migrate_mod.export_frames(
                        src.engine, prompt, timeout_s=timeout_s,
                    )
                else:
                    src_url = str(getattr(src, "url", "") or "")
                    if not src_url.startswith("http"):
                        raise migrate_mod.MigrationError(
                            f"p2p owner {src_id} has no page-fetch "
                            "transport"
                        )
                    frames = migrate_mod.fetch_pages(
                        src_url, prompt, timeout_s, wire=wire
                    )
                ack = migrate_mod.bind_frames(
                    decision.handle.engine, frames, timeout_s=timeout_s
                )
            else:
                fetch = getattr(decision.handle, "p2p_fetch", None)
                src_url = str(getattr(src, "url", "") or "")
                if fetch is None or not src_url.startswith("http"):
                    raise migrate_mod.MigrationError(
                        "routed replica cannot run a p2p fetch "
                        "(no transport)"
                    )
                ack = fetch(prompt, src_url, timeout_s, wire=wire)
            elapsed = time.perf_counter() - t0
            with self._lock:
                self.p2p_fetch_total += 1
                self.p2p_bytes_in_total += int(ack.get("bytes", 0))
                # feed the cost model's bandwidth EMA from LANDED fetches
                # only (a failed fetch says nothing about wire speed);
                # idempotent re-binds ack 0 bytes and are skipped
                if int(ack.get("bytes", 0)) > 0 and elapsed > 0:
                    obs_bw = int(ack["bytes"]) / elapsed
                    self._p2p_bw_ema = (
                        obs_bw
                        if self._p2p_bw_ema <= 0.0
                        else 0.8 * self._p2p_bw_ema + 0.2 * obs_bw
                    )
            log.info(
                "p2p fetched %s pages (%s bytes) %s → %s in %.1f ms",
                ack.get("pages"), ack.get("bytes"), src_id,
                decision.replica_id, (time.perf_counter() - t0) * 1e3,
            )
            return True
        except Exception as e:  # noqa: BLE001 — every failure falls back
            with self._lock:
                self.p2p_fetch_fallback_total += 1
                fallbacks = self.p2p_fetch_fallback_total
            self._flight.dump(
                "p2p-fetch-failed",
                counters={"p2p_fetch_fallback_total": fallbacks},
                extra={
                    "error": str(e), "src": src_id,
                    "dst": decision.replica_id,
                    "match": int(decision.p2p_match),
                    "total_ms": round((time.perf_counter() - t0) * 1e3, 3),
                    "fallback": "local-cold-prefill",
                },
                force=True,
            )
            log.warning(
                "p2p page fetch %s → %s failed (%s); prefilling cold",
                src_id, decision.replica_id, e,
            )
            return False

    def prefetch(
        self,
        tokens,
        session_id: Optional[str] = None,
        adapter: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> dict:
        """Prefetch-on-hint (§23): a beacon hint — 'this session's next
        turn is coming' — warms the pages BEFORE the request routes.
        Runs the exact route() the request will run (so the sticky pin
        and the eventual dispatch agree on the replica), then fires the
        P2P/durable page fetch immediately instead of on the dispatch
        path; by the time the real request arrives, its prefix admits
        warm. Best-effort end to end: a shed, a hint nobody can improve
        on, or a failed fetch all return ``prefetched: False`` and cost
        the caller nothing — the request path is unchanged either way."""
        with self._lock:
            self.prefetch_total += 1
        try:
            decision = self.route(
                tokens, session_id=session_id, adapter=adapter,
                tenant=tenant,
            )
        except FleetShedError as e:
            return {"prefetched": False, "reason": str(e)}
        if not decision.p2p_source:
            return {
                "prefetched": False,
                "replica": decision.replica_id,
                "match": int(decision.expected_match),
                "reason": "no-deeper-owner",
            }
        ok = self._p2p_fetch(decision, list(tokens))
        if ok:
            with self._lock:
                self.prefetch_fetch_total += 1
        return {
            "prefetched": ok,
            "replica": decision.replica_id,
            "source": decision.p2p_source,
            "match": int(decision.p2p_match if ok else decision.expected_match),
        }

    def stream_generate(
        self,
        tokens,
        options: Optional[dict] = None,
        session_id: Optional[str] = None,
        timeout_s: Optional[float] = None,
    ) -> Iterator[dict]:
        """Route + STREAM one request with mid-stream warm failover
        (docs/SERVING.md §17). Yields router-sequenced frames (one
        contiguous ``seq`` across failovers — the client-facing
        no-dup/no-drop/no-reorder guarantee):

          route    before every hop: replica_id / url / local flag /
                   tokens-resumed count, plus the RouteDecision object
                   (in-process consumers only; never serialized)
          tokens   token chunks, piped through from the serving replica
          heartbeat  forwarded transport liveness (consumers may ignore
                   them; forwarding keeps this generator closeable
                   between tokens)
          end      exactly once on success: finish_reason, usage against
                   the ORIGINAL prompt, router-observed ttft_s/total_s,
                   the serving replica and the failover count

        A replica dying mid-stream (ReplicaError) is quarantined and the
        request re-dispatches to a survivor with ``prompt + delivered
        tokens`` as the new prompt — prefix reuse (and the host tier's
        spilled prefixes) makes the resume warm, and greedy resumed
        streams are token-exact vs an uninterrupted run. Each failover
        dumps a ``fleet-failover`` flight record carrying the hop's frame
        trace. Sheds exclude-and-retry as before; a bad request
        (ValueError) propagates untouched."""
        from langstream_tpu.models.configs import GenerationOptions

        tokens = list(tokens)
        options = dict(options or {})
        # the canonical parse — NOT a re-implementation of the key chains
        # and defaults, which would silently diverge from what the
        # serving engine actually enforces
        parsed = GenerationOptions.from_dict(options)
        budget = int(parsed.max_new_tokens)
        total_s = (
            float(timeout_s) if timeout_s is not None
            else hop_timeout_s(options)
        )
        started = time.monotonic()
        first_token_at: Optional[float] = None
        delivered: list[int] = []
        out_seq = 0
        excluded: set = set()
        last_shed: Optional[FleetShedError] = None
        trace: deque = deque(maxlen=64)
        failovers = 0
        # set on a mid-stream death; counted + dumped only once route()
        # actually finds a survivor — a terminal failure is not a
        # "failover" (the metric means RESUMED, §17)
        pending_failover: Optional[dict] = None
        adapter = str(options.get("adapter") or "") or None
        tenant = getattr(parsed, "tenant", None)
        # disaggregated handoff state (§18): ``forced`` short-circuits
        # route() for the hop that must land on a SPECIFIC replica (the
        # decode target the KV just migrated to, or the prefill replica
        # decoding in place after a failed migration); ``last_dfa_state``
        # is the constrained stream's host-mirrored grammar state as
        # carried by the tokens frames — what makes a mid-derivation
        # resume legal instead of refused
        forced: Optional[RouteDecision] = None
        last_dfa_state: Optional[int] = None
        # attempt budget: one per replica, EXTENDED by one whenever a
        # prefill handoff consumes a turn (its hop ends in a migration,
        # not a failure) — a full fleet's worth of failovers still fits,
        # and the all-replicas-died exit below keeps raising ReplicaError
        # rather than letting an extra route() read as a shed
        attempts, max_attempts = 0, self.replica_count
        while attempts < max_attempts:
            attempts += 1
            prompt = tokens + delivered
            opts = dict(options)
            if delivered:
                # the resumed stream finishes the ORIGINAL budget: tokens
                # already delivered never re-generate (and never re-bill)
                opts["max-tokens"] = max(1, budget - len(delivered))
                if parsed.response_format and last_dfa_state is not None:
                    # resume the derivation FROM the carried state — the
                    # survivor's DFA must not restart at 0 (§18)
                    opts["grammar-resume-state"] = int(last_dfa_state)
            if forced is not None:
                decision, forced = forced, None
            else:
                try:
                    decision = self.route(
                        prompt, session_id=session_id, exclude=excluded,
                        adapter=adapter, tenant=tenant,
                    )
                except FleetShedError as e:
                    if delivered:
                        raise ReplicaError(
                            f"stream lost its replica after "
                            f"{len(delivered)} token(s) and no survivor "
                            f"is routable: {e}"
                        ) from e
                    raise
                if (
                    "grammar-resume-state" in opts
                    and not self._has_cap(decision.replica_id, "dfa-resume")
                ):
                    # a legacy survivor would silently DROP the resume
                    # state and restart the DFA at 0 — invalid output
                    # dressed as valid. Exclude it; another survivor may
                    # honor the state, and none at all is a loud failure
                    # (the all-attempts exit below).
                    excluded.add(decision.replica_id)
                    continue
            # P2P page fetch (§21): the route says another live peer owns
            # this prompt's prefix ≥ p2p_threshold tokens deeper than the
            # chosen replica — pull the pages over the migration wire
            # BEFORE dispatch so the prefill below starts warm. First hop
            # only (a resume's prefix already lives where it streamed),
            # and strictly best-effort: a failed fetch costs one counter
            # bump + flight dump inside _p2p_fetch, then this same hop
            # prefills cold.
            if decision.p2p_source and not delivered:
                self._p2p_fetch(decision, prompt)
            # prefill handoff (§18): run prefill + the FIRST token on the
            # prefill-tagged replica (TTFT comes from there), then migrate
            # the KV pages to a decode replica and finish the stream where
            # the steady decode pool lives
            handoff = (
                decision.disagg
                and budget - len(delivered) > 1
                and self.migrate_enabled
            )
            if handoff:
                opts["max-tokens"] = 1
            if pending_failover is not None:
                # the resume has a survivor: NOW it is a warm failover
                failovers += 1
                with self._lock:
                    self.stream_failover_total += 1
                    stream_failovers = self.stream_failover_total
                self._flight.dump(
                    "fleet-failover",
                    counters={
                        "delivered": pending_failover["delivered"],
                        "stream_failovers_total": stream_failovers,
                        "failover_total": self.failover_total,
                    },
                    extra={
                        **pending_failover,
                        "resumed_on": decision.replica_id,
                    },
                    force=True,  # every mid-stream resume is an incident
                )
                pending_failover = None
            yield {
                "v": FRAME_SCHEMA, "seq": out_seq, "kind": "route",
                "replica": decision.replica_id,
                "url": str(getattr(decision.handle, "url", "") or ""),
                "local": bool(getattr(decision.handle, "is_local", False)),
                "resumed": len(delivered),
                "disagg": bool(decision.disagg),
                "decision": decision,
            }
            out_seq += 1
            remaining = total_s - (time.monotonic() - started)
            if remaining <= 0:
                raise ReplicaError(
                    f"hop budget ({total_s:.1f}s) exhausted after "
                    f"{len(delivered)} token(s)"
                )
            stream_fn = getattr(decision.handle, "generate_stream", None)
            hop_t0 = time.perf_counter()
            handed_off = False
            try:
                frames = (
                    stream_fn(prompt, opts, timeout_s=remaining)
                    if stream_fn is not None
                    else self._oneshot_frames(
                        decision.handle, prompt, opts, remaining
                    )
                )
                for frame in frames:
                    kind = frame.get("kind")
                    trace.append({
                        "seq": frame.get("seq"), "kind": kind,
                        "n": (
                            len(frame.get("tokens") or [])
                            if kind == "tokens" else 0
                        ),
                        "t": round(time.monotonic() - started, 4),
                        "replica": decision.replica_id,
                    })
                    if kind == "tokens":
                        try:
                            toks = [
                                int(t) for t in frame.get("tokens") or []
                            ]
                        except (TypeError, ValueError) as bad:
                            # frame CONTENT from the replica, not the
                            # caller's request: this must read as a dead
                            # hop (failover), never as a bad request
                            raise ReplicaError(
                                f"replica {decision.replica_id}: corrupt "
                                f"tokens frame ({bad})"
                            ) from bad
                        if not toks:
                            continue
                        if first_token_at is None:
                            first_token_at = time.monotonic()
                        delivered.extend(toks)
                        if frame.get("dfa_state") is not None:
                            try:
                                last_dfa_state = int(frame["dfa_state"])
                            except (TypeError, ValueError):
                                last_dfa_state = None
                        yield {
                            "seq": out_seq, "kind": "tokens",
                            "tokens": toks, "replica": decision.replica_id,
                        }
                        out_seq += 1
                    elif kind == "end":
                        with self._hist_lock:
                            self.hop_hist.record(
                                time.perf_counter() - hop_t0
                            )
                        if (
                            handoff
                            and str(frame.get("finish_reason")) == "length"
                            and len(delivered) < budget
                        ):
                            # prefill phase done (our 1-token clamp, not a
                            # real completion): migrate, then resume on
                            # the decode target — or decode in place when
                            # anything about the transfer fails
                            forced = self._handoff_target(
                                decision, tokens, delivered, parsed,
                                last_dfa_state, session_id, excluded,
                            )
                            close = getattr(frames, "close", None)
                            if close is not None:
                                close()
                            handed_off = True
                            max_attempts += 1  # this turn was no failure
                            break
                        now = time.monotonic()
                        yield {
                            "seq": out_seq, "kind": "end",
                            "finish_reason": str(
                                frame.get("finish_reason", "stop")
                            ),
                            "prompt_tokens": len(tokens),
                            "completion_tokens": len(delivered),
                            "ttft_s": round(
                                (first_token_at or now) - started, 6
                            ),
                            "total_s": round(now - started, 6),
                            "engine_ttft_s": float(frame.get("ttft_s", 0.0)),
                            "failovers": failovers,
                            "replica": decision.replica_id,
                        }
                        return
                    elif kind == "heartbeat":
                        # forward (re-sequenced): the consumer may ignore
                        # them, but YIELDING here parks this generator at
                        # a resumable point between tokens — an abandoned
                        # stream's close() lands at the next heartbeat
                        # instead of waiting out an inter-token gap
                        yield {
                            "seq": out_seq, "kind": "heartbeat",
                            "replica": decision.replica_id,
                        }
                        out_seq += 1
                if handed_off:
                    continue
                raise ReplicaError(
                    f"replica {decision.replica_id}: stream ended without "
                    "terminal frame"
                )
            except GeneratorExit:
                # the CONSUMER abandoned this stream (disconnect, local
                # shortcut): close the hop so the serving replica cancels
                # its in-flight request instead of decoding to the budget
                close = getattr(frames, "close", None)
                if close is not None:
                    close()
                raise
            except FleetShedError as e:
                last_shed = e
                excluded.add(decision.replica_id)
                continue
            except ValueError:
                raise  # the REQUEST is bad — never retried across the fleet
            except ReplicaError as e:
                log.warning(
                    "replica %s failed mid-dispatch (%s); failing over "
                    "(%d token(s) delivered)",
                    decision.replica_id, e, len(delivered),
                )
                # failed/wedged hops land in the histogram too — an
                # incident is exactly when the hop-latency panel must move
                with self._hist_lock:
                    self.hop_hist.record(time.perf_counter() - hop_t0)
                self.note_failover(decision.replica_id)
                excluded.add(decision.replica_id)
                if (
                    delivered and parsed.response_format
                    and last_dfa_state is None
                ):
                    # a grammar-constrained stream whose frames carried NO
                    # DFA state (legacy peer / one-shot adapter) cannot
                    # resume mid-derivation: the survivor's DFA would
                    # restart at state 0 and append a SECOND derivation
                    # after the partial one — invalid output dressed as
                    # valid. With the state on the wire (tokens frames,
                    # §18) the resume continues the derivation instead.
                    raise ReplicaError(
                        f"constrained stream lost its replica after "
                        f"{len(delivered)} token(s) and its frames carried "
                        "no DFA state; mid-derivation resume would break "
                        "the grammar guarantee"
                    ) from e
                if delivered and len(delivered) >= budget:
                    # the replica died BETWEEN its final tokens frame and
                    # the terminal frame: the budget is fully delivered —
                    # synthesize the end instead of re-dispatching for
                    # tokens an uninterrupted run would never generate
                    now = time.monotonic()
                    yield {
                        "seq": out_seq, "kind": "end",
                        "finish_reason": "length",
                        "prompt_tokens": len(tokens),
                        "completion_tokens": len(delivered),
                        "ttft_s": round((first_token_at or now) - started, 6),
                        "total_s": round(now - started, 6),
                        "engine_ttft_s": 0.0,
                        "failovers": failovers,
                        "replica": decision.replica_id,
                    }
                    return
                if delivered:
                    pending_failover = {
                        "victim": decision.replica_id,
                        "delivered": len(delivered),
                        "resumed_prompt_len": len(tokens) + len(delivered),
                        "error": str(e),
                        "frames": list(trace),
                    }
                continue
        if last_shed is not None and not delivered:
            with self._lock:
                self.shed_total += 1
            raise last_shed
        # nobody shed — every attempt DIED. ReplicaError (not a shed) so
        # callers can tell "fleet is saturated, back off" from "fleet is
        # broken, serve locally if you can" (the completions fallback)
        raise ReplicaError(
            f"every replica failed this stream "
            f"({len(delivered)} token(s) delivered)"
        )

    def generate(
        self,
        tokens,
        options: Optional[dict] = None,
        session_id: Optional[str] = None,
        timeout_s: Optional[float] = None,
    ) -> tuple[dict[str, Any], RouteDecision]:
        """Blocking route + dispatch: drain ``stream_generate`` (same
        failover semantics, now WARM mid-stream instead of restart-cold)
        into the one-shot result shape. The decision returned is the
        replica that actually FINISHED the stream. ``timeout_s`` defaults
        to None so the deadline-derived hop budget applies here too —
        a non-None default would quietly reinstate the flat 600s."""
        delivered: list[int] = []
        decision: Optional[RouteDecision] = None
        end: Optional[dict] = None
        for frame in self.stream_generate(
            tokens, options, session_id=session_id, timeout_s=timeout_s
        ):
            kind = frame.get("kind")
            if kind == "route":
                decision = frame["decision"]
            elif kind == "tokens":
                delivered.extend(frame["tokens"])
            elif kind == "end":
                end = frame
        assert end is not None and decision is not None
        out = {
            "tokens": delivered,
            "finish_reason": end["finish_reason"],
            "prompt_tokens": end["prompt_tokens"],
            "ttft_s": end["ttft_s"],
            "total_s": end["total_s"],
        }
        return out, decision

    # -- autoscale hint -------------------------------------------------------

    def desired_replicas(
        self,
        target_queue_wait_s: float = 0.5,
        min_replicas: int = 1,
        max_replicas: int = 64,
    ) -> int:
        """The k8s planner's scale hint, from the fleet-wide queue-wait EMA:
        scale OUT proportionally when the mean routable queue wait exceeds
        the target (capped at 4× per step so one burst can't quadruple the
        fleet), scale IN one replica at a time only when queues are empty
        AND occupancy is low (conservative — killing a warm replica throws
        away its aliased pages). With no routable beacon the hint holds the
        current size: never scale on missing data.

        ``min_replicas=0`` legalizes scale-to-zero (§23), gated three
        ways: demand has been quiet for 60× the target window (the next
        route() stamp resurrects the fleet), every queue is empty with
        zero occupancy, and EVERY routable replica advertises the
        ``durable`` cap — the drain hibernates its sessions to disk, so
        going dark loses nothing. One non-durable replica in the fleet
        vetoes zero: its sessions would die with it."""
        now = time.monotonic()
        with self._lock:
            total = len(self._replicas)
            routable = [
                s for s in self._replicas.values() if self._routable(s, now)
            ]
            live = [s.beacon for s in routable]
            caps = [s.caps for s in routable]
            quiet_s = now - self._last_demand_t
        if not live:
            return max(min_replicas, min(total, max_replicas))
        n = len(live)
        ema = sum(float(b.get("queue_wait_ema_s", 0.0)) for b in live) / n
        occ = sum(
            float(b.get("active_slots", 0)) / max(1, b.get("max_batch", 1))
            for b in live
        ) / n
        busy = sum(
            int(b.get("active_slots", 0) or 0) + int(b.get("queued", 0) or 0)
            for b in live
        )
        if ema > target_queue_wait_s:
            want = math.ceil(n * min(ema / target_queue_wait_s, 4.0))
        elif ema < 0.1 * target_queue_wait_s and occ < 0.5 and n > 1:
            want = n - 1
        else:
            want = n
        if (
            min_replicas == 0
            and want <= 1
            and busy == 0
            and quiet_s > 60.0 * max(target_queue_wait_s, 0.1)
            and all("durable" in c for c in caps)
        ):
            want = 0
        return max(min_replicas, min(want, max_replicas))

    def desired_replicas_by_role(
        self,
        target_queue_wait_s: float = 0.5,
        min_replicas: int = 1,
        max_replicas: int = 64,
    ) -> dict[str, int]:
        """Role-split autoscale hint for disaggregated fleets (§18): the
        PREFILL pool scales on its own queue-wait EMA (prefill-heavy
        admissions queue there — wait is the burst-absorption signal),
        the DECODE pool on occupancy/load-score (decode replicas run a
        high-occupancy steady state by design; queue wait stays near zero
        until they are genuinely full). Pools scale independently with
        the same out-cap/in-conservatism as ``desired_replicas``; a role
        with no routable beacon holds its current count. Empty dict when
        the fleet advertises no roles (homogeneous fleets keep the scalar
        hint)."""
        now = time.monotonic()
        with self._lock:
            by_role: dict[str, list] = {}
            totals: dict[str, int] = {}
            for s in self._replicas.values():
                role = s.role
                totals[role] = totals.get(role, 0) + 1
                if self._routable(s, now):
                    by_role.setdefault(role, []).append(s.beacon)
        if set(totals) <= {"mixed"}:
            return {}
        out: dict[str, int] = {}
        for role, total in sorted(totals.items()):
            live = by_role.get(role) or []
            if not live:
                out[role] = max(min_replicas, min(total, max_replicas))
                continue
            n = len(live)
            ema = sum(
                float(b.get("queue_wait_ema_s", 0.0)) for b in live
            ) / n
            occ = sum(
                float(b.get("active_slots", 0))
                / max(1, b.get("max_batch", 1))
                for b in live
            ) / n
            load = sum(float(b.get("load_score", 0.0)) for b in live) / n
            if role == "prefill":
                if ema > target_queue_wait_s:
                    want = math.ceil(
                        n * min(ema / target_queue_wait_s, 4.0)
                    )
                elif ema < 0.1 * target_queue_wait_s and n > 1:
                    want = n - 1
                else:
                    want = n
            else:
                # decode/mixed: occupancy-first — a pool running hot
                # (≥85% slots or load past ~2, i.e. saturated occupancy +
                # page pressure) grows; a cold one (<30%) shrinks by one
                if occ >= 0.85 or load >= 2.0:
                    want = math.ceil(n * min(max(occ / 0.85, 1.0), 4.0))
                elif occ < 0.3 and ema < 0.1 * target_queue_wait_s and n > 1:
                    want = n - 1
                else:
                    want = n
            out[role] = max(min_replicas, min(want, max_replicas))
        return out

    # -- stats ----------------------------------------------------------------

    def stats(self) -> dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            routable = sum(
                1 for s in self._replicas.values() if self._routable(s, now)
            )
            out = {
                "fleet-policy": self.policy,
                "fleet-lambda": self.lam,
                "fleet-replica-count": len(self._replicas),
                "fleet-routable-replicas": routable,
                "fleet-routed-affinity-total": self.routed_affinity_total,
                "fleet-routed-sticky-total": self.routed_sticky_total,
                "fleet-sticky-held-total": self.sticky_held_total,
                "fleet-routed-balanced-total": self.routed_balanced_total,
                "fleet-routed-adapter-total": self.routed_adapter_total,
                "fleet-routed-tenant-affinity-total": (
                    self.routed_tenant_affinity_total
                ),
                "fleet-tenant-shed-total": self.tenant_shed_total,
                "fleet-shed-total": self.shed_total,
                "fleet-failover-total": self.failover_total,
                "fleet-stream-failovers-total": self.stream_failover_total,
                "fleet-beacon-failures-total": self.beacon_failures_total,
                "fleet-circuit-open-total": self.circuit_open_total,
                "fleet-routed-prefill-total": self.routed_prefill_total,
                "fleet-migrations-total": self.migrations_total,
                "fleet-migrate-pages-total": self.migrate_pages_total,
                "fleet-migrate-bytes-total": self.migrate_bytes_total,
                "fleet-migrate-fallbacks-total": self.migrate_fallbacks_total,
                "fleet-p2p-fetch-total": self.p2p_fetch_total,
                "fleet-p2p-fetch-fallback-total": (
                    self.p2p_fetch_fallback_total
                ),
                "fleet-p2p-bytes-in-total": self.p2p_bytes_in_total,
                "fleet-p2p-cost-routed-total": self.p2p_cost_routed_total,
                "fleet-p2p-bw-ema-bytes-s": round(self._p2p_bw_ema, 1),
                "fleet-prefetch-total": self.prefetch_total,
                "fleet-prefetch-fetch-total": self.prefetch_fetch_total,
                "fleet-roles": {
                    role: sum(
                        1 for s in self._replicas.values() if s.role == role
                    )
                    for role in ("prefill", "decode", "mixed")
                },
                "fleet-circuit-open-replicas": sum(
                    1 for s in self._replicas.values() if s.circuit_open
                ),
                "fleet-sticky-sessions": len(self._sticky),
            }
        out["fleet-dispatch-p50-ms"] = round(
            self.dispatch_hist.percentile(0.50) * 1e3, 4
        )
        out["fleet-dispatch-p99-ms"] = round(
            self.dispatch_hist.percentile(0.99) * 1e3, 4
        )
        out["fleet-hop-p50-ms"] = round(
            self.hop_hist.percentile(0.50) * 1e3, 4
        )
        out["fleet-hop-p99-ms"] = round(
            self.hop_hist.percentile(0.99) * 1e3, 4
        )
        out["fleet-migrate-p50-ms"] = round(
            self.migrate_hist.percentile(0.50) * 1e3, 4
        )
        out["fleet-migrate-p99-ms"] = round(
            self.migrate_hist.percentile(0.99) * 1e3, 4
        )
        # mirrored into /metrics by the genai exporter (same load() path
        # as the engine histograms — docs/SERVING.md §12/§17)
        out["histograms"] = {
            "fleet_hop_s": self.hop_hist.snapshot(),
            "fleet_migrate_s": self.migrate_hist.snapshot(),
        }
        out["fleet-desired-replicas"] = self.desired_replicas()
        out["fleet-desired-replicas-by-role"] = (
            self.desired_replicas_by_role()
        )
        # process-wide wire byte accounting by protocol (§21): counted at
        # each SENDING site in serving/wire-aware code paths — the
        # v1-vs-v2 overhead panel's raw series
        from langstream_tpu.serving import wire as wire_mod

        wb = wire_mod.wire_stats()
        out["fleet-wire-bytes-v1-total"] = int(wb.get("v1", 0))
        out["fleet-wire-bytes-v2-total"] = int(wb.get("v2", 0))
        return out


# ---------------------------------------------------------------------------
# Standalone replica server (bench_fleet / failure drills):
#   python -m langstream_tpu.serving.fleet --config '{"model": "tiny-test"}'
# prints one JSON line {"url": ..., "replica": ...} once the engine is warm,
# then serves /state + /fleet/generate until stdin closes.
# ---------------------------------------------------------------------------


async def _serve(config: dict[str, Any], host: str, port: int) -> None:
    import asyncio
    import sys

    from langstream_tpu.ai.tpu_serving import _EngineHolder
    from langstream_tpu.runtime.http_server import RuntimeHttpServer

    # wire-level fault drills (docs/SERVING.md §17): the worker's config
    # may carry a net-* spec for THIS process's transport/handler sites —
    # separate keys from the engine's fault-injection so a drill can cut
    # the wire of a perfectly healthy engine
    wire_spec = str(config.get("wire-fault-injection") or "").strip()
    if wire_spec:
        from langstream_tpu.serving.faultinject import FaultInjector

        set_wire_injector(FaultInjector(
            wire_spec,
            seed=int(config.get("wire-fault-seed", 0)),
            stall_s=float(config.get("wire-fault-stall-s", 0.05)),
        ))
    holder = _EngineHolder(config)
    engine = holder.engine()  # builds + starts + registers the beacon
    replica_id = str(config.get("fleet-replica-id") or "replica-0")
    server = RuntimeHttpServer(
        metrics_text=lambda: "",
        agents_info=lambda: [{"replica": replica_id, "role": "fleet-replica"}],
        host=host,
        port=port,
    )
    await server.start()
    print(
        json.dumps({"url": server.url, "replica": replica_id}), flush=True
    )
    loop = asyncio.get_running_loop()
    # parent closes our stdin to stop us (portable subprocess lifecycle)
    await loop.run_in_executor(None, sys.stdin.read)
    # teardown ORDER matters (§19 satellite): unregister the beacon and
    # drain the engine FIRST, while the HTTP server still serves — peers
    # stop routing here within one refresh (empty /state beats the old
    # race where new remote routes landed mid-drain and died as hop
    # failures against the wrong breaker), and in-flight remote streams
    # finish over the still-open wire. Only then drop the server and
    # hard-stop.
    await loop.run_in_executor(None, holder.begin_drain)
    await server.stop()
    holder.close()


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import asyncio

    p = argparse.ArgumentParser(description="serve one fleet replica")
    p.add_argument("--config", required=True, help="tpu-serving config JSON")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    args = p.parse_args(argv)
    config = json.loads(args.config)
    asyncio.run(_serve(config, args.host, args.port))
    return 0


if __name__ == "__main__":  # pragma: no cover — subprocess entry
    raise SystemExit(main())
