"""Fleet router: radix-prefix-affinity routing + cache-aware load balancing
across N serving-engine replicas (ROADMAP item 3).

One engine is fast (BENCH_r05), but a second replica placed blindly HALVES
the prefix hit rate: requests sharing a preamble land on whichever replica
the balancer felt like, each replica re-prefills the preamble cold, and the
paged pool's zero-copy aliasing (PR 5) never fires. This module is the tier
that millions of users actually hit — the piece between the gateway and the
engines:

- **Beacons** (`beacon_from_engine`, served at ``GET /state`` by the
  runtime HTTP server): each replica periodically advertises a compact
  state document — its ``load_score`` (queue-wait p90 + occupancy + page
  pressure, serving/observability.py), queue-wait EMA, free KV pages,
  drain/quarantine flags, and the top-K prefix DIGESTS its radix index
  holds (``pagepool.prefix_digest`` — 8-byte hashes, never token content;
  the same redaction stance as the flight recorder). The non-mutating
  ``match_len`` probes exist so beacon building and router probing never
  touch LRU recency: advertising a prefix must not pin it.

- **Router** (`FleetRouter`): dispatches each request by *prefix affinity
  first, load second*. It hashes the incoming prompt at every advertised
  boundary length and scores each replica

      score(r) = expected_match_tokens(r) − λ · load_score(r)

  routing to the argmax; when no replica holds a usable prefix the request
  goes to the least-loaded replica instead. λ (tokens per load-score unit,
  default 256) is the knob that decides when a hot replica is TOO hot to be
  worth its warm cache — see docs/SERVING.md §13 for tuning. Sticky
  sessions (``langstream-client-session-id`` → replica) keep multi-turn
  chats on the replica whose pages they aliased. Overload sheds against
  the replicas' EXPORTED signals (every routable replica's admission queue
  full, or every queue-wait EMA past the bound) rather than a blind
  request cap, and a replica that dies mid-burst is quarantined and its
  requests re-routed — in-flight work fails over COLD to a survivor
  (DeepServe's affinity-and-load dispatch, PAPERS.md).

- **Autoscale hint** (`FleetRouter.desired_replicas`): the k8s planner's
  scale signal, derived from the fleet-wide queue-wait EMA (scale-up) and
  occupancy (scale-down) — surfaced as the ``langstream.ai/desired-replicas``
  annotation k8s/resources.py honors on the agent StatefulSet.

The routing tier is deliberately ABOVE the engines and programmable
(PAPERS.md "Software-Defined Agentic Serving"): transports are duck-typed
(`InProcessReplica` for tests/embedded runners, `HttpReplica` over the
runtime HTTP server for real pods), and the policy is a constructor knob
(``affinity`` | ``round-robin`` | ``least-loaded`` — round-robin exists as
the bench control arm, not a production mode).

Run ``python -m langstream_tpu.serving.fleet --config '<json>'`` to serve
one replica (engine + /state + /fleet/generate) as a standalone process —
the multi-process CPU fleet bench (bench.py bench_fleet) and the failure
drills are built on this.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from langstream_tpu.api.metrics import Histogram, log_buckets
from langstream_tpu.serving.pagepool import prefix_digest

log = logging.getLogger(__name__)

BEACON_SCHEMA = "lstpu-beacon-v1"
STATE_SCHEMA = "lstpu-state-v1"

# λ default: tokens of expected prefix match one unit of load score is
# worth. load_score ≈ queue-wait p90 seconds + occupancy (0..1) + page
# pressure (0..1); at λ=256 a fully-busy replica (occupancy+pages ≈ 2)
# still wins the route when it holds ≥512 more warm prefix tokens than an
# idle one, but one second of queue wait erases a 256-token advantage.
DEFAULT_LAMBDA = 256.0


class FleetShedError(RuntimeError):
    """The fleet cannot place this request right now (every routable
    replica is saturated, or none is routable). Callers surface it exactly
    like the engine's ShedError — HTTP 429 with Retry-After."""

    def __init__(self, reason: str, retry_after_s: float = 1.0) -> None:
        super().__init__(reason)
        self.retry_after_s = retry_after_s


class ReplicaError(RuntimeError):
    """A dispatch to one replica failed (process died, HTTP unreachable,
    engine stopped). The router quarantines the replica and fails the
    request over to a survivor — this error type is what separates
    'replica is broken' from 'replica said no' (FleetShedError)."""


# ---------------------------------------------------------------------------
# Beacon
# ---------------------------------------------------------------------------


def beacon_from_engine(
    replica_id: str, engine: Any, url: str = "", top_k: int = 32,
) -> dict[str, Any]:
    """Build the compact state beacon one replica advertises. Token content
    never appears — prefixes travel as (digest, length) pairs. Safe to call
    from any thread (engine.stats() and the advertisement registries take
    their own locks)."""
    stats = engine.stats()
    adv = getattr(engine, "prefix_advertisement", None)
    boundaries, prefixes = adv(top_k) if adv is not None else ((), [])
    hist = stats.get("histograms") or {}
    ttft = hist.get("engine_ttft_s") or {}
    thread = getattr(engine, "_thread", None)
    dead = getattr(engine, "_dead", None) is not None or (
        thread is None or not thread.is_alive()
    )
    pages_total = stats.get("kv-pages-total", 0)
    return {
        "schema": BEACON_SCHEMA,
        "id": str(replica_id),
        "url": url,
        "at": round(time.time(), 3),
        "load_score": stats.get("load-score", 0.0),
        "queue_wait_ema_s": stats.get("queue-wait-ema-s", 0.0),
        "active_slots": stats.get("active-slots", 0),
        "max_batch": stats.get("max-batch", 0),
        "queued": stats.get("queued", 0),
        "queue_depth": int(getattr(engine, "_queue", None).maxsize or 0)
        if getattr(engine, "_queue", None) is not None
        else 0,
        "shed_policy": getattr(engine, "shed_policy", "block"),
        "shed_total": stats.get("shed-total", 0),
        "kv_pages_total": pages_total,
        "kv_pages_free": max(0, pages_total - stats.get("kv-pages-in-use", 0)),
        "draining": bool(stats.get("draining", False)),
        "quarantined": bool(dead),
        "prefix_hit_rate": stats.get("prefix-cache-hit-rate", 0.0),
        "prefill_tokens_saved_total": stats.get("prefill-tokens-saved-total", 0),
        "ttft_p50_ms": round(float(ttft.get("p50", 0.0)) * 1e3, 3),
        "ttft_p99_ms": round(float(ttft.get("p99", 0.0)) * 1e3, 3),
        "boundaries": [int(b) for b in boundaries],
        # device-resident prefixes vs hibernated ones (tiered KV, §16):
        # a spilled session's digest keeps advertising so sticky routing
        # survives hibernation — the router scores it at a discount (the
        # restore is cheap but not free). Advertisement triples may come
        # from the dense pool too, where everything is device-resident.
        "prefixes": [
            [d, int(n)] for d, n, tier in prefixes if tier != "host"
        ],
        "spilled_prefixes": [
            [d, int(n)] for d, n, tier in prefixes if tier == "host"
        ],
        # resident LoRA adapters (NAMES only, never factors): the router's
        # adapter-affinity signal — landing a tenant's request on a replica
        # already holding its adapter skips a hot-swap dispatch (§15)
        "adapters": [
            str(a)
            for a in (
                engine.adapter_advertisement()
                if hasattr(engine, "adapter_advertisement")
                else ()
            )
        ],
    }


def validate_beacon(doc: dict[str, Any]) -> bool:
    """Schema check for one beacon (docs/SERVING.md §13): raises ValueError
    on the first violation. Enforces the redaction contract — a beacon
    carries digests, never tokens."""
    if not isinstance(doc, dict):
        raise ValueError("beacon must be a JSON object")
    if doc.get("schema") != BEACON_SCHEMA:
        raise ValueError(f"unknown beacon schema {doc.get('schema')!r}")
    for key in (
        "id", "at", "load_score", "queue_wait_ema_s", "draining",
        "quarantined", "prefixes",
    ):
        if key not in doc:
            raise ValueError(f"beacon missing field {key!r}")
    for key in ("prefixes", "spilled_prefixes"):
        for j, pair in enumerate(doc.get(key) or []):
            if (
                not isinstance(pair, (list, tuple))
                or len(pair) != 2
                or not isinstance(pair[0], str)
                or not isinstance(pair[1], int)
            ):
                raise ValueError(
                    f"{key} advertisement {j} is not [digest, length]"
                )
    for j, name in enumerate(doc.get("adapters") or []):
        if not isinstance(name, str):
            raise ValueError(f"adapter advertisement {j} is not a name string")
    for forbidden in ("tokens", "prompt", "text", "prompt_tokens"):
        if forbidden in doc:
            raise ValueError(f"beacon carries token-content key {forbidden!r}")
    json.dumps(doc)
    return True


# ---------------------------------------------------------------------------
# Local replica registry (the runtime HTTP server's /state + /fleet/generate
# read this — same process-global pattern as observability.RECENT_DUMPS, so
# the server never holds an engine reference)
# ---------------------------------------------------------------------------

_LOCAL_LOCK = threading.Lock()
_LOCAL: dict[str, dict[str, Callable]] = {}


def register_local(
    replica_id: str,
    beacon_fn: Callable[[], dict],
    generate_fn: Optional[Callable[[dict], dict]] = None,
    reset_fn: Optional[Callable[[], None]] = None,
) -> None:
    """Expose this process's engine on the runtime HTTP server: ``GET
    /state`` serves ``beacon_fn``, ``POST /fleet/generate`` runs
    ``generate_fn`` (fleet-internal dispatch), ``POST /fleet/reset`` runs
    ``reset_fn`` (bench warmup hygiene)."""
    with _LOCAL_LOCK:
        _LOCAL[str(replica_id)] = {
            "beacon": beacon_fn, "generate": generate_fn, "reset": reset_fn,
        }


def unregister_local(replica_id: str) -> None:
    with _LOCAL_LOCK:
        _LOCAL.pop(str(replica_id), None)


def local_state() -> dict[str, Any]:
    """The /state document: every engine registered in this process (one,
    for every real topology)."""
    with _LOCAL_LOCK:
        entries = list(_LOCAL.items())
    replicas = []
    for replica_id, fns in entries:
        try:
            replicas.append(fns["beacon"]())
        except Exception:  # noqa: BLE001 — a crashed engine still beacons
            log.exception("beacon build failed for %s", replica_id)
            replicas.append(
                {
                    "schema": BEACON_SCHEMA, "id": replica_id, "url": "",
                    "at": round(time.time(), 3), "load_score": 1e9,
                    "queue_wait_ema_s": 0.0, "draining": False,
                    "quarantined": True, "prefixes": [],
                }
            )
    return {"schema": STATE_SCHEMA, "replicas": replicas}


def local_generate(payload: dict[str, Any]) -> dict[str, Any]:
    """Fleet-internal dispatch into this process's engine (the POST
    /fleet/generate body). Blocking — the HTTP server runs it in an
    executor. Raises ReplicaError when no engine is registered (the
    router treats that as a dead replica and fails over)."""
    with _LOCAL_LOCK:
        if not _LOCAL:
            raise ReplicaError("no serving engine registered in this process")
        fns = next(iter(_LOCAL.values()))
    gen = fns.get("generate")
    if gen is None:
        raise ReplicaError("registered engine does not accept fleet dispatch")
    return gen(payload)


def local_reset() -> None:
    with _LOCAL_LOCK:
        entries = list(_LOCAL.values())
    for fns in entries:
        reset = fns.get("reset")
        if reset is not None:
            reset()


def engine_generate(
    engine: Any, payload: dict[str, Any], timeout_s: float = 600.0,
) -> dict[str, Any]:
    """The canonical ``generate_fn`` for ``register_local``: run one
    completion on the local engine from a fleet-dispatch payload
    (``{"prompt_tokens": [...], "options": {...}}``) and return a plain
    JSON-able result. Engine sheds propagate as FleetShedError so the HTTP
    layer can answer 429 + Retry-After.

    Cross-process cancel (ROADMAP 3b): when the options carry a
    ``cancel-key`` (the client session id the dispatching gateway routes
    disconnects by), the in-flight request registers in THIS process's
    lifecycle registry, so a forwarded ``POST /fleet/cancel`` from the
    gateway frees the slot at the next chunk boundary."""
    from langstream_tpu.models.configs import GenerationOptions
    from langstream_tpu.serving import lifecycle
    from langstream_tpu.serving.engine import GenerationRequest, ShedError

    tokens = [int(t) for t in payload.get("prompt_tokens") or []]
    if not tokens:
        raise ValueError("fleet dispatch payload carries no prompt_tokens")
    options = payload.get("options") or {}
    opts = GenerationOptions.from_dict(options)
    cancel_key = str(options.get("cancel-key") or "")
    # pre-built so it can register for cross-process cancel BEFORE the
    # submit; engine.generate keeps the submit/wait/cancel-on-timeout
    # contract in one place
    request = GenerationRequest(prompt_tokens=tokens, options=opts)
    if cancel_key:
        lifecycle.register(cancel_key, request)
    try:
        try:
            result = engine.generate(request=request, timeout=timeout_s)
        except ShedError as e:
            raise FleetShedError(str(e), retry_after_s=e.retry_after_s) from e
    finally:
        if cancel_key:
            lifecycle.unregister(cancel_key, request)
    return {
        "tokens": [int(t) for t in result.tokens],
        "finish_reason": result.finish_reason,
        "prompt_tokens": result.prompt_tokens,
        "ttft_s": round(result.ttft_s, 6),
        "total_s": round(result.total_s, 6),
    }


# ---------------------------------------------------------------------------
# Replica transports (duck-typed: .replica_id, .fetch_beacon(), .generate())
# ---------------------------------------------------------------------------


class InProcessReplica:
    """A replica living in this process — the unit-test / embedded-runner
    transport, and the 'self' handle when the completions service fronts
    its own engine plus remote peers."""

    is_local = True

    def __init__(self, replica_id: str, engine: Any, url: str = "") -> None:
        self.replica_id = str(replica_id)
        self.engine = engine
        self.url = url or f"local:{replica_id}"

    def fetch_beacon(self) -> dict[str, Any]:
        return beacon_from_engine(self.replica_id, self.engine, url=self.url)

    def generate(
        self, tokens, options: Optional[dict] = None, timeout_s: float = 600.0,
    ) -> dict[str, Any]:
        try:
            return engine_generate(
                self.engine,
                {"prompt_tokens": list(tokens), "options": options or {}},
                timeout_s=timeout_s,
            )
        except (FleetShedError, ValueError):
            # sheds re-route; a BAD REQUEST is the caller's bug — neither
            # may quarantine the replica (a malformed request retried
            # across the fleet would mark every replica failed)
            raise
        except Exception as e:  # noqa: BLE001 — stopped/crashed engine
            raise ReplicaError(f"replica {self.replica_id}: {e}") from e

    def reset_histograms(self) -> None:
        self.engine.reset_histograms()


class HttpReplica:
    """A replica behind its runtime HTTP server (entrypoint pods, the
    bench's subprocess fleet). Uses stdlib urllib — these calls run on the
    router's refresher thread and dispatch executors, never an event loop."""

    is_local = False

    def __init__(
        self, replica_id: str, base_url: str,
        beacon_timeout_s: float = 2.0, generate_timeout_s: float = 600.0,
    ) -> None:
        self.replica_id = str(replica_id)
        self.url = base_url.rstrip("/")
        self.beacon_timeout_s = beacon_timeout_s
        self.generate_timeout_s = generate_timeout_s

    def _get(self, path: str, timeout_s: float) -> dict[str, Any]:
        with urllib.request.urlopen(self.url + path, timeout=timeout_s) as r:
            return json.loads(r.read().decode("utf-8"))

    def fetch_beacon(self) -> dict[str, Any]:
        try:
            doc = self._get("/state", self.beacon_timeout_s)
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ReplicaError(f"replica {self.replica_id}: {e}") from e
        replicas = doc.get("replicas") or []
        for b in replicas:
            if b.get("id") == self.replica_id:
                return b
        if replicas:
            return replicas[0]
        raise ReplicaError(f"replica {self.replica_id}: empty /state")

    def generate(
        self, tokens, options: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ) -> dict[str, Any]:
        body = json.dumps(
            {"prompt_tokens": list(map(int, tokens)), "options": options or {}}
        ).encode("utf-8")
        req = urllib.request.Request(
            self.url + "/fleet/generate", data=body,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=timeout_s or self.generate_timeout_s
            ) as r:
                return json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            if e.code == 429:
                retry = float(e.headers.get("Retry-After") or 1.0)
                raise FleetShedError(
                    f"replica {self.replica_id} shed", retry_after_s=retry
                ) from e
            if 400 <= e.code < 500:
                # the REQUEST is bad, not the replica: retrying it on the
                # rest of the fleet would brown out every replica
                raise ValueError(
                    f"replica {self.replica_id} rejected request: "
                    f"HTTP {e.code} {e.reason}"
                ) from e
            raise ReplicaError(
                f"replica {self.replica_id}: HTTP {e.code}"
            ) from e
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise ReplicaError(f"replica {self.replica_id}: {e}") from e

    def reset_histograms(self) -> None:
        try:
            urllib.request.urlopen(
                urllib.request.Request(
                    self.url + "/fleet/reset", data=b"{}", method="POST",
                    headers={"Content-Type": "application/json"},
                ),
                timeout=self.beacon_timeout_s,
            ).read()
        except (urllib.error.URLError, OSError) as e:
            raise ReplicaError(f"replica {self.replica_id}: {e}") from e


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------


@dataclass
class _ReplicaState:
    handle: Any
    beacon: dict[str, Any] = field(default_factory=dict)
    beacon_at: float = -1e18  # monotonic of last SUCCESSFUL refresh
    failed_at: float = -1e18  # monotonic of last mark_failed
    digests: dict[str, int] = field(default_factory=dict)  # digest → length
    # hibernated (host-tier) prefix digests: the session's KV survives on
    # the replica but needs a restore — scored at spill_discount
    spilled_digests: dict[str, int] = field(default_factory=dict)
    adapters: frozenset = frozenset()  # resident LoRA adapter names


@dataclass
class RouteDecision:
    replica_id: str
    handle: Any
    kind: str  # affinity | sticky | balanced
    expected_match: int
    score: float


class FleetRouter:
    """Prefix-affinity-first, load-second dispatch across replicas.

    ``route()`` is pure host bookkeeping under one lock — no I/O, no
    hashing beyond one digest per advertised boundary length (<1 ms p50,
    histogram-enforced by the bench). Beacons refresh on a background
    thread (``start()``); a replica whose beacon goes stale, whose process
    stops answering, or that advertises drain/quarantine simply drops out
    of the routable set — requests re-route, nothing hangs."""

    POLICIES = ("affinity", "round-robin", "least-loaded")

    def __init__(
        self,
        replicas: list[Any],
        *,
        lam: float = DEFAULT_LAMBDA,
        policy: str = "affinity",
        beacon_ttl_s: float = 10.0,
        refresh_interval_s: float = 0.5,
        sticky_ttl_s: float = 600.0,
        fail_cooldown_s: float = 5.0,
        shed_queue_wait_s: float = 30.0,
        adapter_affinity_tokens: float = 512.0,
        spill_discount: float = 0.5,
    ) -> None:
        if policy not in self.POLICIES:
            raise ValueError(
                f"unknown fleet policy {policy!r}; supported: {self.POLICIES}"
            )
        if not replicas:
            raise ValueError("fleet router needs >= 1 replica")
        self.lam = float(lam)
        self.policy = policy
        self.beacon_ttl_s = float(beacon_ttl_s)
        self.refresh_interval_s = float(refresh_interval_s)
        self.sticky_ttl_s = float(sticky_ttl_s)
        self.fail_cooldown_s = float(fail_cooldown_s)
        self.shed_queue_wait_s = float(shed_queue_wait_s)
        # adapter affinity in PREFIX-TOKEN units: routing a tenant to a
        # replica already holding its adapter is scored as worth this many
        # warm prefix tokens (a hot-swap dispatch ≈ re-prefilling that
        # much prompt on the engines measured; tune alongside λ — §15)
        self.adapter_affinity_tokens = float(adapter_affinity_tokens)
        # a HIBERNATED prefix match (the owner spilled the session's pages
        # to host RAM) is worth this fraction of a device-resident match:
        # the restore is a DMA upload, cheaper than re-prefilling but not
        # free — and it says nothing about the replica being otherwise
        # idle. 0 ignores spilled advertisements; 1 scores them at par.
        self.spill_discount = min(1.0, max(0.0, float(spill_discount)))
        self._lock = threading.Lock()
        self._replicas: dict[str, _ReplicaState] = {}
        for r in replicas:
            if r.replica_id in self._replicas:
                raise ValueError(f"duplicate replica id {r.replica_id!r}")
            self._replicas[r.replica_id] = _ReplicaState(handle=r)
        self._sticky: dict[str, tuple[str, float]] = {}
        self._rr = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters (under _lock) + the dispatch-overhead histogram the
        # acceptance criterion reads
        self.routed_affinity_total = 0
        self.routed_sticky_total = 0
        self.routed_balanced_total = 0
        self.routed_adapter_total = 0
        self.shed_total = 0
        self.failover_total = 0
        self._hist_lock = threading.Lock()
        self.dispatch_hist = Histogram(
            "fleet_dispatch_s",
            "router route() host wall time per dispatch (s)",
            log_buckets(1e-7, 1.0, 4),
        )

    # -- beacon refresh -----------------------------------------------------

    def refresh_all(self) -> int:
        """Fetch every replica's beacon once (synchronously). Returns how
        many refreshed successfully. Failures just leave the old beacon to
        age out — route() treats stale as unroutable."""
        ok = 0
        for state in list(self._replicas.values()):
            try:
                beacon = state.handle.fetch_beacon()
            except ReplicaError as e:
                log.debug("beacon refresh failed: %s", e)
                continue
            except Exception:  # noqa: BLE001 — refresher must never die
                log.exception(
                    "beacon refresh crashed for %s", state.handle.replica_id
                )
                continue
            with self._lock:
                state.beacon = beacon
                state.beacon_at = time.monotonic()
                state.digests = {
                    d: int(n) for d, n in (beacon.get("prefixes") or [])
                }
                state.spilled_digests = {
                    d: int(n)
                    for d, n in (beacon.get("spilled_prefixes") or [])
                }
                state.adapters = frozenset(
                    str(a) for a in (beacon.get("adapters") or [])
                )
            ok += 1
        return ok

    def start(self, initial_refresh: bool = True) -> None:
        if initial_refresh:
            self.refresh_all()
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._refresh_loop, name="fleet-beacons", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self.refresh_interval_s):
            self.refresh_all()

    # -- health -------------------------------------------------------------

    @property
    def replica_count(self) -> int:
        return len(self._replicas)

    def note_failover(self, replica_id: str) -> None:
        """A caller-observed mid-dispatch death: quarantine the replica AND
        count the failover — the completions path's failover loop must show
        up in fleet stats exactly like router.generate's own."""
        self.mark_failed(replica_id)
        with self._lock:
            self.failover_total += 1

    def mark_failed(self, replica_id: str) -> None:
        """A dispatch to this replica failed: quarantine it for
        ``fail_cooldown_s`` (and until a FRESH beacon proves it back). Its
        sticky sessions fail over cold at their next request."""
        with self._lock:
            state = self._replicas.get(replica_id)
            if state is None:
                return
            now = time.monotonic()
            state.failed_at = now
            # the beacon that routed us here predates the failure — drop it
            # so recovery requires a refresh newer than the incident
            state.beacon_at = -1e18

    def _routable(self, state: _ReplicaState, now: float) -> bool:
        if now - state.failed_at < self.fail_cooldown_s:
            return False
        if now - state.beacon_at > self.beacon_ttl_s:
            return False
        b = state.beacon
        return not (b.get("draining") or b.get("quarantined"))

    # -- routing ------------------------------------------------------------

    @staticmethod
    def _load(beacon: dict[str, Any]) -> float:
        return float(beacon.get("load_score", 0.0) or 0.0)

    def route(
        self,
        tokens,
        session_id: Optional[str] = None,
        exclude: Optional[set] = None,
        adapter: Optional[str] = None,
    ) -> RouteDecision:
        """Pick the replica for one request. Raises FleetShedError when no
        replica is routable or every routable replica is saturated (full
        admission queue, or queue-wait EMA past ``shed_queue_wait_s``).
        ``adapter``: the request's LoRA adapter name — replicas advertising
        it resident score an ``adapter_affinity_tokens`` bonus alongside
        prefix affinity."""
        t0 = time.perf_counter()
        try:
            return self._route(
                list(tokens), session_id, exclude or set(), adapter
            )
        finally:
            # Histogram.record is single-writer by contract (the engine's
            # histograms have exactly one writer thread); route() runs on
            # many dispatch threads, so the router serializes its own
            # recording
            with self._hist_lock:
                self.dispatch_hist.record(time.perf_counter() - t0)

    def _route(
        self, tokens: list, session_id: Optional[str], exclude: set,
        adapter: Optional[str] = None,
    ) -> RouteDecision:
        now = time.monotonic()
        with self._lock:
            live = [
                s
                for rid, s in self._replicas.items()
                if rid not in exclude and self._routable(s, now)
            ]
            if not live:
                self.shed_total += 1
                raise FleetShedError(
                    "no routable replica (all stale, draining, quarantined "
                    "or excluded)",
                    retry_after_s=max(self.refresh_interval_s, 0.5),
                )
            # fleet-level shed: every routable replica says it cannot take
            # more — the replicas' OWN exported signals, not a blind bound
            saturated = [
                s
                for s in live
                if (
                    s.beacon.get("queue_depth", 0) > 0
                    and s.beacon.get("queued", 0)
                    >= s.beacon.get("queue_depth", 0)
                )
                or float(s.beacon.get("queue_wait_ema_s", 0.0))
                >= self.shed_queue_wait_s
            ]
            if len(saturated) == len(live):
                self.shed_total += 1
                retry = min(
                    max(float(s.beacon.get("queue_wait_ema_s", 0.0)), 0.1)
                    for s in live
                )
                raise FleetShedError(
                    f"all {len(live)} routable replicas saturated",
                    retry_after_s=retry,
                )
            if self.policy == "round-robin":
                state = live[self._rr % len(live)]
                self._rr += 1
                self.routed_balanced_total += 1
                return self._decide(state, "balanced", 0, session_id, now)
            # sticky: same session stays on its replica while that replica
            # stays routable (its aliased pages are live there)
            if session_id:
                self._prune_sticky(now)
                held = self._sticky.get(session_id)
                if held is not None:
                    rid, last_used = held
                    state = self._replicas.get(rid)
                    if (
                        now - last_used <= self.sticky_ttl_s
                        and state is not None
                        and state in live
                    ):
                        self.routed_sticky_total += 1
                        return self._decide(state, "sticky", 0, session_id, now)
                    # replica gone or the session idled past its TTL (its
                    # pages are likely evicted by now): fall through — the
                    # session re-routes cold to whatever wins below
                    self._sticky.pop(session_id, None)
            if self.policy == "least-loaded":
                state = min(live, key=lambda s: self._load(s.beacon))
                self.routed_balanced_total += 1
                return self._decide(state, "balanced", 0, session_id, now)
            # affinity scoring: hash the prompt once per advertised length
            # (device-resident AND hibernated advertisements both probe)
            lengths = sorted(
                {
                    n
                    for s in live
                    for src in (s.digests, s.spilled_digests)
                    for n in src.values()
                    if n <= len(tokens) - 1
                }
            )
            probe = {n: prefix_digest(tokens[:n]) for n in lengths}
            best, best_score, best_match = None, None, 0
            best_adapter_hit = False
            for s in live:
                match, spilled_match = 0, 0
                for n in lengths:
                    if s.digests.get(probe[n]) == n and n > match:
                        match = n
                    if (
                        s.spilled_digests.get(probe[n]) == n
                        and n > spilled_match
                    ):
                        spilled_match = n
                # a hibernated session's KV still lives on its owner — a
                # restore beats a cold re-prefill anywhere else, so the
                # spilled match competes, discounted (tiered KV, §16)
                effective = max(
                    match, int(spilled_match * self.spill_discount)
                )
                adapter_hit = bool(adapter) and adapter in s.adapters
                score = (
                    effective
                    + (self.adapter_affinity_tokens if adapter_hit else 0.0)
                    - self.lam * self._load(s.beacon)
                )
                if best_score is None or score > best_score:
                    best, best_score, best_match = s, score, effective
                    best_adapter_hit = adapter_hit
            assert best is not None
            if best_adapter_hit:
                self.routed_adapter_total += 1
            if best_match > 0 or best_adapter_hit:
                self.routed_affinity_total += 1
                kind = "affinity"
            else:
                # nobody holds a usable prefix: least-loaded fallback (the
                # scored argmax already IS least-loaded when match==0 for
                # everyone, since score reduces to −λ·load)
                self.routed_balanced_total += 1
                kind = "balanced"
            return self._decide(best, kind, best_match, session_id, now)

    def _decide(
        self,
        state: _ReplicaState,
        kind: str,
        match: int,
        session_id: Optional[str],
        now: float,
    ) -> RouteDecision:
        rid = state.handle.replica_id
        if session_id:
            self._sticky[session_id] = (rid, now)
        return RouteDecision(
            replica_id=rid,
            handle=state.handle,
            kind=kind,
            expected_match=match,
            score=match - self.lam * self._load(state.beacon),
        )

    def _prune_sticky(self, now: float) -> None:
        if len(self._sticky) < 4096:
            return
        self._sticky = {
            k: v
            for k, v in self._sticky.items()
            if now - v[1] <= self.sticky_ttl_s
        }

    # -- dispatch with failover ----------------------------------------------

    def generate(
        self,
        tokens,
        options: Optional[dict] = None,
        session_id: Optional[str] = None,
        timeout_s: float = 600.0,
    ) -> tuple[dict[str, Any], RouteDecision]:
        """Route + dispatch one request, failing over COLD to a surviving
        replica when the chosen one dies mid-flight (ReplicaError). A
        replica that merely sheds is excluded and the rest get a chance;
        when everyone sheds, the fleet-level FleetShedError propagates with
        the smallest retry-after observed."""
        tokens = list(tokens)
        excluded: set = set()
        last_shed: Optional[FleetShedError] = None
        for _ in range(self.replica_count):
            decision = self.route(tokens, session_id, exclude=excluded)
            try:
                out = decision.handle.generate(
                    tokens, options or {}, timeout_s
                )
                return out, decision
            except FleetShedError as e:
                last_shed = e
                excluded.add(decision.replica_id)
            except ReplicaError as e:
                log.warning(
                    "replica %s failed mid-dispatch (%s); failing over",
                    decision.replica_id, e,
                )
                self.note_failover(decision.replica_id)
                excluded.add(decision.replica_id)
        if last_shed is not None:
            with self._lock:
                self.shed_total += 1
            raise last_shed
        raise FleetShedError(
            "every replica failed or shed this request", retry_after_s=1.0
        )

    # -- autoscale hint -------------------------------------------------------

    def desired_replicas(
        self,
        target_queue_wait_s: float = 0.5,
        min_replicas: int = 1,
        max_replicas: int = 64,
    ) -> int:
        """The k8s planner's scale hint, from the fleet-wide queue-wait EMA:
        scale OUT proportionally when the mean routable queue wait exceeds
        the target (capped at 4× per step so one burst can't quadruple the
        fleet), scale IN one replica at a time only when queues are empty
        AND occupancy is low (conservative — killing a warm replica throws
        away its aliased pages). With no routable beacon the hint holds the
        current size: never scale on missing data."""
        now = time.monotonic()
        with self._lock:
            total = len(self._replicas)
            live = [
                s.beacon
                for s in self._replicas.values()
                if self._routable(s, now)
            ]
        if not live:
            return max(min_replicas, min(total, max_replicas))
        n = len(live)
        ema = sum(float(b.get("queue_wait_ema_s", 0.0)) for b in live) / n
        occ = sum(
            float(b.get("active_slots", 0)) / max(1, b.get("max_batch", 1))
            for b in live
        ) / n
        if ema > target_queue_wait_s:
            want = math.ceil(n * min(ema / target_queue_wait_s, 4.0))
        elif ema < 0.1 * target_queue_wait_s and occ < 0.5 and n > 1:
            want = n - 1
        else:
            want = n
        return max(min_replicas, min(want, max_replicas))

    # -- stats ----------------------------------------------------------------

    def stats(self) -> dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            routable = sum(
                1 for s in self._replicas.values() if self._routable(s, now)
            )
            out = {
                "fleet-policy": self.policy,
                "fleet-lambda": self.lam,
                "fleet-replica-count": len(self._replicas),
                "fleet-routable-replicas": routable,
                "fleet-routed-affinity-total": self.routed_affinity_total,
                "fleet-routed-sticky-total": self.routed_sticky_total,
                "fleet-routed-balanced-total": self.routed_balanced_total,
                "fleet-routed-adapter-total": self.routed_adapter_total,
                "fleet-shed-total": self.shed_total,
                "fleet-failover-total": self.failover_total,
                "fleet-sticky-sessions": len(self._sticky),
            }
        out["fleet-dispatch-p50-ms"] = round(
            self.dispatch_hist.percentile(0.50) * 1e3, 4
        )
        out["fleet-dispatch-p99-ms"] = round(
            self.dispatch_hist.percentile(0.99) * 1e3, 4
        )
        out["fleet-desired-replicas"] = self.desired_replicas()
        return out


# ---------------------------------------------------------------------------
# Standalone replica server (bench_fleet / failure drills):
#   python -m langstream_tpu.serving.fleet --config '{"model": "tiny-test"}'
# prints one JSON line {"url": ..., "replica": ...} once the engine is warm,
# then serves /state + /fleet/generate until stdin closes.
# ---------------------------------------------------------------------------


async def _serve(config: dict[str, Any], host: str, port: int) -> None:
    import asyncio
    import sys

    from langstream_tpu.ai.tpu_serving import _EngineHolder
    from langstream_tpu.runtime.http_server import RuntimeHttpServer

    holder = _EngineHolder(config)
    engine = holder.engine()  # builds + starts + registers the beacon
    replica_id = str(config.get("fleet-replica-id") or "replica-0")
    server = RuntimeHttpServer(
        metrics_text=lambda: "",
        agents_info=lambda: [{"replica": replica_id, "role": "fleet-replica"}],
        host=host,
        port=port,
    )
    await server.start()
    print(
        json.dumps({"url": server.url, "replica": replica_id}), flush=True
    )
    loop = asyncio.get_running_loop()
    # parent closes our stdin to stop us (portable subprocess lifecycle)
    await loop.run_in_executor(None, sys.stdin.read)
    await server.stop()
    holder.close()


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import asyncio

    p = argparse.ArgumentParser(description="serve one fleet replica")
    p.add_argument("--config", required=True, help="tpu-serving config JSON")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    args = p.parse_args(argv)
    config = json.loads(args.config)
    asyncio.run(_serve(config, args.host, args.port))
    return 0


if __name__ == "__main__":  # pragma: no cover — subprocess entry
    raise SystemExit(main())
