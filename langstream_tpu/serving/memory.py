"""Serving HBM accounting: what a (model, batch, context) configuration
actually costs on a chip, BEFORE allocating it.

The reference never has to answer this question — its serving is delegated
to remote providers (OpenAICompletionService.java etc.), so context length
is someone else's capacity problem. Here the model lives in local HBM, and
the honest ceiling for long-context serving is arithmetic, not marketing:
weights + decode cache + chunked-prefill local cache + XLA workspace must
fit. ``plan_serving_memory`` computes the terms from the real param/cache
pytree shapes (``jax.eval_shape`` — nothing is allocated), and
``max_context_single_chip`` inverts the plan to the largest power-of-two
context a given HBM budget serves.

Used by bench.py's long-prompt phases and the capacity docs/tests; the
engine logs the plan at startup so an over-committed config fails loudly
with numbers instead of an opaque RESOURCE_EXHAUSTED mid-request.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax

from langstream_tpu.models.configs import ModelConfig


def _tree_bytes(shape_tree: Any) -> int:
    return sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(shape_tree)
    )


@dataclass(frozen=True)
class ServingMemoryPlan:
    weights_bytes: int
    cache_bytes: int  # decode cache: max_batch × max_seq_len
    long_cache_bytes: int  # chunked-prefill local cache (one prompt wide)
    workspace_bytes: int  # XLA scratch / activation headroom estimate
    # Residual decode-chunk temp: ONE LAYER's cache slice. The layer scan
    # carries the cache and updates it in place via dynamic-update-slice
    # (transformer._scan_layers_inplace), so the old cache-sized xs/ys
    # double-buffer is gone (r4 it OOMed llama-3-8b past B=48); what
    # remains live is the current layer's read slice + its updated copy.
    scan_buffer_bytes: int = 0
    # kv_bound slice+splice peak: a decode chunk at a SLICED bound copies
    # the cache's first `bound` columns out and back (engine._decode_chunk),
    # so up to bound/width of the cache is live ON TOP of the full cache.
    # The largest SLICED ladder bound is the largest pow2 strictly below
    # max_seq_len (the full-width program skips the slice; the ladder floors
    # at 64) — NOT width/2: for non-pow2 widths (T=1536 → bound 1024 =
    # 2/3 cache; T=1025 → bound 1024 ≈ the whole cache) the old cache/2
    # assumption under-reported and the full-ladder precompile OOMed configs
    # the plan had blessed. The r5b precompile made this peak unavoidable
    # at startup — the llama B=84 @ T=1024 config that "fit" without this
    # term compile-OOMed by exactly this allocation.
    bound_slice_bytes: int = 0
    # fused-iteration peak: with overlapped prefill–decode scheduling the
    # admission local cache (prefill_batch rows × the largest bucket width)
    # is live WHILE a decode chunk holds its kv_bound slice — before the
    # fused scheduler the two alternated, so neither plan term saw the sum.
    fused_prefill_bytes: int = 0
    # prefix KV pool (serving/prefix_cache.py): pool-entry rows × the
    # largest bucket width, resident for the engine's whole lifetime. Sized
    # by the `prefix-cache-fraction` knob; 0 when the cache is off.
    prefix_pool_bytes: int = 0
    # unified paged KV pool (serving/pagepool.py, kv_layout="paged"): ONE
    # [L, P, Hkv, page_size, D] device pool replaces the decode cache, the
    # prefix pool, the kv_bound slice/splice peak AND the chunked-prefill
    # local caches (paged segments write straight into the slot's pages) —
    # when this term is set, cache/bound_slice/long_cache/prefix_pool are 0.
    # Sized by pages_for_fraction: dense-parity token capacity plus the
    # prefix-cache-fraction alias headroom.
    page_pool_bytes: int = 0
    # multi-LoRA adapter pool (serving/adapters.py): the fixed-shape
    # stacked low-rank factor tree — rows × per-row bytes, resident for
    # the engine's lifetime. Sized by `adapter-pool-fraction`; 0 when no
    # adapters are configured.
    adapter_pool_bytes: int = 0
    # grammar DFA pool (serving/constrain.py): the PACKED planes — the
    # [G+1, S, ceil(V/32)] uint32 legality bitmask plus default-successor
    # [G+1, S] and exception key/next [G+1, E] int32 transition arrays.
    # ~1/28 of the dense [G+1, S, V] int32 table this replaced (~0.7 GiB
    # at a 256k vocab with 4×128; 64 slots now fit in ~0.3 GiB —
    # docs/SERVING.md §15 has the sizing table).
    grammar_pool_bytes: int = 0
    # tiered KV host arena (serving/pagepool.HostPageTier): pinned HOST
    # RAM, not HBM — deliberately excluded from total_bytes (which is the
    # HBM number an over-committed config dies on). Sized by the
    # `host-kv-fraction` knob relative to the device pool; it appears in
    # the plan so the startup log is honest about the process RSS a
    # million-hibernated-sessions config will claim (docs/SERVING.md §16).
    host_spill_bytes: int = 0
    # disaggregated serving (docs/SERVING.md §18): worst-case HOST-RAM
    # staging for one in-flight KV-page migration (one request's page set
    # serialized end-to-end). Host RAM like host_spill_bytes — excluded
    # from the HBM total; 0 on mixed-role replicas.
    migrate_staging_bytes: int = 0
    # streamed weight load (models/streamload.py, docs/SERVING.md §22):
    # the host-RAM staging high-water mark of the shard→device pipeline —
    # the readahead window of per-layer assembly buffers, NOT the ~2×
    # weights the eager path peaks at. HOST RAM like host_spill_bytes;
    # excluded from the HBM total, and transient (released once the last
    # layer uploads) — it appears so the startup log's RSS story covers
    # the load, the phase the pod is being health-probed through.
    weight_load_staging_bytes: int = 0
    # durable session tier (serving/durable.py, docs/SERVING.md §23): the
    # configured on-DISK checkpoint budget (`durable-max-bytes`; 0 with
    # the tier off or uncapped). Neither HBM nor RAM — it appears in the
    # summary so the startup log names every byte tier the engine can
    # touch, and so an operator sizing the durable volume sees the cap
    # they configured next to the arena it checkpoints.
    durable_disk_bytes: int = 0
    # self-speculative verify chunk (engine._verify_chunk): the multi-token
    # forward materializes fp32 logits for ALL k+1 positions of every slot
    # ([B, k+1, V] — k+1 times the decode step's [B, V], which the flat
    # workspace absorbs), and the rejection sampler's FILTER branch
    # (any slot with top-k/top-p) peaks at ~5 such buffers live at once:
    # scaled logits, the descending sort, the rank-masked copy, softmax
    # probs and their cumsum (serving/sampling.py _apply_filters). Charged
    # at 5× — at B=192, k=4, V=256k that is ~4.6 GiB, and a plan that only
    # counted the greedy path would bless configs that OOM on the first
    # sampled request. 0 with speculation off.
    verify_chunk_bytes: int = 0

    @property
    def total_bytes(self) -> int:
        return (
            self.weights_bytes
            + self.cache_bytes
            + self.long_cache_bytes
            + self.workspace_bytes
            + self.scan_buffer_bytes
            + self.bound_slice_bytes
            + self.fused_prefill_bytes
            + self.prefix_pool_bytes
            + self.page_pool_bytes
            + self.verify_chunk_bytes
            + self.adapter_pool_bytes
            + self.grammar_pool_bytes
        )

    def fits(self, hbm_bytes: int) -> bool:
        return self.total_bytes <= hbm_bytes

    def per_chip_bytes(self, devices: int) -> int:
        """First-order per-chip share on a sharded mesh: the plan's trees
        are GLOBAL, and the big terms (weights on model×expert, the dense
        cache / paged pool on model when the kv heads divide) shard across
        the mesh while the workspace allowance replicates per chip.
        Dividing everything except the workspace by the device count is
        the right startup-log read now that the paged pool is legal under
        meshes too (round 13); the achieved-bandwidth gauge does the exact
        per-axis split at runtime (engine._achieved_hbm_gbps)."""
        d = max(1, int(devices))
        return self.workspace_bytes + (self.total_bytes - self.workspace_bytes) // d

    def _agentic_summary(self) -> str:
        gib = 1024**3
        parts = []
        if self.adapter_pool_bytes:
            parts.append(f"adapter-pool {self.adapter_pool_bytes / gib:.2f}GiB + ")
        if self.grammar_pool_bytes:
            parts.append(f"grammar-pool {self.grammar_pool_bytes / gib:.2f}GiB + ")
        return "".join(parts)

    def _weight_load_suffix(self) -> str:
        if not self.weight_load_staging_bytes:
            return ""
        return (
            f" [+ weight-load staging "
            f"{self.weight_load_staging_bytes / 1024**3:.2f}GiB RAM, "
            f"transient]"
        )

    def summary(self) -> str:
        gib = 1024**3
        if self.page_pool_bytes:
            host = (
                f" [+ host KV tier {self.host_spill_bytes / gib:.2f}GiB RAM]"
                if self.host_spill_bytes
                else ""
            )
            if self.migrate_staging_bytes:
                host += (
                    f" [+ migrate staging "
                    f"{self.migrate_staging_bytes / gib:.2f}GiB RAM]"
                )
            if self.durable_disk_bytes:
                host += (
                    f" [+ durable KV tier "
                    f"≤{self.durable_disk_bytes / gib:.2f}GiB disk]"
                )
            host += self._weight_load_suffix()
            return (
                f"weights {self.weights_bytes / gib:.2f}GiB + "
                f"page-pool {self.page_pool_bytes / gib:.2f}GiB "
                f"(+{self.scan_buffer_bytes / gib:.2f}GiB layer slices) + "
                f"fused-prefill {self.fused_prefill_bytes / gib:.2f}GiB + "
                f"verify-chunk {self.verify_chunk_bytes / gib:.2f}GiB + "
                f"{self._agentic_summary()}"
                f"workspace {self.workspace_bytes / gib:.2f}GiB = "
                f"{self.total_bytes / gib:.2f}GiB{host}"
            )
        return (
            f"weights {self.weights_bytes / gib:.2f}GiB + "
            f"cache {self.cache_bytes / gib:.2f}GiB "
            f"(+{self.scan_buffer_bytes / gib:.2f}GiB scan double-buffer, "
            f"+{self.bound_slice_bytes / gib:.2f}GiB kv_bound slice peak) + "
            f"long-prefill {self.long_cache_bytes / gib:.2f}GiB + "
            f"fused-prefill {self.fused_prefill_bytes / gib:.2f}GiB + "
            f"prefix-pool {self.prefix_pool_bytes / gib:.2f}GiB + "
            f"verify-chunk {self.verify_chunk_bytes / gib:.2f}GiB + "
            f"{self._agentic_summary()}"
            f"workspace {self.workspace_bytes / gib:.2f}GiB = "
            f"{self.total_bytes / gib:.2f}GiB"
            f"{self._weight_load_suffix()}"
        )


def largest_sliced_bound(max_seq_len: int) -> int:
    """The widest kv_bound ladder step that actually SLICES the cache: the
    largest power of two strictly below ``max_seq_len``, floored at 64 (the
    ladder's first rung; the full-width program runs unsliced). 0 when the
    cache is too narrow to ever slice."""
    if max_seq_len <= 64:
        return 0
    bound = 64
    while bound * 2 < max_seq_len:
        bound *= 2
    return bound


def plan_serving_memory(
    config: ModelConfig,
    max_batch: int,
    max_seq_len: int,
    *,
    quantized_weights: bool = False,
    long_prefill: bool = True,
    workspace_bytes: int = 1 << 30,
    prefill_batch: int = 0,
    prefill_bucket: int = 0,
    prefill_streams: int = 1,
    prefix_pool_entries: int = 0,
    prefix_pool_width: int = 0,
    speculation_tokens: int = 0,
    kv_layout: str = "dense",
    page_size: int = 64,
    kv_pages: int = 0,
    page_fraction: float = 0.0,
    host_kv_fraction: float = 0.0,
    adapter_pool_rows: int = 0,
    adapter_rank: int = 0,
    grammar_slots: int = 0,
    grammar_states: int = 0,
    grammar_exceptions: int = 65536,
    migrate_staging: bool = False,
    weight_load_staging: int = 0,
    durable_max_bytes: int = 0,
) -> ServingMemoryPlan:
    """Account a ServingEngine's HBM from the actual pytree shapes.

    ``long_prefill``: include the local cache(s) the chunked-prefill /
    ring path holds while a max-length prompt streams in (engine._long_step
    allocates one at the pow2 width covering the prompt, here bounded by
    ``max_seq_len``); ``prefill_streams`` of them may be live at once under
    the fused scheduler. ``prefill_batch``/``prefill_bucket``: shape of the
    admission local cache (prefill_batch rows × the largest bucket width)
    that a fused iteration holds alongside the decode chunk's kv_bound
    slice — 0 omits the term (pre-overlap accounting).
    ``prefix_pool_entries``/``prefix_pool_width``: shape of the prefix
    KV pool (serving/prefix_cache.py) — 0 omits the term (cache off).
    ``speculation_tokens``: drafts per verify iteration (k) when
    self-speculative decoding is on — the verify dispatch holds up to
    ~5 [max_batch, k+1, vocab] fp32 buffers at the sampler's filtered
    peak (see the field note); 0 omits the term (speculation off).
    ``workspace_bytes``: flat allowance for activations, XLA scratch, and
    the collectives' staging buffers — 1GiB is empirically comfortable for
    8B-class decode at B≤96.
    ``kv_layout``: "paged" swaps the dense cache + kv_bound slice +
    long-prefill + prefix-pool terms for ONE page-pool term
    (serving/pagepool.py): ``kv_pages`` pages of ``page_size`` tokens, or
    ``pages_for_fraction(max_batch, max_seq_len, page_size,
    page_fraction)`` when kv_pages is 0.
    ``host_kv_fraction``: tiered-KV host arena pages relative to the
    device pool (``ceil(pages × fraction)``, same per-page bytes) — the
    ``host_spill_bytes`` term is HOST RAM, reported but excluded from the
    HBM total; 0 omits it (tier off, and always 0 under the dense layout).
    ``adapter_pool_rows``/``adapter_rank``: shape of the multi-LoRA device
    pool (serving/adapters.py) — 0 omits the term (no adapters).
    ``grammar_slots``/``grammar_states``/``grammar_exceptions``: shape of
    the constrained-decoding packed DFA pool (serving/constrain.py —
    bitmask + default-successor/exceptions planes) — grammar_slots 0
    omits the term (the shared zero/disabled contract).
    ``weight_load_staging``: measured (or estimated) host-RAM high-water
    mark of the streamed weight-load pipeline (models/streamload.py) —
    reported like host_spill_bytes, excluded from the HBM total; 0 omits
    it (eager load, or no checkpoint).
    ``durable_max_bytes``: configured on-disk cap of the durable session
    tier (serving/durable.py, §23) — disk, reported-only, excluded from
    every RAM/HBM total; 0 omits it (tier off or uncapped).
    """
    from langstream_tpu.models.quant import init_random_quantized_params
    from langstream_tpu.models.transformer import init_params, make_kv_cache

    adapter_bytes = 0
    if adapter_pool_rows > 0 and adapter_rank > 0:
        from langstream_tpu.serving.adapters import lora_pool_bytes

        adapter_bytes = lora_pool_bytes(config, adapter_pool_rows, adapter_rank)
    grammar_bytes = 0
    if grammar_slots > 0 and grammar_states > 0:
        from langstream_tpu.serving.constrain import grammar_pool_bytes

        grammar_bytes = grammar_pool_bytes(
            grammar_slots, grammar_states, config.vocab_size,
            grammar_exceptions,
        )

    paged = kv_layout == "paged"
    if paged:
        from langstream_tpu.models.transformer import make_page_pool
        from langstream_tpu.serving.pagepool import (
            pages_for_fraction,
            table_len_for,
        )

        num_pages = kv_pages or pages_for_fraction(
            max_batch, max_seq_len, page_size, page_fraction
        )
        pool_shape = jax.eval_shape(
            lambda: make_page_pool(config, num_pages, page_size)
        )
        pool_bytes = _tree_bytes(pool_shape)
        host_spill_bytes = 0
        if host_kv_fraction > 0:
            import math

            host_spill_bytes = (
                math.ceil(num_pages * host_kv_fraction)
                * (pool_bytes // max(1, num_pages))
            )
        # disaggregated serving (§18): one in-flight KV migration stages a
        # request's worst-case page set in host RAM on BOTH ends (sender
        # snapshot fetch, receiver frame buffer + decode) — transient, but
        # a plan that ignored it would bless hosts with no headroom for
        # the transfer the role topology exists to make. HOST RAM, like
        # host_spill_bytes; excluded from the HBM total.
        migrate_staging_bytes = 0
        if migrate_staging:
            migrate_staging_bytes = (
                table_len_for(max_seq_len, page_size)
                * (pool_bytes // max(1, num_pages))
            )
        fused_shape = (
            jax.eval_shape(
                lambda: make_kv_cache(
                    config, prefill_batch, min(prefill_bucket, max_seq_len)
                )
            )
            if prefill_batch > 0 and prefill_bucket > 0
            else None
        )
        key = jax.ShapeDtypeStruct((2,), jax.numpy.uint32)
        if quantized_weights:
            params_shape = jax.eval_shape(
                lambda k: init_random_quantized_params(config, k), key
            )
        else:
            params_shape = jax.eval_shape(lambda k: init_params(config, k), key)
        return ServingMemoryPlan(
            weights_bytes=_tree_bytes(params_shape),
            cache_bytes=0,
            long_cache_bytes=0,  # paged segments write straight into pages
            workspace_bytes=workspace_bytes,
            # 2 layer slices (read + updated copy) live inside the step scan
            scan_buffer_bytes=2 * pool_bytes // max(config.n_layers, 1),
            bound_slice_bytes=0,  # the table IS the bound — no slice/splice
            fused_prefill_bytes=_tree_bytes(fused_shape) if fused_shape else 0,
            prefix_pool_bytes=0,  # aliasing shares the one pool
            page_pool_bytes=pool_bytes,
            host_spill_bytes=host_spill_bytes,
            migrate_staging_bytes=migrate_staging_bytes,
            weight_load_staging_bytes=max(0, int(weight_load_staging)),
            durable_disk_bytes=max(0, int(durable_max_bytes)),
            verify_chunk_bytes=(
                5 * max_batch * (speculation_tokens + 1) * config.vocab_size * 4
                if speculation_tokens > 0
                else 0
            ),
            adapter_pool_bytes=adapter_bytes,
            grammar_pool_bytes=grammar_bytes,
        )

    key = jax.ShapeDtypeStruct((2,), jax.numpy.uint32)
    if quantized_weights:
        params_shape = jax.eval_shape(
            lambda k: init_random_quantized_params(config, k), key
        )
    else:
        params_shape = jax.eval_shape(lambda k: init_params(config, k), key)
    cache_shape = jax.eval_shape(
        lambda: make_kv_cache(config, max_batch, max_seq_len)
    )
    long_shape = (
        jax.eval_shape(lambda: make_kv_cache(config, 1, max_seq_len))
        if long_prefill
        else None
    )
    fused_shape = (
        jax.eval_shape(
            lambda: make_kv_cache(
                config, prefill_batch, min(prefill_bucket, max_seq_len)
            )
        )
        if prefill_batch > 0 and prefill_bucket > 0
        else None
    )
    prefix_shape = (
        jax.eval_shape(
            lambda: make_kv_cache(
                config, prefix_pool_entries, min(prefix_pool_width, max_seq_len)
            )
        )
        if prefix_pool_entries > 0 and prefix_pool_width > 0
        else None
    )
    cache_bytes = _tree_bytes(cache_shape)
    sliced = largest_sliced_bound(max_seq_len)
    return ServingMemoryPlan(
        weights_bytes=_tree_bytes(params_shape),
        cache_bytes=cache_bytes,
        long_cache_bytes=(
            _tree_bytes(long_shape) * max(1, prefill_streams)
            if long_shape
            else 0
        ),
        workspace_bytes=workspace_bytes,
        # 2 layer slices (read + updated copy) live inside the chunk scan
        scan_buffer_bytes=2 * cache_bytes // max(config.n_layers, 1),
        # the widest chunk that still slices copies `sliced` of the cache's
        # max_seq_len columns out and back alongside the full cache — for
        # non-pow2 widths that is MORE than cache/2 (T=1536 → 2/3; T=1025 →
        # ~all of it), which the old cache//2 shortcut hid
        bound_slice_bytes=cache_bytes * sliced // max_seq_len if sliced else 0,
        fused_prefill_bytes=_tree_bytes(fused_shape) if fused_shape else 0,
        prefix_pool_bytes=_tree_bytes(prefix_shape) if prefix_shape else 0,
        # ~5 live [B, k+1, V] fp32 buffers at the sampler's filtered peak
        # (see field note)
        verify_chunk_bytes=(
            5 * max_batch * (speculation_tokens + 1) * config.vocab_size * 4
            if speculation_tokens > 0
            else 0
        ),
        adapter_pool_bytes=adapter_bytes,
        grammar_pool_bytes=grammar_bytes,
        weight_load_staging_bytes=max(0, int(weight_load_staging)),
    )


def max_context_single_chip(
    config: ModelConfig,
    max_batch: int,
    hbm_bytes: int,
    *,
    quantized_weights: bool = True,
    ceiling: int = 1 << 20,
) -> int:
    """Largest power-of-two max_seq_len (≥1k) the HBM budget serves, or 0.

    This is the number the llama-3.1 128k preset must be honest about: NTK
    scaling makes 128k *positions* work, but one chip serves only what the
    cache arithmetic allows — shard (tp/seq) for the rest.
    """
    best = 0
    width = 1024
    while width <= min(ceiling, config.max_seq_len):
        plan = plan_serving_memory(
            config, max_batch, width, quantized_weights=quantized_weights
        )
        if not plan.fits(hbm_bytes):
            break
        best = width
        width *= 2
    return best
