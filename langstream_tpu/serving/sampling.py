"""Token sampling: greedy / temperature / top-k / top-p, jittable and batched.

Per-slot sampling params are carried as arrays so one compiled sampler serves
a heterogeneous continuous batch (different temperatures per request).

Perf note (measured on v5e through the device tunnel): a full-vocab sort at
[64, 256000] costs ~25ms — more than the whole gemma-2b transformer step —
so the sort only runs when some slot actually has top-k/top-p enabled
(lax.cond, runtime-gated), and the top-k + top-p cutoffs share ONE sort.
All-greedy batches (the common chat default, temperature=0) reduce to a
single argmax with no gumbel draw.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _greedy_argmax(logits: jax.Array) -> jax.Array:
    """Two-stage argmax over the vocab: per-group MAX first, then the
    argmax within the single winning group. The wide [B, 256k] pass is now
    a pure max reduction — no index tracking at vocab width at all (the
    previous grouped form still ran a full-width argmax to precompute every
    group's within-offset, index math this version defers to ONE gathered
    [B, 128] group). PERF.md's untaken two-stage-argmax lever: ~0.4 ms/step
    on gemma's 256k vocab, now the default for every greedy slot.
    Tie semantics match jnp.argmax exactly (first index wins): the winning
    group is the FIRST group attaining the global max, and the within-group
    argmax picks the first position inside it — the same element a global
    first-index scan lands on.

    Ragged vocabs (GPT-2-family 50257 etc.) pad with -inf columns to the
    next multiple of 128 so the grouped path ALWAYS runs — the old silent
    fallback to the slow single-pass argmax cost exactly the models it was
    meant to serve. -inf pads sit past every real column, so first-index
    tie-breaking never selects one: a pad wins its group only when the
    group is all -inf, and an all--inf row resolves to index 0 the same
    way jnp.argmax does."""
    b, v = logits.shape
    group = 128
    if v % group:
        pad = group - v % group
        logits = jnp.pad(logits, ((0, 0), (0, pad)), constant_values=-jnp.inf)
        v += pad
    grouped = logits.reshape(b, v // group, group)
    maxima = jnp.max(grouped, axis=-1)  # [B, v/group] — pure max, no indices
    top_group = jnp.argmax(maxima, axis=-1)  # [B] first group with the max
    winner = jnp.take_along_axis(
        grouped, top_group[:, None, None], axis=1
    )[:, 0]  # [B, group]
    return top_group * group + jnp.argmax(winner, axis=-1)


def _expand_allowed(allowed: jax.Array, vocab: int) -> jax.Array:
    """Grammar mask → [..., V] bool. Two spellings arrive here:

    - packed ``[..., ceil(V/32)]`` uint32 (serving/constrain.py's
      legality bitmask, LSB-first: token t → bit t % 32 of word t // 32)
      — expanded on device with one shift/AND, so the mask rides HBM at
      1 bit/token and only becomes bytes inside the fused step;
    - legacy ``[..., V]`` bool — passed through untouched.

    The dtype dispatch is a Python branch: dtypes are static under jit,
    so each spelling traces its own (already-distinct-signature) program.
    """
    if allowed.dtype != jnp.uint32:
        return allowed
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (allowed[..., None] >> shifts) & jnp.uint32(1)  # [..., W, 32]
    flat = bits.reshape(*allowed.shape[:-1], allowed.shape[-1] * 32)
    return flat[..., :vocab].astype(bool)


def _apply_filters(s: jax.Array, top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """top-k + top-p cutoffs over [R, V] scaled logits with per-row params
    (0 / 1.0 = disabled); one descending sort serves both. Shared by
    ``sample`` (R = batch) and ``speculative_verify`` (R = batch x draft
    positions) so the two samplers cannot drift apart."""
    v = s.shape[-1]
    sorted_desc = jnp.sort(s, axis=-1)[:, ::-1]
    # top-k: value at rank k-1 (k=0 → keep all → rank v-1)
    k_idx = jnp.clip(jnp.where(top_k > 0, top_k, v) - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
    # top-p on the top-k-masked distribution, masked by rank (equivalent
    # to re-sorting the masked logits: masking keeps a sorted prefix)
    ranks = jnp.arange(v)[None, :]
    sorted_masked = jnp.where(ranks <= k_idx[:, None], sorted_desc, -jnp.inf)
    probs = jax.nn.softmax(sorted_masked, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p[:, None]  # cumulative prob EXCLUSIVE < p
    cutoff = jnp.where(keep, sorted_masked, jnp.inf).min(axis=-1, keepdims=True)
    return jnp.where(s < jnp.maximum(kth, cutoff), -jnp.inf, s)


@functools.partial(jax.jit, static_argnames=())
def sample(
    logits: jax.Array,  # [B, V] fp32
    key: jax.Array,
    temperature: jax.Array,  # [B]
    top_k: jax.Array,  # [B] int32, 0 = disabled
    top_p: jax.Array,  # [B] fp32, 1.0 = disabled
    allowed: jax.Array = None,  # [B, W] uint32 packed / [B, V] bool mask
) -> jax.Array:
    """Returns sampled token ids [B]. temperature 0 → greedy for that slot.

    ``allowed`` (constrained decoding, serving/constrain.py): illegal
    tokens drop to -inf BEFORE the greedy argmax and the top-k/top-p
    filters, so a constrained slot's output is guaranteed inside its
    grammar on both the greedy and sampled paths. The mask lands AFTER the
    NaN guard's finite check — a grammar's own -inf columns must not read
    as a poisoned row (the guard exists for device faults, not masks), and
    the DFA's no-dead-end invariant guarantees at least one True per row
    so the masked softmax stays finite.

    NaN guard: a row whose logits contain any non-finite value (NaN/±inf
    overflow — a numerically-poisoned KV row or a device fault) returns the
    sentinel ``-1`` instead of a token. Sampling from such a row is
    undefined (categorical over NaN probabilities), and silently emitting
    garbage poisons the slot's cache for every later step; the engine
    quarantines the slot on sight of the sentinel (fails that request,
    zeroes its KV rows) while every other slot keeps decoding. +inf alone
    also trips it: softmax over +inf is NaN anyway. The check is one
    vocab-wide AND-reduction — VPU-cheap next to the transformer step,
    unlike the sort this module already gates behind any_filter."""
    b, v = logits.shape
    finite = jnp.all(jnp.isfinite(logits), axis=-1)  # [B]
    if allowed is not None:
        logits = jnp.where(_expand_allowed(allowed, v), logits, -jnp.inf)
    greedy = _greedy_argmax(logits)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    any_sample = jnp.any(temperature > 0.0)
    any_filter = jnp.any((temperature > 0.0) & ((top_k > 0) | (top_p < 1.0)))

    def sampled_branch(s: jax.Array) -> jax.Array:
        filtered = lax.cond(
            any_filter, lambda x: _apply_filters(x, top_k, top_p), lambda x: x, s
        )
        return jax.random.categorical(key, filtered, axis=-1)

    sampled = lax.cond(any_sample, sampled_branch, lambda _: greedy, scaled)
    out = jnp.where(temperature <= 0.0, greedy, sampled)
    return jnp.where(finite, out, -1)


@functools.partial(jax.jit, static_argnames=())
def speculative_verify(
    logits: jax.Array,  # [B, K+1, V] fp32 — per-position next-token logits
    drafts: jax.Array,  # [B, K] int32 — the n-gram drafts being verified
    key: jax.Array,
    temperature: jax.Array,  # [B]
    top_k: jax.Array,  # [B] int32, 0 = disabled
    top_p: jax.Array,  # [B] fp32, 1.0 = disabled
    allowed: jax.Array = None,  # [B, K+1, W] uint32 / [B, K+1, V] bool mask
) -> tuple[jax.Array, jax.Array]:
    """Batched draft verification for self-speculative decoding.

    ``allowed`` (constrained decoding): position j's mask is derived from
    the DFA state AFTER consuming drafts 0..j-1 (the engine ships the
    per-position state ids; serving/constrain.py). Masking the verify
    logits with the SAME per-position masks non-speculative decode would
    apply keeps the exactness invariants under constraints: greedy rows
    accept the longest prefix matching the MASKED argmax chain (an illegal
    draft's -inf logit can never equal the argmax, so it is rejected
    exactly where plain masked decode would have emitted something else),
    and sampled rows rejection-sample against the masked softmax (an
    illegal draft has p(d)=0 → never accepted; corrections/bonus draws
    come from the masked residual) — the emitted marginal is exactly the
    masked p.

    Position j of ``logits`` is the model's next-token distribution after
    consuming verify input j (input 0 = the slot's current token, inputs
    1..K = the drafts), all scored in ONE forward. Returns
    ``(out [B, K+1] int32, accept [B] int32)``: ``accept`` drafts were
    accepted and the emitted tokens are ``out[:, :accept+1]`` — out[:, j]
    equals drafts[:, j] for j < accept, and out[:, accept] is the
    correction (greedy: the argmax the draft failed to match; sampled: a
    residual draw) or, at accept == K, the bonus token from the last
    position. Every verify thus emits between 1 and K+1 tokens per slot.

    Greedy rows (temperature <= 0) accept the longest draft prefix matching
    the argmax chain — token-exact with non-speculative greedy decode by
    construction, since each position's logits condition on exactly the
    accepted prefix. Sampled rows use standard rejection sampling against
    the point-mass draft distribution the n-gram index implies (q(d) = 1):
    accept d with prob min(1, p(d)/q(d)) = p(d); on the first rejection
    resample from the residual norm(max(p - q, 0)) — p with d removed,
    renormalized — so the emitted marginal is exactly p (the lossless
    speculative-sampling identity).

    NaN guard (same contract as ``sample``): a slot with ANY non-finite
    position among its K+1 rows emits the ``-1`` sentinel with accept 0;
    the engine quarantines it on sight.
    """
    b, k1, v = logits.shape
    k = k1 - 1
    finite = jnp.all(jnp.isfinite(logits.reshape(b, -1)), axis=-1)  # [B]
    if allowed is not None:
        logits = jnp.where(_expand_allowed(allowed, v), logits, -jnp.inf)
    greedy = _greedy_argmax(logits.reshape(b * k1, v)).reshape(b, k1)
    greedy_acc = drafts == greedy[:, :k]  # [B, K]

    any_sample = jnp.any(temperature > 0.0)
    any_filter = jnp.any((temperature > 0.0) & ((top_k > 0) | (top_p < 1.0)))

    def sampled_branch(_) -> tuple[jax.Array, jax.Array]:
        temp = jnp.maximum(temperature, 1e-6)[:, None, None]
        flat = (logits / temp).reshape(b * k1, v)
        # per-slot filters repeat across the K+1 positions (one request =
        # one sampling config); the sort is gated exactly like sample()'s
        flat = lax.cond(
            any_filter,
            lambda s: _apply_filters(
                s, jnp.repeat(top_k, k1), jnp.repeat(top_p, k1)
            ),
            lambda s: s,
            flat,
        )
        filtered = flat.reshape(b, k1, v)
        probs = jax.nn.softmax(filtered, axis=-1)
        key_u, key_r = jax.random.split(key)
        u = jax.random.uniform(key_u, (b, k))
        p_draft = jnp.take_along_axis(
            probs[:, :k], drafts[..., None], axis=-1
        )[..., 0]
        acc = u < p_draft  # [B, K]
        # corrections: residual (draft token removed) at positions 0..K-1;
        # position K is the bonus draw — its mask index is out of bounds,
        # so the drop-mode scatter leaves it unfiltered. A correction row
        # is only CONSUMED when its draft was rejected (prob 1 - p(d)), so
        # the all--inf row a p(d)=1 draft would leave can never be read.
        mask_cols = jnp.concatenate(
            [drafts, jnp.full((b, 1), v, jnp.int32)], axis=1
        )
        masked = filtered.at[
            jnp.arange(b)[:, None], jnp.arange(k1)[None, :], mask_cols
        ].set(-jnp.inf, mode="drop")
        corr = jax.random.categorical(key_r, masked, axis=-1)  # [B, K+1]
        return acc, corr

    s_acc, s_corr = lax.cond(
        any_sample, sampled_branch, lambda _: (greedy_acc, greedy), 0
    )
    is_greedy = (temperature <= 0.0)[:, None]
    acc = jnp.where(is_greedy, greedy_acc, s_acc)
    corr = jnp.where(is_greedy, greedy, s_corr)
    # accepted length = longest all-accepted prefix
    accept = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=-1), axis=-1)
    drafts_padded = jnp.concatenate(
        [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1
    )
    positions = jnp.arange(k1)[None, :]
    out = jnp.where(positions < accept[:, None], drafts_padded, corr)
    accept = jnp.where(finite, accept, 0)
    out = jnp.where(finite[:, None], out, -1)
    return out.astype(jnp.int32), accept.astype(jnp.int32)
