"""Token sampling: greedy / temperature / top-k / top-p, jittable and batched.

Per-slot sampling params are carried as arrays so one compiled sampler serves
a heterogeneous continuous batch (different temperatures per request).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=())
def sample(
    logits: jax.Array,  # [B, V] fp32
    key: jax.Array,
    temperature: jax.Array,  # [B]
    top_k: jax.Array,  # [B] int32, 0 = disabled
    top_p: jax.Array,  # [B] fp32, 1.0 = disabled
) -> jax.Array:
    """Returns sampled token ids [B]. temperature 0 → greedy for that slot."""
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k: mask everything below the k-th largest (k=0 → keep all)
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]  # descending
    k_idx = jnp.clip(jnp.where(top_k > 0, top_k, v) - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_logits, k_idx[:, None], axis=-1)
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    # top-p (nucleus): smallest prefix of sorted probs with cumsum ≥ p
    sorted2 = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs_sorted = jax.nn.softmax(sorted2, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    # keep tokens whose cumulative prob (exclusive) < p
    keep_sorted = (cum - probs_sorted) < top_p[:, None]
    cutoff = jnp.where(
        keep_sorted, sorted2, jnp.inf
    ).min(axis=-1, keepdims=True)  # smallest kept logit
    scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)

    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled)
