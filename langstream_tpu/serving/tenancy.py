"""Multi-tenant overload control: fair-share scheduling, per-tenant quotas,
and the brownout degradation ladder (ISSUE 14 / ROADMAP 5c).

One engine serves MANY tenants, and before this module the boundary between
them was a comment: admission was FIFO, the round-6 token-budget scheduler
was tenant-blind, and shedding was global — one tenant's burst inflated
every other tenant's p99. This module makes tenancy a first-class scheduler
input (PAPERS.md "Software-Defined Agentic Serving": per-request policy as a
scheduler input; DeepServe: consolidation only works with ENFORCED
isolation):

- **TenantSpec / TenantRegistry**: per-tenant weight, hard slot cap, queue
  share, and token-rate quota (a token bucket charged for prefill AND
  generated tokens), plus the per-tenant lifecycle counters (shed /
  deadline / cancelled / queue-wait EMA / TTFT histogram) that make the
  noisy-neighbor story observable and testable. Unknown tenants get a
  default spec (weight 1.0, no caps) so tenancy is never a deployment
  prerequisite.

- **TenantQueue**: the engine's bounded admission queue, now per-tenant
  weighted deficit round-robin (DRR, deficits in PREFILL-TOKEN units so the
  iteration's prefill budget — not just request count — divides by weight).
  Work-conserving: an idle tenant's share flows to the busy ones, but a
  bursting tenant can never out-pop its weight while others have queued
  work. Priority (low | normal | high) breaks ties WITHIN a tenant, never
  across tenants — priority is a tenant's own knob, not a fleet-wide
  queue jump. A per-tenant ``queue_share`` caps how much of the bounded
  queue one tenant may occupy, so a burst backpressures (or sheds) the
  burster before it fills the shared queue.

- **BrownoutController**: the graceful-degradation ladder the engine walks
  under sustained load (the round-11 ``load_score`` is the input). Each
  step is hysteresis-gated (enter/exit thresholds + a dwell), counted, and
  fully reversed when load clears:

      level 1  spec-shrink   speculative draft k halves (fewer wasted
                             verify columns at low acceptance under load)
      level 2  spec-off      speculation disabled (every weight read goes
                             to committed tokens)
      level 3  reject-low    low-priority admissions shed at the door
      level 4  reject-quota  over-quota tenants shed at the door

  Decode of already-admitted work is NEVER degraded in correctness: the
  ladder only touches draft proposal counts and admission — the greedy
  speculative path is token-exact with speculation on, shrunk, or off
  (the round-9 invariant), so every delivered stream stays exact at every
  ladder step.

No jax imports: the gateway and the metrics-artifact guards load this
module without building an engine.
"""

from __future__ import annotations

import math
import queue as _queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from langstream_tpu.api.metrics import Histogram, log_buckets

# the record header/property the gateway stamps the langstream tenant id
# into (client-supplied header wins — multi-app front doors may map their
# own identity onto serving tenants) and the completions step reads back
# into GenerationOptions.tenant
TENANT_HEADER = "langstream-tenant"

# requests that never named a tenant all share this one — tenancy must not
# be a deployment prerequisite, and "everything is one tenant" degrades to
# exactly the old FIFO behavior
DEFAULT_TENANT = "default"

PRIORITIES = ("low", "normal", "high")

# shed-reply record properties (docs/SERVING.md §19): when a service-gateway
# request/reply roundtrip hits a quota/overload shed, the completions step
# answers with a reply record carrying these instead of erroring the
# pipeline — the gateway maps them to HTTP 429 + Retry-After
SHED_PROPERTY = "ls-shed"
RETRY_AFTER_PROPERTY = "ls-retry-after-s"

# the service gateway's request/reply correlation header (the same literal
# gateway/server.py stamps — defined here too so the completions step can
# recognize a service roundtrip without importing the gateway layer)
SERVICE_REQUEST_ID_PROPERTY = "langstream-service-request-id"


class TenantShareExceeded(Exception):
    """One tenant's slice of the bounded admission queue is full (its
    configured ``queue_share``); the GLOBAL queue may still have room.
    Always a shed for that tenant — never backpressure for everyone."""

    def __init__(self, tenant: str, cap: int) -> None:
        super().__init__(
            f"tenant {tenant!r} queue share full ({cap} entries)"
        )
        self.tenant = tenant
        self.cap = cap


@dataclass
class TenantSpec:
    """One tenant's declared scheduling policy (the ``tenants:`` config
    block on tpu-serving; docs/SERVING.md §19)."""

    name: str
    # WDRR weight: tenant A at weight 2 gets twice tenant B's share of the
    # iteration prefill-token budget and the free-slot pool under
    # contention. Idle share flows to busy tenants (work-conserving).
    weight: float = 1.0
    # hard cap on concurrently active slots (never borrowed past, even
    # with the engine otherwise idle); None = bounded by fair share only
    max_slots: Optional[int] = None
    # fraction of the bounded admission queue this tenant may occupy
    # (0 < share <= 1); None = bounded by the global depth only
    queue_share: Optional[float] = None
    # sustained token-rate quota (prefill + generated tokens per second,
    # token-bucket enforced); None = unmetered. Over-quota tenants shed
    # FIRST under pressure and outright at brownout level 4.
    token_rate: Optional[float] = None
    # bucket depth in seconds of token_rate (burst headroom)
    burst_s: float = 2.0

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError("tenant spec needs a name")
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, got {self.weight}"
            )
        if self.max_slots is not None and int(self.max_slots) < 1:
            raise ValueError(
                f"tenant {self.name!r}: max_slots must be >= 1"
            )
        if self.queue_share is not None and not (0 < self.queue_share <= 1):
            raise ValueError(
                f"tenant {self.name!r}: queue_share must be in (0, 1], "
                f"got {self.queue_share}"
            )
        if self.token_rate is not None and self.token_rate <= 0:
            raise ValueError(
                f"tenant {self.name!r}: token_rate must be > 0"
            )

    @staticmethod
    def from_dict(d: dict) -> "TenantSpec":
        ms = d.get("max-slots", d.get("max_slots"))
        qs = d.get("queue-share", d.get("queue_share"))
        tr = d.get("token-rate", d.get("token_rate"))
        return TenantSpec(
            name=str(d.get("name") or ""),
            weight=float(d.get("weight", 1.0)),
            max_slots=int(ms) if ms is not None else None,
            queue_share=float(qs) if qs is not None else None,
            token_rate=float(tr) if tr is not None else None,
            burst_s=float(d.get("burst-s", d.get("burst_s", 2.0))),
        )


class _TokenBucket:
    """Token-rate quota enforcement. Charged AFTER the fact (prefill at
    admission, generated tokens as they deliver), so the balance may go
    negative — ``over_quota`` is ``balance <= 0`` and ``retry_after_s`` is
    the time until the refill brings it positive. Not thread-safe on its
    own; the registry lock covers it."""

    def __init__(self, rate: float, burst_s: float) -> None:
        self.rate = float(rate)
        self.burst = max(self.rate * max(burst_s, 0.1), 1.0)
        self._balance = self.burst
        self._at = time.monotonic()

    def _refill(self, now: float) -> None:
        self._balance = min(
            self.burst, self._balance + (now - self._at) * self.rate
        )
        self._at = now

    def charge(self, n: float, now: Optional[float] = None) -> None:
        self._refill(now if now is not None else time.monotonic())
        self._balance -= n

    def balance(self, now: Optional[float] = None) -> float:
        self._refill(now if now is not None else time.monotonic())
        return self._balance

    def over_quota(self, now: Optional[float] = None) -> bool:
        return self.balance(now) <= 0

    def retry_after_s(self, now: Optional[float] = None) -> float:
        deficit = -self.balance(now)
        if deficit <= 0:
            return 0.0
        return max(deficit / self.rate, 0.05)


class TenantState:
    """One tenant's live accounting. Counter mutations go through the
    registry (one lock); the TTFT histogram has exactly ONE writer (the
    engine thread), the api.metrics single-writer contract."""

    def __init__(self, spec: TenantSpec) -> None:
        self.spec = spec
        self.bucket = (
            _TokenBucket(spec.token_rate, spec.burst_s)
            if spec.token_rate is not None
            else None
        )
        self.submitted_total = 0
        self.admitted_total = 0
        self.shed_total = 0
        self.deadline_total = 0
        self.cancelled_total = 0
        self.prefill_tokens_total = 0
        self.generated_tokens_total = 0
        self.queue_wait_ema_s = 0.0
        self.ttft_hist = Histogram(
            f"tenant_ttft_s[{spec.name}]",
            "per-tenant time to first token (s)",
            log_buckets(1e-3, 120.0, 4),
        )


class TenantRegistry:
    """All tenants the engine has seen: configured ones up front, unknown
    ones lazily under a default spec. Thread-safe (submitter threads shed
    and read quota; the engine thread charges and attributes).

    ``max_dynamic`` bounds lazy creation: the tenant name arrives on a
    CLIENT-controlled header, and without a cap a scripted client sending
    a fresh name per request would grow per-tenant state (and every
    stats()/beacon walk) without bound. Past the cap, unseen names fold
    into the shared default tenant — attribution degrades gracefully,
    memory does not."""

    def __init__(
        self,
        specs: Optional[list[TenantSpec]] = None,
        max_dynamic: int = 512,
    ) -> None:
        self._lock = threading.Lock()
        self._states: dict[str, TenantState] = {}
        self.max_dynamic = max(1, int(max_dynamic))
        for spec in specs or []:
            if spec.name in self._states:
                raise ValueError(f"duplicate tenant spec {spec.name!r}")
            self._states[spec.name] = TenantState(spec)
        self._configured = len(self._states)
        self.folded_tenants_total = 0

    def state(self, name: str) -> TenantState:
        name = name or DEFAULT_TENANT
        with self._lock:
            st = self._states.get(name)
            if st is None:
                if (
                    name != DEFAULT_TENANT
                    and len(self._states) - self._configured
                    >= self.max_dynamic
                ):
                    # cap reached: fold into the default tenant instead of
                    # allocating state for a name a hostile client invented
                    self.folded_tenants_total += 1
                    st = self._states.get(DEFAULT_TENANT)
                    if st is None:
                        st = TenantState(TenantSpec(name=DEFAULT_TENANT))
                        self._states[DEFAULT_TENANT] = st
                    return st
                st = TenantState(TenantSpec(name=name))
                self._states[name] = st
            return st

    def weight(self, name: str) -> float:
        return self.state(name).spec.weight

    # -- quota ---------------------------------------------------------------

    def charge(self, name: str, tokens: float) -> None:
        st = self.state(name)
        with self._lock:
            if st.bucket is not None:
                st.bucket.charge(tokens)

    def over_quota(self, name: str) -> bool:
        st = self.state(name)
        with self._lock:
            return st.bucket is not None and st.bucket.over_quota()

    def quota_retry_after_s(self, name: str) -> float:
        st = self.state(name)
        with self._lock:
            return st.bucket.retry_after_s() if st.bucket is not None else 0.0

    # -- attribution ---------------------------------------------------------

    def note_submit(self, name: str) -> None:
        st = self.state(name)
        with self._lock:
            st.submitted_total += 1

    def note_shed(self, name: str) -> None:
        st = self.state(name)
        with self._lock:
            st.shed_total += 1

    def note_deadline(self, name: str) -> None:
        st = self.state(name)
        with self._lock:
            st.deadline_total += 1

    def note_cancelled(self, name: str) -> None:
        st = self.state(name)
        with self._lock:
            st.cancelled_total += 1

    def note_admitted(self, name: str, prefill_tokens: int) -> None:
        st = self.state(name)
        with self._lock:
            st.admitted_total += 1
            st.prefill_tokens_total += prefill_tokens
            if st.bucket is not None:
                st.bucket.charge(prefill_tokens)

    def note_generated(self, name: str, tokens: int = 1) -> None:
        st = self.state(name)
        with self._lock:
            st.generated_tokens_total += tokens
            if st.bucket is not None:
                st.bucket.charge(tokens)

    def note_queue_wait(self, name: str, wait_s: float) -> None:
        st = self.state(name)
        with self._lock:
            st.queue_wait_ema_s = (
                wait_s
                if st.queue_wait_ema_s == 0
                else 0.8 * st.queue_wait_ema_s + 0.2 * wait_s
            )

    def note_ttft(self, name: str, ttft_s: float) -> None:
        # engine thread only (Histogram single-writer contract)
        self.state(name).ttft_hist.record(ttft_s)

    def queue_wait_ema_s(self, name: str) -> float:
        st = self.state(name)
        with self._lock:
            return st.queue_wait_ema_s

    def snapshot(
        self, queued: Optional[dict[str, int]] = None,
        active: Optional[dict[str, int]] = None,
    ) -> dict[str, dict[str, Any]]:
        """Per-tenant stats block (engine stats() → beacons → Grafana).
        Plain-serializable; histograms collapse to their percentiles. ONE
        registry-lock acquisition for the whole pass — this runs on every
        metrics poll and beacon build, interleaved with the engine's
        per-token charges on the same lock; the histogram snapshots take
        their own locks outside it."""
        out: dict[str, dict[str, Any]] = {}
        hists: dict[str, Any] = {}
        with self._lock:
            for name, st in self._states.items():
                hists[name] = st.ttft_hist
                out[name] = {
                    "weight": st.spec.weight,
                    "submitted-total": st.submitted_total,
                    "admitted-total": st.admitted_total,
                    "shed-total": st.shed_total,
                    "deadline-total": st.deadline_total,
                    "cancelled-total": st.cancelled_total,
                    "prefill-tokens-total": st.prefill_tokens_total,
                    "generated-tokens-total": st.generated_tokens_total,
                    "queue-wait-ema-s": round(st.queue_wait_ema_s, 4),
                    "over-quota": (
                        st.bucket is not None and st.bucket.over_quota()
                    ),
                    "queued": int((queued or {}).get(name, 0)),
                    "active-slots": int((active or {}).get(name, 0)),
                }
        for name, h in hists.items():
            snap = h.snapshot()
            out[name]["ttft-p50-s"] = snap["p50"]
            out[name]["ttft-p99-s"] = snap["p99"]
        return out


@dataclass
class _TenantLane:
    """One tenant's slice of the admission queue: a deque per priority
    (FIFO within a priority — priority breaks ties within the tenant)."""

    lanes: dict[str, deque] = field(
        default_factory=lambda: {p: deque() for p in PRIORITIES}
    )
    deficit: float = 0.0

    def __len__(self) -> int:
        return sum(len(d) for d in self.lanes.values())

    def push(self, priority: str, request: Any) -> None:
        self.lanes[priority].append(request)

    def head(self) -> Optional[Any]:
        for p in ("high", "normal", "low"):
            if self.lanes[p]:
                return self.lanes[p][0]
        return None

    def pop(self) -> Any:
        for p in ("high", "normal", "low"):
            if self.lanes[p]:
                return self.lanes[p].popleft()
        raise _queue.Empty


class TenantQueue:
    """Bounded multi-tenant admission queue with weighted deficit
    round-robin pop. Drop-in for the engine's old ``queue.Queue`` surface
    (``maxsize`` / ``qsize()`` / ``put`` / ``put_nowait`` / ``get_nowait``
    raising ``queue.Full`` / ``queue.Empty``), plus:

    - per-tenant ``queue_share`` caps raise :class:`TenantShareExceeded`
      on put (NEVER block — one tenant's burst must not backpressure the
      shared front door);
    - ``get_nowait(skip=...)`` runs DRR over tenants with queued work,
      deficits in the caller's cost units (prefill-token buckets), so the
      iteration's prefill budget divides by weight; ``skip`` lets the
      engine hold back tenants at their slot cap while others drain.

    With one tenant and default priorities this is exactly a FIFO — the
    pre-tenancy behavior, bit for bit.
    """

    def __init__(
        self,
        maxsize: int,
        registry: TenantRegistry,
        cost_fn: Optional[Callable[[Any], float]] = None,
        tenant_fn: Optional[Callable[[Any], str]] = None,
        quantum: float = 2048.0,
    ) -> None:
        self.maxsize = int(maxsize)
        self._registry = registry
        self._cost_fn = cost_fn or (lambda _r: 1.0)
        self._tenant_fn = tenant_fn or (
            lambda r: getattr(getattr(r, "options", None), "tenant", None)
            or DEFAULT_TENANT
        )
        # base DRR quantum: one full round credits a weight-1 tenant
        # enough for one largest-bucket prompt, so weights translate
        # directly into prefill-token share per round
        self.quantum = max(1.0, float(quantum))
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._lanes: dict[str, _TenantLane] = {}
        self._order: deque[str] = deque()  # tenants with queued work, RR
        self._size = 0

    # -- queue.Queue surface --------------------------------------------------

    def qsize(self) -> int:
        with self._lock:
            return self._size

    def depth_by_tenant(self) -> dict[str, int]:
        with self._lock:
            return {t: len(l) for t, l in self._lanes.items() if len(l)}

    def tenants_with_work(self) -> list[str]:
        with self._lock:
            return [t for t in self._order if len(self._lanes[t])]

    def _share_cap(self, tenant: str) -> Optional[int]:
        """The tenant's queue slice, or None when unconfigured (bounded by
        the global depth only — the share check must never fire for a
        tenant that declared no share, or a lone tenant could never fill
        its own queue)."""
        share = self._registry.state(tenant).spec.queue_share
        if share is None:
            return None
        return max(1, int(math.floor(share * self.maxsize)))

    def _put_locked(self, request: Any) -> None:
        tenant = self._tenant_fn(request)
        lane = self._lanes.get(tenant)
        if lane is None:
            lane = self._lanes[tenant] = _TenantLane()
        priority = (
            getattr(getattr(request, "options", None), "priority", None)
            or "normal"
        )
        if priority not in PRIORITIES:
            priority = "normal"
        if len(lane) == 0 and tenant not in self._order:
            self._order.append(tenant)
        lane.push(priority, request)
        self._size += 1

    def put_nowait(self, request: Any) -> None:
        with self._lock:
            tenant = self._tenant_fn(request)
            lane = self._lanes.get(tenant)
            cap = self._share_cap(tenant)
            if cap is not None and lane is not None and len(lane) >= cap:
                raise TenantShareExceeded(tenant, cap)
            if self._size >= self.maxsize:
                raise _queue.Full
            self._put_locked(request)

    def put(self, request: Any, timeout: Optional[float] = None) -> None:
        """Blocking put (shed_policy="block" backpressure) — but ONLY on
        the GLOBAL bound. A tenant at its own share cap sheds immediately
        (TenantShareExceeded): blocking the shared submitter thread on one
        tenant's self-inflicted backlog would be the exact noisy-neighbor
        coupling this queue exists to remove."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        with self._not_full:
            tenant = self._tenant_fn(request)
            while True:
                lane = self._lanes.get(tenant)
                cap = self._share_cap(tenant)
                if cap is not None and lane is not None and len(lane) >= cap:
                    raise TenantShareExceeded(tenant, cap)
                if self._size < self.maxsize:
                    self._put_locked(request)
                    return
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise _queue.Full
                self._not_full.wait(remaining)

    def get_nowait(self, skip: Optional[set] = None) -> Any:
        """WDRR pop. Tenants with queued work hold a running DEFICIT in
        cost units (prefill-token buckets). The pop picks the first tenant
        in round-robin order whose deficit covers its head request's cost
        and rotates it to the back (per-request interleaving); when nobody
        can afford its head, every eligible tenant is credited the SAME
        number of rounds of ``quantum × weight`` — computed in closed form,
        so one call never spins regardless of weight or cost magnitudes —
        and the first affordable tenant pops. Over any busy window each
        tenant's popped cost converges to its weight share, which is
        exactly how the fused iteration's prefill-token budget divides.
        ``skip``: tenants the engine is holding back this iteration (at
        their slot cap while others wait) — their entries stay queued.
        Raises ``queue.Empty`` when nothing (outside ``skip``) is queued."""
        skip = skip or set()
        with self._not_full:
            # drop emptied lanes from the round entirely — deficits reset
            # on empty anyway (standard DRR: no hoarding), and the lane
            # dict must not grow one entry per client-invented tenant name
            while self._order and len(self._lanes[self._order[0]]) == 0:
                del self._lanes[self._order.popleft()]
            eligible = [
                t for t in self._order if len(self._lanes[t]) and t not in skip
            ]
            if not eligible:
                raise _queue.Empty

            def _cost(t: str) -> float:
                return max(1.0, float(self._cost_fn(self._lanes[t].head())))

            def _pop() -> Optional[Any]:
                for t in list(self._order):
                    if t in skip or len(self._lanes[t]) == 0:
                        continue
                    lane = self._lanes[t]
                    c = _cost(t)
                    if lane.deficit >= c:
                        request = lane.pop()
                        lane.deficit -= c
                        self._size -= 1
                        if len(lane) == 0:
                            self._order.remove(t)
                            del self._lanes[t]
                        else:
                            # rotate to the back: the next pop visits the
                            # other tenants first (interleaving)
                            self._order.remove(t)
                            self._order.append(t)
                        self._not_full.notify()
                        return request
                return None

            got = _pop()
            if got is not None:
                return got
            # nobody can afford its head: credit the minimum whole number
            # of rounds that makes SOMEONE affordable (closed form — the
            # deficits advance exactly as if the round-robin had spun)
            rounds = min(
                math.ceil(
                    (_cost(t) - self._lanes[t].deficit)
                    / (self.quantum * self._registry.weight(t))
                )
                for t in eligible
            )
            rounds = max(1, rounds)
            for t in eligible:
                self._lanes[t].deficit += (
                    rounds * self.quantum * self._registry.weight(t)
                )
            got = _pop()
            assert got is not None  # the credited minimum guarantees one
            return got


class BrownoutController:
    """The graceful-degradation ladder (docs/SERVING.md §19). The engine
    feeds it the round-11 ``load_score`` on its own thread; the controller
    answers with the current level and per-step flags. Hysteresis: a step
    ENGAGES only after ``dwell_s`` of load at/above ``enter_load`` since
    the last transition, and RELEASES only after ``dwell_s`` at/below
    ``exit_load`` — one level per dwell in either direction, so a load
    spike walks the ladder gradually and a recovery unwinds it the same
    way (fully: level 0 restores every behavior)."""

    LADDER = ("spec-shrink", "spec-off", "reject-low", "reject-quota")

    def __init__(
        self,
        enter_load: float = 2.0,
        exit_load: float = 1.0,
        dwell_s: float = 0.5,
    ) -> None:
        if exit_load > enter_load:
            raise ValueError(
                f"brownout exit_load ({exit_load}) must be <= enter_load "
                f"({enter_load}) — the hysteresis band"
            )
        self.enter_load = float(enter_load)
        self.exit_load = float(exit_load)
        self.dwell_s = max(0.0, float(dwell_s))
        self.level = 0
        self.transitions_total = 0
        self.engagements = {step: 0 for step in self.LADDER}
        self.last_load = 0.0
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None

    # -- effect flags (cheap reads on the engine hot path) --------------------

    @property
    def spec_off(self) -> bool:
        return self.level >= 2

    @property
    def reject_low(self) -> bool:
        return self.level >= 3

    @property
    def reject_quota(self) -> bool:
        return self.level >= 4

    def draft_k(self, k: int) -> int:
        """Effective speculative draft count at the current level: full k
        at level 0, halved at level 1 (spec-shrink), 0 past that. The
        dispatch SHAPE never changes (drafts are data, not shape), so no
        recompile rides a brownout transition."""
        if self.level >= 2:
            return 0
        if self.level == 1:
            return max(1, k // 2)
        return k

    def observe(self, load: float, now: Optional[float] = None):
        """Advance the ladder one step at most. Returns ``(old, new)`` on
        a transition, None otherwise."""
        now = time.monotonic() if now is None else now
        self.last_load = load
        if load >= self.enter_load:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if (
                self.level < len(self.LADDER)
                and now - self._above_since >= self.dwell_s
            ):
                old = self.level
                self.level += 1
                self.transitions_total += 1
                self.engagements[self.LADDER[self.level - 1]] += 1
                self._above_since = now  # next step needs its own dwell
                return (old, self.level)
        elif load <= self.exit_load:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if self.level > 0 and now - self._below_since >= self.dwell_s:
                old = self.level
                self.level -= 1
                self.transitions_total += 1
                self._below_since = now
                return (old, self.level)
        else:
            # inside the hysteresis band: hold the level, reset both clocks
            self._above_since = None
            self._below_since = None
        return None

    def snapshot(self) -> dict[str, Any]:
        return {
            "level": self.level,
            "step": (
                self.LADDER[self.level - 1] if self.level > 0 else "none"
            ),
            "transitions-total": self.transitions_total,
            "engagements": dict(self.engagements),
            "last-load": round(self.last_load, 4),
        }


def effective_max_new_tokens(options: Any, prompt_len: int) -> int:
    """The request's generation cap with its ``max_cost_tokens`` budget
    applied: cost = prompt + generated, so the budget leaves
    ``max_cost_tokens - prompt_len`` for decode. Callers validate that the
    budget covers at least one generated token at submit."""
    max_new = int(options.max_new_tokens)
    budget = getattr(options, "max_cost_tokens", None)
    if budget is None:
        return max_new
    return max(0, min(max_new, int(budget) - prompt_len))
