"""Engine observability: streaming histograms, request-lifecycle trace
emission, and the flight recorder.

The engine's five interacting fast paths (overlap, prefix aliasing,
speculation, paged pool, fault recovery) used to report averages and
counters only — `stats()` EMAs say nothing about tails, and when a
quarantine or restart fired the evidence of *why* was already gone. This
module is the missing layer (PAPERS.md "DeepServe" and "STREAM" both treat
per-request tail telemetry as the *control signal* for scheduling):

- **Histograms** (`ENGINE_HISTOGRAMS` + `api/metrics.py Histogram`):
  log-spaced fixed-bucket distributions for TTFT, inter-token latency,
  queue wait, prefill/decode dispatch time, accepted-tokens-per-step, and
  fetch latency. The engine owns the live instances; `stats()` snapshots
  them and the completions exporter mirrors them into the Prometheus
  registry (`_bucket`/`_sum`/`_count` on `/metrics`).
- **Load score** (`load_score`): queue-wait p90 + slot occupancy +
  page-pool pressure — the per-engine signal ROADMAP item 3's cache-aware
  balancer routes on. Dimensionally it is seconds + two fractions; it is a
  RELATIVE ordering score across replicas, not a physical quantity.
- **Request spans** (`emit_request_spans`): one `engine.request` span per
  request with `engine.queued` / `engine.prefill` / `engine.decode`
  children, assembled from phase timestamps at completion (one emission
  point — nothing on the token hot loop) and joined to the gateway trace
  via the propagated ``ls-trace-id``.
- **Flight recorder** (`FlightRecorder`): a lock-cheap ring of the last N
  engine iterations (phase timings, batch composition, pages in use,
  compiled-program count, injector firings). Snapshotted and dumped as
  JSON — redacted of token content by construction — whenever a NaN or
  page-integrity quarantine, an engine restart, or a shed burst fires,
  and on demand via `stats(dump=True)`.

No jax imports: tests and the metrics-artifact guards load this module
without building an engine.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Optional

from langstream_tpu.api.metrics import Histogram, log_buckets
from langstream_tpu.tracing import TRACER, Span

log = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# Histogram taxonomy (docs/SERVING.md §12 — names, units, what moves them)
# ---------------------------------------------------------------------------

ENGINE_HISTOGRAMS: dict[str, dict[str, Any]] = {
    "engine_ttft_s": {
        "help": "time to first token, submit to first delivered token (s)",
        "buckets": log_buckets(1e-3, 120.0, 4),
    },
    "engine_intertoken_s": {
        "help": "inter-token latency per slot, consecutive deliveries (s)",
        "buckets": log_buckets(1e-4, 10.0, 4),
    },
    "engine_queue_wait_s": {
        "help": "admission queue wait, submit to queue exit (s)",
        "buckets": log_buckets(1e-4, 120.0, 4),
    },
    "engine_prefill_dispatch_s": {
        "help": "host wall time of one prefill/segment dispatch (s)",
        "buckets": log_buckets(1e-4, 60.0, 4),
    },
    "engine_decode_step_s": {
        "help": "device decode/verify step time, per token step (s)",
        "buckets": log_buckets(1e-5, 10.0, 4),
    },
    "engine_accepted_tokens_per_step": {
        "help": "tokens emitted per slot per verify dispatch (speculation)",
        "buckets": (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0),
    },
    "engine_fetch_s": {
        "help": "device-to-host token fetch latency per chunk (s)",
        "buckets": log_buckets(1e-4, 10.0, 4),
    },
    # tiered KV (docs/SERVING.md §16): spill runs on its dedicated worker
    # thread (device→host copy + arena write + checksum, per entry);
    # restore runs ON the admission path (host→device upload of a
    # hibernated prefix) — its tail is literally added TTFT, which is why
    # it gets its own histogram instead of folding into prefill dispatch
    "engine_spill_s": {
        "help": "host-tier spill (device→host copy + checksum) per entry (s)",
        "buckets": log_buckets(1e-4, 60.0, 4),
    },
    "engine_restore_s": {
        "help": "host-tier restore (host→device page upload) per warm "
                "admission (s)",
        "buckets": log_buckets(1e-4, 60.0, 4),
    },
    # durable session tier (docs/SERVING.md §23): checkpoint runs on the
    # durable worker thread (arena/device bytes → temp+fsync+rename frame
    # stream, per entry); restore runs ON the admission path (disk read +
    # CRC/checksum verify + device upload of a checkpointed prefix) — its
    # tail is added TTFT for a resurrected session, same reasoning as
    # engine_restore_s above, one tier further down
    "engine_durable_checkpoint_s": {
        "help": "durable-tier checkpoint (serialize + fsync + rename) per "
                "entry (s)",
        "buckets": log_buckets(1e-4, 120.0, 4),
    },
    "engine_durable_restore_s": {
        "help": "durable-tier restore (disk read + verify + device "
                "upload) per resurrected admission (s)",
        "buckets": log_buckets(1e-4, 120.0, 4),
    },
    # cold start (docs/SERVING.md §22, ROADMAP 3a): one sample per engine
    # build — checkpoint-to-device wall time of the weight load (streamed
    # pipeline or eager). Sparse by design (engines build once), but the
    # fleet-wide histogram is exactly the scale-up drill's headline: a
    # replica resurrected against a warm compile cache should be weight-
    # load-bound, and this is that bound
    "engine_weight_load_s": {
        "help": "checkpoint→device weight load per engine build, read + "
                "transform + transfer wall (s)",
        "buckets": log_buckets(1e-2, 600.0, 4),
    },
}


# fleet-wire distributions (serving/fleet.py, docs/SERVING.md §17): owned
# by the ROUTER, not the engine — kept here so the genai exporter and the
# metrics-artifact guards share one bucket spec without importing fleet.py
# (which pulls jax via pagepool)
FLEET_HISTOGRAMS: dict[str, dict[str, Any]] = {
    "fleet_hop_s": {
        "help": "remote fleet hop wall time, dispatch to terminal frame "
                "OR hop failure (s) — failed/wedged hops count, so the "
                "tail moves during incidents",
        "buckets": log_buckets(1e-3, 600.0, 4),
    },
    # disaggregated prefill/decode (docs/SERVING.md §18): one sample per
    # attempted KV-page migration, snapshot-to-ACK (or to the failure
    # that triggered the decode-in-place fallback — failed migrations
    # count, so the panel moves during incidents)
    "fleet_migrate_s": {
        "help": "KV-page migration wall time, snapshot dispatch to "
                "receiver ACK or failure (s) — failed migrations count",
        "buckets": log_buckets(1e-4, 120.0, 4),
    },
}


def build_histograms() -> dict[str, Histogram]:
    return {
        name: Histogram(name, spec["help"], spec["buckets"])
        for name, spec in ENGINE_HISTOGRAMS.items()
    }


def load_score(
    queue_wait_p90_s: float, occupancy: float, page_pressure: float
) -> float:
    """Per-engine load score for the (future) cache-aware balancer:
    queue-wait p90 (seconds — the dominant term under real overload) +
    slot occupancy (0..1) + page-pool pressure (0..1). Higher = more
    loaded; compare across replicas, not against a threshold."""
    return round(
        max(0.0, queue_wait_p90_s)
        + min(max(occupancy, 0.0), 1.0)
        + min(max(page_pressure, 0.0), 1.0),
        4,
    )


# ---------------------------------------------------------------------------
# Request-lifecycle spans
# ---------------------------------------------------------------------------


def _span_id() -> str:
    return uuid.uuid4().hex[:16]


def emit_request_spans(
    trace_id: Optional[str],
    stamps: dict[str, float],
    attributes: dict[str, Any],
    status: str = "ok",
) -> Optional[str]:
    """Emit the per-request span tree from monotonic phase ``stamps``
    (``submitted`` required; ``admitted`` / ``first_token`` / ``finished``
    optional — missing phases collapse: a request cancelled in queue gets
    only the root + ``engine.queued``). Returns the trace id used.

    Called ONCE per request at completion, from the engine thread (or the
    expiry sweep) — never from the token delivery loop."""
    if not TRACER.enabled:
        return trace_id
    submitted = stamps.get("submitted")
    if submitted is None:
        return trace_id
    now_mono = time.monotonic()
    finished = stamps.get("finished", now_mono)
    offset = time.time() - now_mono  # monotonic → wall conversion
    trace_id = trace_id or uuid.uuid4().hex[:16]
    root = Span(
        name="engine.request",
        trace_id=trace_id,
        span_id=_span_id(),
        parent_id=None,
        start_s=submitted + offset,
        duration_s=max(0.0, finished - submitted),
        attributes=dict(attributes),
        status=status,
    )
    children: list[Span] = []

    def child(name: str, start: float, end: float, **attrs: Any) -> None:
        children.append(
            Span(
                name=name,
                trace_id=trace_id,
                span_id=_span_id(),
                parent_id=root.span_id,
                start_s=start + offset,
                duration_s=max(0.0, end - start),
                attributes=attrs,
            )
        )

    admitted = stamps.get("admitted")
    first_token = stamps.get("first_token")
    child(
        "engine.queued",
        submitted,
        admitted if admitted is not None else finished,
        slot=attributes.get("slot", -1),
    )
    if admitted is not None:
        child(
            "engine.prefill",
            admitted,
            first_token if first_token is not None else finished,
            slot=attributes.get("slot", -1),
            path=attributes.get("path", ""),
            prefill_chunks=attributes.get("prefill_chunks", 0),
        )
    if first_token is not None:
        child(
            "engine.decode",
            first_token,
            finished,
            slot=attributes.get("slot", -1),
            decode_iterations=attributes.get("decode_iterations", 0),
            verify_dispatches=attributes.get("verify_dispatches", 0),
        )
    # children first so /traces consumers see a complete tree the moment
    # the root appears
    for span in children:
        TRACER.emit(span)
    TRACER.emit(root)
    return trace_id


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

FLIGHT_SCHEMA = "lstpu-flight-v1"

# every ring entry carries at least these (engine._iterate builds them);
# extra keys are allowed, token CONTENT is not (see validate_flight_dump)
ITERATION_FIELDS = (
    "i",        # engine iteration number (monotonic, counts idle too)
    "t",        # wall-clock seconds
    "active",   # active decode slots
    "queued",   # admission queue depth
    "dispatch", # "decode" | "verify" | "" (nothing dispatched)
    "steps",    # decode steps (or k+1 verify width) dispatched
    "kv_pages", # physical pages in use (0 under the dense layout)
    "host_pages", # host-tier arena slots in use (0 with the tier off)
    "programs", # distinct compiled device programs so far
    "phase_ms", # {"sweep","prefill","dispatch","process","spill","restore"}
                # host-wall ms (spill/restore are 0 with the tier off)
)

# token content must never reach a dump: dumps travel to incident channels
_FORBIDDEN_KEYS = frozenset(
    {"tokens", "token", "prompt", "prompt_tokens", "generated", "text",
     "drafts", "value"}
)

DUMP_REASONS = (
    "nan-quarantine", "page-quarantine", "adapter-quarantine",
    "engine-restart", "shed-burst",
    "on-demand",
    # a host-tier restore blocked an admission past the bound (slow host
    # RAM, checksum thrash, or a spill the hit had to wait out) — dumped
    # by the engine's restore path, token-content-free like every reason
    "spill-stall",
    # SPMD leader/follower disagreement (echo mismatch, sequence gap, or a
    # failed replay): dumped on the FOLLOWER, tagged with the ControlBlock
    # seq — on first detection a resync is requested (§20) and the dump is
    # the evidence; a fatal repeat/structural divergence dumps the same
    # reason (debounced per reason like every dump path)
    "spmd-divergence",
    # a replica died mid-STREAM on the fleet wire and the router re-
    # dispatched prompt + delivered tokens to a survivor (docs/SERVING.md
    # §17): dumped by the ROUTER's recorder with the hop's frame TRACE
    # (seq/kind/count metadata, never token content) in extra — its
    # iteration ring is empty because the router runs no engine loop
    "fleet-failover",
    # a KV-page migration between replicas failed (checksum mismatch,
    # wire cut, deadline, receiver pool exhaustion — docs/SERVING.md
    # §18): dumped by the ROUTER with per-phase timings (snapshot /
    # transfer / bind ms) and the fallback taken, never page content
    "migrate-failed",
    # the brownout controller walked the degradation ladder (either
    # direction — docs/SERVING.md §19): dumped with the level, the step
    # name and the load score that drove it, so a postmortem shows WHAT
    # the engine turned off (and back on) under the overload it captured
    "brownout",
    # SPMD slice resilience (docs/SERVING.md §20). spmd-recover: the
    # LEADER entered coordinated recovery — an engine-loop crash answered
    # with OP_RECOVER at a fresh epoch (extra: epoch, error, restart), or
    # a follower divergence report answered with OP_RESYNC (extra: kind
    # "resync", the follower's request). spmd-wedge: the FOLLOWER
    # watchdog detected a silenced leader (no announcement, heartbeats
    # included, within spmd-watchdog-s) and is exiting for a coordinated
    # pod restart — the dump (extra: last-seq, watchdog-s) is the
    # incident artifact a hung slice otherwise never leaves
    "spmd-recover",
    "spmd-wedge",
    # a P2P page fetch from a prefix-owning peer failed (checksum, cut
    # wire, deadline, owner gone — docs/SERVING.md §21): dumped by the
    # ROUTER with the owner/destination ids, the advertised match depth
    # and the fallback taken (local cold prefill), never page content
    "p2p-fetch-failed",
    # a durable-tier restore failed (torn/corrupt checkpoint, stale
    # manifest, missing object, stalled or full volume — docs/SERVING.md
    # §23): dumped by the ENGINE's admit path with the entry digest, the
    # failure and the fallback taken (local cold prefill) — the entry is
    # marked dead so the failure fires once, never page or token content
    "durable-restore-failed",
)

# process-global recent dumps (newest last): the runtime HTTP server's
# /flight endpoint reads this without holding an engine reference. The
# lock covers append AND copy — iterating a deque while another thread
# appends raises, and /flight must not 500 at the exact moment an
# incident produces a dump
RECENT_DUMPS: deque = deque(maxlen=8)
_RECENT_LOCK = threading.Lock()


def recent_dumps() -> list[dict[str, Any]]:
    with _RECENT_LOCK:
        return list(RECENT_DUMPS)


class FlightRecorder:
    """Bounded ring of per-iteration engine records. ``record`` is engine-
    thread-only and lock-cheap (one deque append under a lock); ``dump``
    may be called from any thread (submit-side shed bursts) and is
    debounced per reason so a fault storm produces one artifact, not
    hundreds."""

    # lock discipline registry (analysis pass `locks`): ring, dump
    # sequencing and the shed-burst window are all record/dump
    # cross-thread state.
    _GUARDED = {
        "_lock": ("_ring", "_seq", "_last_dump_t", "_shed_window"),
    }

    def __init__(
        self,
        capacity: int = 256,
        dump_dir: Optional[str] = None,
        min_dump_interval_s: float = 2.0,
    ) -> None:
        self.capacity = max(8, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.dump_dir = dump_dir
        self.min_dump_interval_s = float(min_dump_interval_s)
        self._last_dump_t: dict[str, float] = {}
        self._seq = 0
        self.dumps_total = 0
        self.last_dump: Optional[dict[str, Any]] = None
        # shed-burst detection: sheds within a sliding 1s window
        self._shed_window: deque = deque(maxlen=64)
        self.shed_burst_threshold = 5

    def record(self, entry: dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(entry)

    def iterations(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def note_shed(self) -> bool:
        """Register one shed; True when the 1s sliding window crosses the
        burst threshold (the caller then dumps with reason shed-burst)."""
        now = time.monotonic()
        with self._lock:
            self._shed_window.append(now)
            recent = sum(1 for t in self._shed_window if now - t <= 1.0)
        return recent >= self.shed_burst_threshold

    def dump(
        self,
        reason: str,
        counters: Optional[dict[str, Any]] = None,
        extra: Optional[dict[str, Any]] = None,
        force: bool = False,
    ) -> Optional[dict[str, Any]]:
        """Snapshot the ring into a postmortem artifact. Returns the dump
        dict (also kept as ``last_dump``, appended to ``RECENT_DUMPS`` and
        written under ``dump_dir`` when set), or None when debounced."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_dump_t.get(reason, -1e9) < (
                self.min_dump_interval_s
            ):
                return None
            self._last_dump_t[reason] = now
            iterations = list(self._ring)
            self._seq += 1
            seq = self._seq
        doc: dict[str, Any] = {
            "schema": FLIGHT_SCHEMA,
            "reason": reason,
            "at": round(time.time(), 3),
            "seq": seq,
            "iterations": iterations,
            "counters": dict(counters or {}),
            "extra": dict(extra or {}),
        }
        self.last_dump = doc
        self.dumps_total += 1
        with _RECENT_LOCK:
            RECENT_DUMPS.append(doc)
        if self.dump_dir:
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                path = os.path.join(
                    self.dump_dir, f"flight-{seq:04d}-{reason}.json"
                )
                with open(path, "w") as f:
                    json.dump(doc, f, indent=1)
                log.warning("flight recorder dumped %d iteration(s) to %s "
                            "(reason: %s)", len(iterations), path, reason)
            except OSError:
                log.exception("flight recorder dump write failed")
        else:
            log.warning(
                "flight recorder dumped %d iteration(s) in memory (reason: %s)",
                len(iterations), reason,
            )
        return doc


def _walk_forbidden(obj: Any, path: str) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            if str(k) in _FORBIDDEN_KEYS:
                raise ValueError(
                    f"flight dump carries token-content key {k!r} at {path}"
                )
            _walk_forbidden(v, f"{path}.{k}")
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _walk_forbidden(v, f"{path}[{i}]")


def validate_flight_dump(doc: dict[str, Any]) -> bool:
    """Validate a dump against the documented schema (docs/SERVING.md §12):
    raises ValueError with the first violation, returns True when clean.
    Used by the chaos CI step and the observability tests — the schema IS
    the contract incident tooling parses."""
    if not isinstance(doc, dict):
        raise ValueError("flight dump must be a JSON object")
    if doc.get("schema") != FLIGHT_SCHEMA:
        raise ValueError(f"unknown flight schema {doc.get('schema')!r}")
    if doc.get("reason") not in DUMP_REASONS:
        raise ValueError(f"unknown dump reason {doc.get('reason')!r}")
    if not isinstance(doc.get("at"), (int, float)):
        raise ValueError("dump missing numeric 'at' timestamp")
    iterations = doc.get("iterations")
    if not isinstance(iterations, list):
        raise ValueError("dump missing 'iterations' list")
    for j, entry in enumerate(iterations):
        if not isinstance(entry, dict):
            raise ValueError(f"iteration {j} is not an object")
        for key in ITERATION_FIELDS:
            if key not in entry:
                raise ValueError(f"iteration {j} missing field {key!r}")
    if not isinstance(doc.get("counters"), dict):
        raise ValueError("dump missing 'counters' object")
    _walk_forbidden(doc, "$")
    json.dumps(doc)  # must be plain-serializable end to end
    return True


# ---------------------------------------------------------------------------
# Engine-facing bundle
# ---------------------------------------------------------------------------


class EngineObservability:
    """Everything the engine consults, behind one ``on`` flag so the
    `observability: off` escape hatch (and the overhead bench's off leg)
    is a single branch on the hot paths."""

    def __init__(
        self,
        enabled: bool = True,
        flight_capacity: int = 256,
        flight_dir: Optional[str] = None,
    ) -> None:
        self.on = bool(enabled)
        self.hist: dict[str, Histogram] = build_histograms() if self.on else {}
        self.flight = FlightRecorder(
            capacity=flight_capacity,
            dump_dir=flight_dir
            if flight_dir is not None
            else (os.environ.get("LSTPU_FLIGHT_DIR") or None),
        )

    def record(self, name: str, value: float) -> None:
        h = self.hist.get(name)
        if h is not None:
            h.record(value)

    def histograms(self) -> dict[str, dict[str, Any]]:
        return {name: h.snapshot() for name, h in self.hist.items()}

    def percentile(self, name: str, p: float) -> float:
        h = self.hist.get(name)
        return h.percentile(p) if h is not None else 0.0

    def reset_histograms(self) -> None:
        for h in self.hist.values():
            h.reset()
