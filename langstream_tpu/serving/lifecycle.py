"""Process-wide request-cancellation registry, keyed by client session.

The gateway and the serving engine meet only through topics (questions in,
answers out), so when a websocket client disconnects the reference simply
lets the pipeline finish into the void — and the engine keeps decoding the
orphan to max_new_tokens, burning a KV slot. This registry is the short
circuit for the deployments where both ends live in one process (the local
runner's embedded gateway, the standalone runner + agent pod):

  - the completions step registers every in-flight GenerationRequest under
    the record's ``langstream-client-session-id`` header (the same header
    the chat-gateway examples route answers by),
  - the gateway's ClientDisconnected paths call ``cancel(session_id)``,
  - the engine frees the cancelled slots at the next chunk boundary.

Cross-process topologies (standalone gateway pod, broker-separated agents)
get no cancellation from this — the disconnect event and the engine are in
different processes. That is a documented gap (docs/SERVING.md §9), not a
silent one: the deadline knobs bound orphan decode time there.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Protocol

log = logging.getLogger(__name__)

# the chat-gateway convention header (examples, bench.py GATEWAYS) — the
# gateway resolves it from the client's ?param.sessionId, the completions
# agent sees it as a record property
SESSION_HEADER = "langstream-client-session-id"


class Cancellable(Protocol):
    def cancel(self) -> None: ...


_lock = threading.Lock()
_by_key: dict[str, dict[int, Any]] = {}


def register(key: str, request: Cancellable) -> None:
    """Track ``request`` under session ``key`` until unregister()."""
    if not key:
        return
    with _lock:
        _by_key.setdefault(key, {})[id(request)] = request


def unregister(key: str, request: Cancellable) -> None:
    if not key:
        return
    with _lock:
        bucket = _by_key.get(key)
        if bucket is not None:
            bucket.pop(id(request), None)
            if not bucket:
                _by_key.pop(key, None)


def cancel(key: str) -> int:
    """Cancel every in-flight request registered under ``key``; returns the
    number cancelled. Requests stay registered until their owner
    unregisters (cancellation resolves them through the engine, which is
    what triggers the owner's unregister)."""
    if not key:
        return 0
    with _lock:
        requests = list(_by_key.get(key, {}).values())
    for request in requests:
        try:
            request.cancel()
        except Exception:  # noqa: BLE001 — one bad entry must not shield the rest
            log.exception("cancel() failed for a request under key %r", key)
    if requests:
        log.info("cancelled %d in-flight request(s) for session %r", len(requests), key)
        _trace_disconnect(key, requests)
    return len(requests)


def _trace_disconnect(key: str, requests: list) -> None:
    """Mark the disconnect-driven cancellation on each request's trace —
    an incident reader asking "why did this generation end early?" finds
    the WebSocket disconnect next to the engine's cancelled span instead
    of inferring it from a counter (docs/SERVING.md §12)."""
    try:
        import time as _time
        import uuid as _uuid

        from langstream_tpu.tracing import TRACER, Span

        if not TRACER.enabled:
            return
        for request in requests:
            trace_id = getattr(request, "trace_id", None)
            if not trace_id:
                continue
            TRACER.emit(Span(
                name="gateway.disconnect-cancel",
                trace_id=trace_id,
                span_id=_uuid.uuid4().hex[:16],
                parent_id=None,
                start_s=_time.time(),
                duration_s=0.0,
                attributes={"session": key},
            ))
    except Exception:  # noqa: BLE001 — tracing must never break teardown
        log.exception("disconnect trace emission failed")


def active_keys() -> list[str]:
    """Snapshot of sessions with in-flight requests (tests/debugging)."""
    with _lock:
        return [k for k, v in _by_key.items() if v]
