"""Process-wide request-cancellation registry, keyed by client session.

The gateway and the serving engine meet only through topics (questions in,
answers out), so when a websocket client disconnects the reference simply
lets the pipeline finish into the void — and the engine keeps decoding the
orphan to max_new_tokens, burning a KV slot. This registry is the short
circuit for the deployments where both ends live in one process (the local
runner's embedded gateway, the standalone runner + agent pod):

  - the completions step registers every in-flight GenerationRequest under
    the record's ``langstream-client-session-id`` header (the same header
    the chat-gateway examples route answers by),
  - the gateway's ClientDisconnected paths call ``cancel(session_id)``,
  - the engine frees the cancelled slots at the next chunk boundary.

Cross-process FLEET routes are covered too (ROADMAP 3b): when the fleet
router dispatches a session's request to a REMOTE replica, the completions
step records the owning replica's base URL here (``register_remote``), the
peer's ``engine_generate`` registers the in-flight request in ITS
process-local registry under the same session key, and ``cancel()``
forwards ``POST /fleet/cancel`` to every recorded owner — so a
disconnected client's remote decode dies at the next chunk boundary
instead of at its deadline. Forwarding is best-effort on a background
thread (a dead peer must not stall the gateway's disconnect path); the
deadline knobs remain the backstop for topologies with no runtime HTTP
server between the processes (docs/SERVING.md §9).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Protocol

log = logging.getLogger(__name__)

# the chat-gateway convention header (examples, bench.py GATEWAYS) — the
# gateway resolves it from the client's ?param.sessionId, the completions
# agent sees it as a record property
SESSION_HEADER = "langstream-client-session-id"


class Cancellable(Protocol):
    def cancel(self) -> None: ...


_lock = threading.Lock()
# lock discipline registry (analysis pass `locks`, docs/ANALYSIS.md):
# both registries are written from gateway threads and read from
# cancel/debug paths — every mutation must hold _lock.
_GUARDED = {"_lock": ("_by_key", "_remote_by_key")}
_by_key: dict[str, dict[int, Any]] = {}
# session → {replica base URL: refcount}: which REMOTE replicas currently
# own in-flight work for the session (fleet dispatch). Refcounted — a
# session can have overlapping requests on the same peer.
_remote_by_key: dict[str, dict[str, int]] = {}


def register_remote(key: str, base_url: str) -> None:
    """Record that session ``key`` has an in-flight request on the replica
    at ``base_url`` (the fleet dispatch path). cancel() forwards there."""
    if not key or not base_url:
        return
    with _lock:
        owners = _remote_by_key.setdefault(key, {})
        owners[base_url] = owners.get(base_url, 0) + 1


def unregister_remote(key: str, base_url: str) -> None:
    if not key or not base_url:
        return
    with _lock:
        owners = _remote_by_key.get(key)
        if owners is None:
            return
        left = owners.get(base_url, 0) - 1
        if left > 0:
            owners[base_url] = left
        else:
            owners.pop(base_url, None)
        if not owners:
            _remote_by_key.pop(key, None)


def _forward_cancel(key: str, urls: list[str]) -> None:
    """POST /fleet/cancel to each owning replica. Runs on a daemon thread:
    best-effort — a dead peer's requests die by deadline as before, and
    the gateway's disconnect path must never stall on a peer timeout."""
    import json as _json
    import urllib.error
    import urllib.request

    for url in urls:
        try:
            req = urllib.request.Request(
                url.rstrip("/") + "/fleet/cancel",
                data=_json.dumps({"session": key}).encode("utf-8"),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=2.0) as r:
                out = _json.loads(r.read().decode("utf-8"))
            log.info(
                "forwarded cancel for session %r to %s (%s cancelled there)",
                key, url, out.get("cancelled"),
            )
        except (urllib.error.URLError, OSError, ValueError) as e:
            log.warning(
                "cancel forward to %s failed for session %r: %s "
                "(deadline remains the backstop)", url, key, e,
            )


def register(key: str, request: Cancellable) -> None:
    """Track ``request`` under session ``key`` until unregister()."""
    if not key:
        return
    with _lock:
        _by_key.setdefault(key, {})[id(request)] = request


def unregister(key: str, request: Cancellable) -> None:
    if not key:
        return
    with _lock:
        bucket = _by_key.get(key)
        if bucket is not None:
            bucket.pop(id(request), None)
            if not bucket:
                _by_key.pop(key, None)


def cancel(key: str) -> int:
    """Cancel every in-flight request registered under ``key``; returns the
    number cancelled LOCALLY. Requests stay registered until their owner
    unregisters (cancellation resolves them through the engine, which is
    what triggers the owner's unregister). Sessions whose work was fleet-
    routed to a remote replica additionally get the cancel FORWARDED to
    the owning replica's /fleet/cancel endpoint (background thread,
    best-effort — ROADMAP 3b)."""
    if not key:
        return 0
    with _lock:
        requests = list(_by_key.get(key, {}).values())
        remote_urls = list(_remote_by_key.get(key, {}))
    if remote_urls:
        threading.Thread(
            target=_forward_cancel, args=(key, remote_urls),
            name="fleet-cancel-forward", daemon=True,
        ).start()
    for request in requests:
        try:
            request.cancel()
        except Exception:  # noqa: BLE001 — one bad entry must not shield the rest
            log.exception("cancel() failed for a request under key %r", key)
    if requests:
        log.info("cancelled %d in-flight request(s) for session %r", len(requests), key)
        _trace_disconnect(key, requests)
    return len(requests)


def _trace_disconnect(key: str, requests: list) -> None:
    """Mark the disconnect-driven cancellation on each request's trace —
    an incident reader asking "why did this generation end early?" finds
    the WebSocket disconnect next to the engine's cancelled span instead
    of inferring it from a counter (docs/SERVING.md §12)."""
    try:
        import time as _time
        import uuid as _uuid

        from langstream_tpu.tracing import TRACER, Span

        if not TRACER.enabled:
            return
        for request in requests:
            trace_id = getattr(request, "trace_id", None)
            if not trace_id:
                continue
            TRACER.emit(Span(
                name="gateway.disconnect-cancel",
                trace_id=trace_id,
                span_id=_uuid.uuid4().hex[:16],
                parent_id=None,
                start_s=_time.time(),
                duration_s=0.0,
                attributes={"session": key},
            ))
    except Exception:  # noqa: BLE001 — tracing must never break teardown
        log.exception("disconnect trace emission failed")


def active_keys() -> list[str]:
    """Snapshot of sessions with in-flight requests (tests/debugging)."""
    with _lock:
        return [k for k, v in _by_key.items() if v]
