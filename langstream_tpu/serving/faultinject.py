"""Deterministic fault injection for the serving engine.

The recovery paths in ``serving/engine.py`` (slot quarantine, loop restart
under backoff, shed-on-full-queue, NaN-guard) are only trustworthy if they
can be DRIVEN on demand — a failure story that has never executed is a
comment, not a feature. This module is the driver: a seedable injector the
engine consults at every fault site, so chaos tests (and staging drills via
env vars) replay the exact same fault sequence on every run.

Sites (where the engine asks ``fires(site)``):
  prefill   raise before a batched admission dispatch (fails one group)
  segment   raise before a chunked-prefill segment dispatch (fails a stream)
  decode    raise before a decode-chunk dispatch (crashes the engine loop —
            exercises quarantine + restart-under-backoff)
  nan       corrupt one active slot's fetched tokens to the NaN-guard
            sentinel (exercises per-slot quarantine + KV row reset)
  verify    corrupt one active slot's fetched VERIFY result (self-
            speculative decoding) to the sentinel with accept forced to 0
            — a fault during verification must quarantine only that slot
  page      corrupt one active slot's page-table entry (paged KV layout:
            host bookkeeping / memory corruption drill) — the engine's
            integrity check must quarantine ONLY that slot and free its
            pages back to the pool through the authoritative owned list
  adapter   corrupt one active slot's dispatch-facing adapter row (the
            multi-LoRA gather index, serving/adapters.py) — serving slot X
            with tenant Y's factors is SILENT wrongness, so the engine
            compares the row against its authoritative copy before every
            decode/verify dispatch and must quarantine ONLY the victim
            while every survivor stays token-exact
  spill     corrupt one host-arena page of the entry a hibernation restore
            is about to upload (tiered KV, serving/pagepool.HostPageTier:
            host-RAM-rot drill) — the arena checksum must catch it and the
            victim admission must fall back to a cold re-prefill, token-
            exact, while survivors and the free lists stay untouched
  weight-load  raise from the streamed shard reader as if a safetensors
            shard came up short mid-read (models/streamload.py) — the
            engine build must abort loudly with the shard + tensor named,
            never retry the poisoned bytes, never serve partial weights
  fetch     stall the device→host fetch thread (slow-tunnel simulation)
  client    stall token delivery before the on_token callback (slow-client
            backpressure simulation)

Durable-tier disk sites (serving/durable.py — docs/SERVING.md §23; these
are consulted by the checkpoint store the engine hands its injector to):
  disk-torn     truncate a just-written checkpoint mid-frame (torn write:
                the CRC32 frame prelude must read it as a dead entry)
  disk-corrupt  flip one payload byte under a valid manifest (bit rot:
                the frame CRC / spill-time checksum must catch it)
  disk-stall    sleep ``stall_s`` inside checkpoint/restore (slow or hung
                volume — the restore deadline must fire, never a hang)
  disk-full     raise before any byte is written (ENOSPC simulation)

Network sites (the fleet wire, serving/fleet.py + runtime/http_server.py —
docs/SERVING.md §17; these drive the replica-to-replica streaming
transport, not the engine, and are consulted by the process-wide WIRE
injector ``fleet.set_wire_injector`` / LSTPU_FAULTS):
  net-connect  refuse the hop before it connects (client-side: HttpReplica
               raises ReplicaError as if the peer's socket was refused)
  net-stall    the stream goes silent mid-token (server-side: the handler
               sleeps ``stall_s`` before the next frame — no tokens, no
               heartbeats; the client's idle timeout must distinguish this
               dead-peer signature from ordinary slow decode)
  net-cut      connection reset after N frames (server-side: the handler
               aborts the transport instead of writing the frame — the
               mid-stream death the warm-failover path exists for)
  net-corrupt  malformed frame (server-side: the handler writes a
               non-JSON line in the frame's place — the client's frame
               validation must fail the hop, never deliver garbage)

Spec grammar (comma-separated, e.g. ``"decode@3,nan@5:4,fetch~0.1"``):
  site@N      fire exactly once, on the Nth call to that site (1-based)
  site@N+     fire on every call from the Nth on
  site@N:M    fire on call N, then every M calls after (periodic)
  site~P      fire with probability P per call (seeded RNG → deterministic
              for a given seed + call sequence)

Activation: pass a ``FaultInjector`` to ``ServingEngine(fault_injector=…)``
(tests), or set env vars for a staging drill —
  LSTPU_FAULTS="decode@40:120,nan@77"   the spec
  LSTPU_FAULT_SEED=0                     RNG seed (pinned in CI chaos runs)
  LSTPU_FAULT_STALL_S=0.05               stall duration for fetch/client
The ``tpu-serving`` resource also forwards ``fault-injection`` /
``fault-seed`` / ``fault-stall-s`` config keys (docs/SERVING.md §9).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger(__name__)

SITES = (
    "prefill", "segment", "decode", "nan", "verify", "page", "adapter",
    "spill", "fetch", "client",
    # fleet-wire sites (docs/SERVING.md §17): applied by the streaming
    # transport and the /fleet/generate handler, not the engine
    "net-connect", "net-stall", "net-cut", "net-corrupt",
    # KV-page migration site (docs/SERVING.md §18): corrupt one page
    # payload of an in-flight replica-to-replica migration — the
    # receiver's per-page checksum must catch it, discard the partial
    # bind (no leaked pages), and the sender must RETAIN its copy so the
    # router can fall back to decode-in-place, token-exact
    "migrate",
    # multi-tenant noisy-neighbor site (docs/SERVING.md §19): when it
    # fires, the engine injects a burst of synthetic low-priority
    # admissions under the "chaos-burst" tenant at the iteration top —
    # the deterministic aggressor of the fair-share drill. The victim
    # tenant's streams must stay token-exact with bounded p99 TTFT while
    # the aggressor absorbs ALL the shedding.
    "tenant-burst",
    # SPMD slice-resilience sites (docs/SERVING.md §20). spmd-crash is
    # consulted by the LEADER engine at the iteration top — a raise there
    # is an engine-loop crash under SPMD, driving the coordinated
    # OP_RECOVER drill (both sides rebuild in place, zero process exits).
    # spmd-wedge and spmd-drop are consulted by the CHANNEL at announce
    # time (transport-layer wire loss, the leader believes it announced):
    # wedge silences the leader permanently (the follower watchdog must
    # detect it within the bound and leave a spmd-wedge flight dump);
    # drop loses ONE idle heartbeat, so the next delivered announcement
    # carries the seq gap the divergence-resync path must heal.
    "spmd-crash", "spmd-wedge", "spmd-drop",
    # streamed weight load (models/streamload.py, docs/SERVING.md §22):
    # consulted by the shard reader before each tensor slice — a firing
    # simulates a truncated/corrupt shard read. The load must fail with a
    # WeightLoadError naming the shard file AND the tensor, no partial
    # engine may come up, and the poisoned checkpoint must never be
    # re-read (zero retries — wrong weights are worse than no weights)
    "weight-load",
    # durable-tier disk sites (serving/durable.py, docs/SERVING.md §23):
    # consulted by the checkpoint store around its read/write paths.
    # disk-torn truncates a just-renamed checkpoint mid-frame (the torn
    # write a crash between rename and the last flushed block leaves);
    # disk-corrupt flips one payload byte (bit rot under a valid
    # manifest); disk-stall sleeps stall_s inside checkpoint/restore
    # (slow or hung volume — the restore deadline must fire); disk-full
    # raises before any byte is written (ENOSPC). Every firing must
    # degrade to a local cold prefill with a durable-restore-failed
    # flight dump — dead entries, never wrong KV, never a hang.
    "disk-torn", "disk-corrupt", "disk-stall", "disk-full",
)

# the NaN-guard sentinel sampling.sample() emits for a non-finite logits row;
# the injector writes the same value into fetched tokens so the engine's
# quarantine path is exercised end-to-end without needing to corrupt device
# memory (serving/sampling.py is unit-tested against real NaN logits)
NAN_SENTINEL = -1


class InjectedFault(RuntimeError):
    """Raised at raise-type sites; stands in for an XLA/device error."""


@dataclass
class _Rule:
    """One site's firing schedule."""

    site: str
    at: int = 0  # first firing call number (1-based); 0 = probability mode
    every: int = 0  # 0 = fire once; >0 = period after `at`; -1 = every call from `at`
    prob: float = 0.0

    def fires(self, call_no: int, rng: random.Random) -> bool:
        if self.at == 0:
            return rng.random() < self.prob
        if call_no < self.at:
            return False
        if self.every == -1:
            return True
        if self.every == 0:
            return call_no == self.at
        return (call_no - self.at) % self.every == 0


def _parse_spec(spec: str) -> dict[str, _Rule]:
    rules: dict[str, _Rule] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "~" in part:
            site, _, p = part.partition("~")
            rule = _Rule(site=site.strip(), prob=float(p))
        elif "@" in part:
            site, _, sched = part.partition("@")
            site = site.strip()
            if sched.endswith("+"):
                rule = _Rule(site=site, at=int(sched[:-1]), every=-1)
            elif ":" in sched:
                n, _, m = sched.partition(":")
                rule = _Rule(site=site, at=int(n), every=max(1, int(m)))
            else:
                rule = _Rule(site=site, at=int(sched))
        else:
            raise ValueError(
                f"bad fault spec part {part!r}: expected site@N, site@N+, "
                "site@N:M, or site~P"
            )
        if rule.site not in SITES:
            raise ValueError(
                f"unknown fault site {rule.site!r}; known: {', '.join(SITES)}"
            )
        rules[rule.site] = rule
    return rules


class FaultInjector:
    """Seedable, thread-safe fault schedule. One per engine.

    Call counters are PER SITE and only advance for sites with a rule, so a
    spec targeting ``decode`` leaves every other path byte-identical to a
    fault-free run — the survivor-token-exactness property the chaos suite
    asserts."""

    def __init__(self, spec: str, seed: int = 0, stall_s: float = 0.05) -> None:
        self.spec = spec
        self.seed = seed
        self.stall_s = stall_s
        self._rules = _parse_spec(spec)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._calls: dict[str, int] = {s: 0 for s in self._rules}
        self.fired: dict[str, int] = {s: 0 for s in self._rules}
        # recent firings (site, call number, wall time) — the flight
        # recorder folds these into its dumps so a postmortem shows WHICH
        # injected fault preceded the quarantine/restart it captured
        from collections import deque

        self.events: "deque[dict]" = deque(maxlen=32)

    @classmethod
    def from_env(cls, env=os.environ) -> Optional["FaultInjector"]:
        spec = env.get("LSTPU_FAULTS", "").strip()
        if not spec:
            return None
        return cls(
            spec,
            seed=int(env.get("LSTPU_FAULT_SEED", "0")),
            stall_s=float(env.get("LSTPU_FAULT_STALL_S", "0.05")),
        )

    def fires(self, site: str) -> bool:
        rule = self._rules.get(site)
        if rule is None:
            return False
        with self._lock:
            self._calls[site] += 1
            hit = rule.fires(self._calls[site], self._rng)
            if hit:
                self.fired[site] += 1
                self.events.append({
                    "site": site,
                    "call": self._calls[site],
                    "t": round(time.time(), 3),
                })
                log.warning(
                    "fault injection: %s fires (call %d, total %d)",
                    site, self._calls[site], self.fired[site],
                )
            return hit

    def fire(self, site: str) -> None:
        """Raise-type sites: raise InjectedFault on schedule."""
        if self.fires(site):
            raise InjectedFault(
                f"injected {site} fault #{self.fired[site]} (spec {self.spec!r})"
            )

    def stall(self, site: str) -> None:
        """Stall-type sites: sleep on schedule."""
        if self.fires(site):
            time.sleep(self.stall_s)

    def corrupt_tokens(self, host, snapshot):
        """``nan`` site: overwrite one active slot's tokens in a fetched
        [steps, B] chunk with the NaN-guard sentinel, exactly as if
        sampling's non-finite guard had tripped on device for that slot.
        The victim is drawn from the seeded RNG over the chunk's snapshot
        (deterministic for a pinned seed). Returns ``(host, victim)`` —
        ``host`` is a writable copy when the site fires (device fetches can
        be read-only), the original array otherwise (victim None)."""
        import numpy as np

        if not snapshot or not self.fires("nan"):
            return host, None
        with self._lock:
            victim = snapshot[self._rng.randrange(len(snapshot))][0]
        host = np.array(host)
        host[:, victim] = NAN_SENTINEL
        return host, victim

    def corrupt_verify(self, packed, snapshot):
        """``verify`` site: corrupt one active slot's row of a fetched
        verify result (``[B, k+2]`` = emitted tokens ++ accepted count) so
        the slot's first delivered token is the NaN-guard sentinel with
        accept forced to 0 — exactly what speculative_verify emits when a
        device fault poisons that slot's logits mid-verification. The
        engine's quarantine path then runs end-to-end for ONE slot while
        every other slot's accepted tokens deliver untouched. Victim drawn
        from the seeded RNG; returns a writable copy when the site fires,
        the original array otherwise."""
        import numpy as np

        if not snapshot or not self.fires("verify"):
            return packed
        with self._lock:
            victim = snapshot[self._rng.randrange(len(snapshot))][0]
        packed = np.array(packed)
        packed[victim, 0] = NAN_SENTINEL  # first emitted token → sentinel
        packed[victim, -1] = 0  # accept 0 → the sentinel is delivered first
        return packed

    def corrupt_adapter_rows(self, rows, snapshot):
        """``adapter`` site: bump one active slot's entry in the engine's
        dispatch-facing adapter-row array, leaving the authoritative copy
        intact — the host-corruption drill for the multi-LoRA gather
        index. The engine's pre-dispatch integrity check must catch the
        mismatch and quarantine only that slot. Victim drawn from the
        seeded RNG; returns the victim slot or None."""
        if not snapshot or not self.fires("adapter"):
            return None
        with self._lock:
            victim = snapshot[self._rng.randrange(len(snapshot))][0]
            rows[victim] = rows[victim] + 1  # any mismatch will do
        return victim

    def corrupt_page_table(self, pool, snapshot):
        """``page`` site: scramble one active slot's page-table entry in
        the HOST table array (the device-facing derivation), leaving the
        allocator's authoritative owned list intact — exactly the class of
        bug/corruption the engine's pre-dispatch integrity check exists to
        catch. Victim drawn from the seeded RNG over the active snapshot;
        returns the victim slot or None."""
        if not snapshot or not self.fires("page"):
            return None
        with self._lock:
            victim = snapshot[self._rng.randrange(len(snapshot))][0]
            # point the slot's first mapped entry somewhere else entirely
            pool.tables[victim, 0] = (pool.tables[victim, 0] + 1) % pool.num_pages
        return victim

    def corrupt_migration_frame(self, frame):
        """``migrate`` site: flip bytes of one page payload of an
        in-flight KV migration (serving/migrate.py) — the wire-corruption
        drill for the replica-to-replica transfer. The frame's stamped
        checksum is left INTACT while the payload is damaged, so the
        receiver's per-page verification must catch the mismatch and
        abort the bind. Returns True when the site fired (the frame was
        mutated in place)."""
        if frame.get("kind") != "page" or not self.fires("migrate"):
            return False
        raw = frame.get("raw")
        if raw:
            # v2 binary payload (serving/wire.py): flip the first raw
            # byte — same bit-rot class, same checksum-must-catch-it
            # contract as the base64 branch below
            damaged = bytearray(raw)
            damaged[0] ^= 0xFF
            frame["raw"] = bytes(damaged)
            return True
        data = frame.get("data") or []
        if not data or not data[0]:
            return False
        # flip the first base64 character to a DIFFERENT valid one: the
        # payload still decodes (same length, same charset) but its bytes
        # differ — exactly the bit-rot-in-flight class the per-page
        # checksum exists to catch, exercised through the verify path
        # rather than the cheaper undecodable-garbage path
        first = data[0][0]
        data[0] = ("A" if first != "A" else "B") + data[0][1:]
        return True

    def corrupt_host_page(self, tier, slots):
        """``spill`` site: flip one byte of one arena slot the restore is
        about to read (drawn from the seeded RNG over the entry's slots) —
        the host-memory-rot drill for the tiered-KV path. The tier's
        checksum verification must catch it and the engine must degrade
        the hit to a cold re-prefill, never serve the poisoned KV.
        Returns the corrupted slot or None."""
        if tier is None or not slots or not self.fires("spill"):
            return None
        with self._lock:
            victim = slots[self._rng.randrange(len(slots))]
        tier.corrupt(victim)
        return victim

    def events_snapshot(self) -> list[dict]:
        """Copy of the recent-firings ring, taken under the injector lock —
        iterating the deque lock-free races fires() appends from the
        engine/fetch threads (deque mutation during iteration raises)."""
        with self._lock:
            return list(self.events)

    def stats(self) -> dict[str, int]:
        return dict(self.fired)
