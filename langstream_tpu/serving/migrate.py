"""KV-page migration over the fleet wire (``lstpu-kvmig-v1``).

Disaggregated prefill/decode (ROADMAP item 2, DeepServe — PAPERS.md
arxiv 2501.14417) needs exactly one new data-plane op: move a published
prefix's KV PAGES from the replica that prefilled them to the replica
that will decode against them. This module is that op, engineered so the
transfer can fail at ANY byte and the request still completes with
correct tokens (STREAM's integrity-checked inter-tier transfer stance,
arxiv 2606.13968, extended from the host-RAM tier to the wire):

- **Frames** (newline-delimited JSON, one monotone ``seq`` per frame):

  ``begin``   prefix length + digest + page count/geometry + the prefix
              TOKENS (data plane, like the /fleet/generate payload — the
              receiver's radix trie is keyed by tokens; beacons and
              flight dumps stay digest-only as ever)
  ``page``    one pool page: base64 leaf blocks (``jax.tree.leaves``
              order — int8 pools ship int8 + scales, half the bytes of
              bf16) + the blake2b-16 checksum ``pagepool.page_checksum``
              stamps. Hibernated sessions ship their host-arena bytes
              with the checksum STORED at spill time — recomputing would
              launder rot the arena already caught.
  ``commit``  terminal: pages_sent + the decode-resume state (sequence
              position, sampling echo, grammar key + host-mirrored DFA
              state when the session is mid-derivation)

- **Discipline**: the receiver binds pages into its own pool only behind
  the per-page checksum (a mismatch aborts with NOTHING allocated), the
  sender frees its copy ONLY on the receiver's ACK, the receiver frees
  ONLY on abort — both free lists are leak-asserted by the chaos suite.
  The ``migrate`` fault site corrupts a page payload in flight; ``net-cut``
  fired against the migration aborts it between frames. Either way the
  router falls back (decode-in-place / re-prefill) and the request stays
  token-exact for greedy sampling.

Transport: in-process transfer is a plain generator handoff; the HTTP
hop POSTs the frames chunked to the receiver's ``POST /fleet/migrate``
(runtime/http_server.py) and reads the ACK JSON. docs/SERVING.md §18.
"""

from __future__ import annotations

import base64
import json
import logging
import time
from typing import Any, Iterator, Optional

import numpy as np

log = logging.getLogger(__name__)

MIG_SCHEMA = "lstpu-kvmig-v1"


class MigrationError(RuntimeError):
    """A KV-page migration failed (checksum mismatch, cut wire, pool
    exhaustion, deadline). Callers fall back — decode-in-place on the
    sender or a cold re-prefill on the receiver — and the sender RETAINS
    its pages; this error never implies lost KV."""


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode("ascii")


def export_frames(
    engine: Any,
    tokens,
    timeout_s: float = 30.0,
    state: Optional[dict] = None,
    phases: Optional[dict] = None,
    raw: bool = False,
) -> Iterator[dict]:
    """Serialize the deepest published prefix covering ``tokens`` into
    migration frames. The snapshot happens EAGERLY (before the first
    frame yields) so a no-prefix/dead-engine failure raises here, while
    the caller can still choose a fallback instead of aborting a
    half-sent stream. The wire injector's ``migrate`` site corrupts one
    page payload in flight; ``net-cut`` aborts between frames —
    both leave the sender's copy intact (release happens only on ACK,
    outside this generator).

    ``raw=True`` ships page payloads as one contiguous native-width byte
    field per frame (``raw``, the lstpu-kvmig-v2 data plane — no base64
    tax) instead of the v1 ``data`` base64 list; checksums and frame
    discipline are identical either way."""
    from langstream_tpu.serving.fleet import wire_injector
    from langstream_tpu.serving.pagepool import join_page_bytes

    tokens = [int(t) for t in tokens]
    t0 = time.monotonic()
    snap = engine.migrate_snapshot(tokens, timeout_s=timeout_s)
    if phases is not None:
        phases["snapshot_ms"] = round((time.monotonic() - t0) * 1e3, 3)
        phases["tier"] = snap["tier"]
    injector = wire_injector()

    def frames() -> Iterator[dict]:
        n = len(snap["blocks"])
        yield {
            "v": MIG_SCHEMA, "seq": 0, "kind": "begin",
            "length": int(snap["length"]),
            "digest": snap["digest"],
            "pages": n,
            "page_size": int(snap["page_size"]),
            "bytes_per_page": int(snap["bytes_per_page"]),
            "tier": snap["tier"],
            "prompt_tokens": tokens[: int(snap["length"])],
        }
        for i, (leaves, checksum) in enumerate(
            zip(snap["blocks"], snap["checksums"])
        ):
            if injector is not None and injector.fires("net-cut"):
                raise MigrationError(
                    f"injected net-cut after {i} of {n} page frame(s)"
                )
            frame = {
                "seq": i + 1, "kind": "page", "i": i,
                "checksum": checksum.hex(),
            }
            if raw:
                frame["raw"] = join_page_bytes(leaves)
            else:
                frame["data"] = [_b64(leaf) for leaf in leaves]
            if injector is not None:
                injector.corrupt_migration_frame(frame)
            yield frame
        yield {
            "seq": n + 1, "kind": "commit", "pages_sent": n,
            "state": dict(state or {}, position=int(snap["length"])),
        }

    return frames()


def _leaf_specs(engine: Any) -> list[tuple[tuple, Any]]:
    """Per-leaf (page_shape, dtype) of the receiver's pool — what one
    serialized page must decode to. Static attributes only: safe to read
    off the engine thread."""
    import jax

    return [
        ((leaf.shape[0],) + tuple(leaf.shape[2:]), leaf.dtype)
        for leaf in jax.tree.leaves(engine._pagepool.dev)  # noqa: SLF001
    ]


def bind_frames(
    engine: Any, frames: Iterator[dict], timeout_s: float = 30.0,
) -> dict:
    """Receiver side: validate + checksum every page frame, then bind the
    pages into ``engine``'s pool and prefix index. ALL verification
    happens before anything is allocated — a cut stream, a corrupt
    payload, or a checksum mismatch aborts with the receiver's free list
    untouched. Returns the ACK dict the sender frees against.

    Accepts BOTH codecs' frame dicts: v1 pages carry a base64 ``data``
    list, v2 pages one contiguous ``raw`` byte field split against this
    pool's leaf layout — checksum discipline is identical either way
    (the §17/§18 chaos semantics hold on both wires)."""
    from langstream_tpu.serving.pagepool import page_checksum, split_page_bytes
    from langstream_tpu.serving.wire import MIG_SCHEMA_V2

    deadline = time.monotonic() + max(0.05, timeout_s)
    t0 = time.monotonic()
    begin: Optional[dict] = None
    blocks: list[list[np.ndarray]] = []
    specs = None
    expected_seq = 0
    try:
        for frame in frames:
            if time.monotonic() > deadline:
                raise MigrationError(
                    f"migration exceeded its {timeout_s:.1f}s budget "
                    f"after {len(blocks)} page(s)"
                )
            if not isinstance(frame, dict) or frame.get("seq") != expected_seq:
                got = frame.get("seq") if isinstance(frame, dict) else None
                raise MigrationError(
                    f"migration sequence broken (got {got!r}, want "
                    f"{expected_seq})"
                )
            expected_seq += 1
            kind = frame.get("kind")
            if kind == "begin":
                if frame.get("v") not in (MIG_SCHEMA, MIG_SCHEMA_V2):
                    raise MigrationError(
                        f"unknown migration schema {frame.get('v')!r}"
                    )
                begin = frame
                specs = _leaf_specs(engine)
            elif kind == "page":
                if begin is None:
                    raise MigrationError("page frame before begin")
                page = []
                try:
                    if frame.get("raw") is not None:
                        page = split_page_bytes(bytes(frame["raw"]), specs)
                    else:
                        for (shape, dtype), b64 in zip(
                            specs, frame.get("data") or []
                        ):
                            raw = base64.b64decode(b64, validate=True)
                            arr = np.frombuffer(raw, dtype=dtype)
                            page.append(arr.reshape(shape))
                    want = bytes.fromhex(str(frame.get("checksum") or ""))
                except (ValueError, TypeError) as e:
                    raise MigrationError(
                        f"page {frame.get('i')} payload undecodable ({e})"
                    ) from e
                if len(page) != len(specs):
                    raise MigrationError(
                        f"page {frame.get('i')} carries {len(page)} leaf "
                        f"blocks; this pool has {len(specs)}"
                    )
                if page_checksum(page) != want:
                    raise MigrationError(
                        f"page {frame.get('i')} failed its checksum — "
                        "discarding the migration (sender retains)"
                    )
                blocks.append(page)
            elif kind == "commit":
                if begin is None:
                    raise MigrationError("commit frame before begin")
                if len(blocks) != int(begin.get("pages") or -1) or (
                    len(blocks) != int(frame.get("pages_sent") or -1)
                ):
                    raise MigrationError(
                        f"commit count mismatch: {len(blocks)} received, "
                        f"begin said {begin.get('pages')}, commit said "
                        f"{frame.get('pages_sent')}"
                    )
                remaining = max(0.05, deadline - time.monotonic())
                ack = engine.migrate_bind(
                    [int(t) for t in begin["prompt_tokens"]],
                    int(begin["length"]),
                    blocks,
                    timeout_s=remaining,
                )
                return {
                    "ok": True,
                    "length": int(begin["length"]),
                    "digest": str(begin.get("digest") or ""),
                    "pages": int(ack.get("pages", 0)),
                    "bytes": int(ack.get("bytes", 0)),
                    "already": bool(ack.get("already", False)),
                    "state": dict(frame.get("state") or {}),
                    "bind_ms": round((time.monotonic() - t0) * 1e3, 3),
                }
            else:
                raise MigrationError(f"unknown migration frame {kind!r}")
    finally:
        close = getattr(frames, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 — abort path must not mask
                log.exception("migration frame close failed")
    raise MigrationError(
        f"migration stream ended after {len(blocks)} page(s) without a "
        "commit frame (cut wire) — nothing was bound"
    )


def transfer(
    src_engine: Any,
    dst_engine: Any,
    tokens,
    timeout_s: float = 30.0,
    state: Optional[dict] = None,
    phases: Optional[dict] = None,
) -> dict:
    """In-process migration: export from ``src_engine``, bind into
    ``dst_engine``, release the source copy on ACK. Raises MigrationError
    with the sender intact on any failure."""
    phases = phases if phases is not None else {}
    frames = export_frames(
        src_engine, tokens, timeout_s=timeout_s, state=state, phases=phases,
    )
    ack = bind_frames(dst_engine, frames, timeout_s=timeout_s)
    _release_on_ack(src_engine, tokens, ack)
    return ack


def _release_on_ack(src_engine: Any, tokens, ack: dict) -> None:
    """Sender frees ONLY on ACK; a failed release is benign (the entry
    stays for LRU) and must never fail a migration that already landed."""
    try:
        src_engine.migrate_release(tokens, int(ack["length"]))
    except Exception as e:  # noqa: BLE001 — ack'd migration stands
        log.warning("post-ACK migration release failed (retained): %s", e)


def push_migration(
    url: str, frames: Iterator[dict], timeout_s: float, wire: str = "v1",
) -> dict:
    """HTTP sender: POST the frame stream chunked to the receiver's
    ``POST /fleet/migrate`` and return its ACK. Any transport failure —
    refused connect, reset mid-body, non-JSON ACK — is a MigrationError;
    the caller's release-on-ACK discipline keeps the sender's copy.

    ``wire`` picks the codec: ``"v1"`` ships the frames as NDJSON lines
    (byte-identical to the pre-v2 wire — the legacy-peer fallback),
    ``"v2"`` ships the lstpu-kvmig-v2 binary body (preamble + framed
    records; pair with ``export_frames(raw=True)`` so page payloads skip
    the base64 round-trip entirely). The caller negotiates via the
    receiver's ``kvmig2`` beacon cap (docs/SERVING.md §21)."""
    import http.client
    import urllib.parse

    from langstream_tpu.serving import wire as wire_mod

    u = urllib.parse.urlsplit(url)
    if u.scheme != "http" or not u.hostname:
        raise MigrationError(f"unsupported migration receiver url {url!r}")
    v2 = wire == "v2"

    def body() -> Iterator[bytes]:
        if v2:
            wire_mod.count_wire_bytes("v2", len(wire_mod.KVMIG2_PREAMBLE))
            yield wire_mod.KVMIG2_PREAMBLE
        for frame in frames:
            if v2:
                chunk = wire_mod.encode_mig_frame(frame)
            else:
                chunk = (json.dumps(frame) + "\n").encode("utf-8")
            wire_mod.count_wire_bytes("v2" if v2 else "v1", len(chunk))
            yield chunk

    content_type = (
        "application/x-lstpu-kvmig2" if v2 else "application/x-ndjson"
    )
    conn = http.client.HTTPConnection(
        u.hostname, u.port or 80, timeout=max(0.05, timeout_s)
    )
    try:
        try:
            # the receiver binds under the SENDER's budget (clamped by the
            # handler): without this a raised fleet-migrate-timeout-s
            # would bound only the push while the bind still died at the
            # receiver's default
            conn.request(
                "POST",
                f"/fleet/migrate?timeout-s={max(0.05, timeout_s):.3f}",
                body=body(),
                headers={"Content-Type": content_type},
                encode_chunked=True,
            )
            resp = conn.getresponse()
            raw = resp.read()
        except MigrationError:
            raise
        except Exception as e:  # noqa: BLE001 — one verdict: hop failed
            raise MigrationError(f"migration push to {url} failed: {e}") from e
        if resp.status != 200:
            raise MigrationError(
                f"migration receiver {url} answered HTTP {resp.status}: "
                f"{raw[:200]!r}"
            )
        try:
            ack = json.loads(raw.decode("utf-8"))
        except ValueError as e:
            raise MigrationError(
                f"migration receiver {url} sent a non-JSON ACK"
            ) from e
        if not ack.get("ok"):
            raise MigrationError(
                f"migration receiver {url} rejected the transfer: "
                f"{ack.get('error')!r}"
            )
        return ack
    finally:
        conn.close()
        close = getattr(frames, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001
                log.exception("migration frame close failed")


def fetch_pages(
    url: str, tokens, timeout_s: float, wire: str = "v2",
) -> Iterator[dict]:
    """Peer-to-peer page fetch client (ROADMAP 2a, docs/SERVING.md §21):
    POST the owning peer's ``/fleet/pages`` and return an iterator of
    migration frames covering the deepest published prefix of ``tokens``
    — the same frames ``bind_frames`` consumes, so the fetch admits warm
    through the one checksum-verified bind path. The owner KEEPS its
    pages (a fetch copies; only a migration moves).

    ``wire`` asks for the codec (``"v2"`` binary when the owner
    advertises ``kvmig2``, ``"v1"`` NDJSON otherwise); the response's
    content type is authoritative. A pre-stream failure on the owner (no
    published prefix, dead engine) answers a JSON error body — raised
    here as MigrationError, like every transport/codec failure, so the
    caller's ladder degrades to the local cold path."""
    import http.client
    import urllib.parse

    from langstream_tpu.serving import wire as wire_mod

    u = urllib.parse.urlsplit(url)
    if u.scheme != "http" or not u.hostname:
        raise MigrationError(f"unsupported page-fetch source url {url!r}")
    body = json.dumps({
        "prompt_tokens": [int(t) for t in tokens],
        "timeout-s": max(0.05, float(timeout_s)),
        "wire": "v2" if wire == "v2" else "v1",
    }).encode("utf-8")
    conn = http.client.HTTPConnection(
        u.hostname, u.port or 80, timeout=max(0.05, timeout_s)
    )
    try:
        conn.request(
            "POST", "/fleet/pages", body=body,
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
    except Exception as e:  # noqa: BLE001 — one verdict: no pages fetched
        conn.close()
        raise MigrationError(f"page fetch from {url} failed: {e}") from e
    ctype = str(resp.getheader("Content-Type") or "")
    if resp.status != 200 or "json" in ctype:
        # pre-stream refusal: the owner answered a JSON error document
        # instead of committing to a frame stream
        try:
            raw = resp.read()
        finally:
            conn.close()
        if resp.status != 200:
            raise MigrationError(
                f"page-fetch source {url} answered HTTP {resp.status}: "
                f"{raw[:200]!r}"
            )
        try:
            doc = json.loads(raw.decode("utf-8"))
        except ValueError:
            doc = {}
        raise MigrationError(
            f"page-fetch source {url} refused: {doc.get('error')!r}"
        )

    def frames() -> Iterator[dict]:
        try:
            if "lstpu-kvmig2" in ctype:
                preamble = wire_mod.read_exact(
                    resp.read, len(wire_mod.KVMIG2_PREAMBLE)
                )
                if preamble != wire_mod.KVMIG2_PREAMBLE:
                    raise wire_mod.WireError(
                        f"bad kvmig2 preamble {preamble!r}"
                    )
                # page payloads from the wire are bounded like the
                # migration receiver's: nothing larger than the begin
                # frame's own bytes_per_page claim should ever arrive,
                # but the DECODE bound must not trust it — use the flat
                # transfer cap (the engine's checksum still gates binds)
                yield from wire_mod.decode_mig_frames(
                    resp.read, max_payload=64 << 20
                )
                return
            while True:
                line = resp.readline()
                if not line:
                    return
                line = line.strip()
                if not line:
                    continue
                yield json.loads(line.decode("utf-8"))
        except MigrationError:
            raise
        except Exception as e:  # noqa: BLE001 — dead wire mid-fetch
            raise MigrationError(
                f"page fetch from {url} died mid-stream: {e}"
            ) from e
        finally:
            conn.close()

    return frames()
