"""Grammar-constrained decoding: JSON-schema / regex → token-level DFA.

The agentic half of ROADMAP item 4 (PAPERS.md "Software-Defined Agentic
Serving": the output SCHEMA is a per-request policy input): a LangStream
tool-calling agent needs the model's completion to be machine-parseable
every time, not most times. This module compiles a ``response_format``
(JSON schema subset, or a raw regex) down to a token-level DFA:

    schema ──► regex ──► byte NFA (Thompson) ──► byte DFA (subset
    construction) ──► token DFA: ``next[state, token_id]`` = the DFA state
    after consuming the token's UTF-8 bytes, or -1 when any byte dies.

The dense ``next`` table stays HOST-side (the authoritative mirror the
engine advances per delivered token — completion detection + the
per-position state ids the speculative verify path masks drafts with).
The DEVICE carries a packed twin, ~32× smaller (the dense ``[G+1, S, V]``
int32 pool was V-linear: ~670 MB at a 256k vocab with 4 slots × 128
states, which is why "hundreds of resident grammars" used to be
impossible):

- **legality bitmask** ``bits [S, ceil(V/32)]`` uint32 — the sign bit of
  ``next`` packed LSB-first (token ``t`` → bit ``t % 32`` of word
  ``t // 32``); ``sampling.sample`` expands it on device with one
  shift/AND inside its existing mask fold;
- **default-successor + sorted-exceptions transition table** — per-state
  modal successor ``defaults [S]`` plus a sorted composite-key exceptions
  array (``key = state · V + token``) probed with ``searchsorted``, so
  fused decode/verify chunks still advance the DFA on device and a
  16-step chunk stays ONE dispatch. Legal tokens advance EXACTLY as the
  dense table (exceptions hold every legal token whose successor is not
  the state's mode); illegal tokens are never sampled (masked to −inf).

Invariants the compiler enforces (the engine's safety net depends on them):

- **No dead ends**: every reachable state has at least one legal token,
  so a constrained slot can never present an all ``-inf`` row to the
  sampler (which would read as a NaN fault and quarantine the slot).
  States that accept with no outgoing byte transitions become COMPLETE
  sink states — every token legal as a self-loop; the engine finishes the
  request with ``finish_reason="stop"`` the moment its host mirror enters
  one, so the self-loop's tokens are never delivered.
- **EOS at accepting states**: when the tokenizer defines one, EOS is
  legal exactly at accepting states (a stop there leaves output matching
  the grammar); the engine's normal stop handling does the rest.

``GrammarRegistry`` is the residency layer, shaped like the adapter pool
(serving/adapters.py): four packed device planes — bits ``[G+1, S, W]``
uint32, defaults ``[G+1, S]`` int32, exception key/next ``[G+1, E]``
int32 — whose row 0 is the unconstrained all-legal self-loop (every base
slot rides it), an LRU over rows G ≥ 1, refcounts pinning rows that
active requests read, and ONE fused traced-row upload program (all four
planes in a single dispatch) warmed at engine startup. Residency state
is lock-guarded: ``release()`` runs from the request ``_finalize``
completion hook OFF the engine thread.
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass
from typing import Any, Iterable, Optional

import numpy as np

log = logging.getLogger(__name__)

DEAD = -1
MAX_DFA_STATES = 4096  # subset-construction blowup guard
BITS_PER_WORD = 32  # uint32 legality-bitmask packing width
DEFAULT_GRAMMAR_EXCEPTIONS = 65536  # per-row exception capacity default
# exception-key pad value: strictly greater than any composite key
# state·V+token the registry admits (it enforces S·V < 2**31 - 1), so a
# searchsorted probe can never false-hit a padded tail entry
_EXC_SENTINEL = 2**31 - 1


class GrammarError(ValueError):
    """The response_format cannot be compiled (unsupported construct,
    state blowup, or a dead-end grammar) — fail the REQUEST with this,
    never the engine."""


# ---------------------------------------------------------------------------
# Regex → byte NFA (Thompson construction)
# ---------------------------------------------------------------------------

_EPS = None  # epsilon edge marker


class _Nfa:
    """Mutable NFA under construction: state i's edges are (byteset, to)
    pairs; byteset None = epsilon."""

    def __init__(self) -> None:
        self.edges: list[list[tuple[Optional[frozenset], int]]] = []

    def state(self) -> int:
        self.edges.append([])
        return len(self.edges) - 1

    def link(self, a: int, b: int, bytes_: Optional[frozenset] = _EPS) -> None:
        self.edges[a].append((bytes_, b))


_SPECIALS = set("()[]{}|*+?.\\")

_ESCAPES = {
    "d": frozenset(range(0x30, 0x3A)),
    "w": frozenset(
        list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
        + list(range(0x61, 0x7B)) + [0x5F]
    ),
    "s": frozenset([0x20, 0x09, 0x0A, 0x0D]),
    "n": frozenset([0x0A]),
    "t": frozenset([0x09]),
    "r": frozenset([0x0D]),
}

_ANY = frozenset(range(256))


def _parse_class(pattern: str, i: int) -> tuple[frozenset, int]:
    """``[...]`` character class starting at pattern[i] == '['."""
    i += 1
    negate = i < len(pattern) and pattern[i] == "^"
    if negate:
        i += 1
    members: set[int] = set()
    first = True
    while i < len(pattern) and (pattern[i] != "]" or first):
        first = False
        if pattern[i] == "\\" and i + 1 < len(pattern):
            esc = pattern[i + 1]
            if esc in _ESCAPES:
                members |= _ESCAPES[esc]
                i += 2
                continue
            lo = ord(esc)
            i += 2
        else:
            lo = ord(pattern[i])
            i += 1
        if i + 1 < len(pattern) and pattern[i] == "-" and pattern[i + 1] != "]":
            hi_ch = pattern[i + 1]
            hi = ord(hi_ch)
            i += 2
            if hi > 255:
                raise GrammarError(
                    "non-ASCII character in class range: classes operate on "
                    "BYTES (multi-byte UTF-8 cannot join a byte set) — use "
                    "the literal outside a class instead"
                )
            members |= set(range(lo, hi + 1))
        else:
            members.add(lo)
        if lo > 255:
            raise GrammarError(
                "non-ASCII character in class: classes operate on BYTES "
                "(multi-byte UTF-8 cannot join a byte set) — use the "
                "literal outside a class instead"
            )
    if i >= len(pattern):
        raise GrammarError(f"unterminated character class in {pattern!r}")
    i += 1  # closing ]
    byteset = frozenset(range(256)) - frozenset(members) if negate else frozenset(members)
    if not byteset:
        raise GrammarError("empty character class")
    return byteset, i


def _regex_to_nfa(pattern: str) -> tuple[_Nfa, int, int]:
    """Recursive-descent Thompson construction over UTF-8 BYTES (non-ASCII
    literals expand to their byte sequences). Supports literals, escapes,
    ``.``, classes, grouping, alternation, and ``* + ?``."""
    nfa = _Nfa()

    def parse_alt(i: int) -> tuple[int, int, int]:
        s0, a0, i = parse_concat(i)
        starts, accepts = [s0], [a0]
        while i < len(pattern) and pattern[i] == "|":
            s, a, i = parse_concat(i + 1)
            starts.append(s)
            accepts.append(a)
        if len(starts) == 1:
            return starts[0], accepts[0], i
        s, a = nfa.state(), nfa.state()
        for st, ac in zip(starts, accepts):
            nfa.link(s, st)
            nfa.link(ac, a)
        return s, a, i

    def parse_concat(i: int) -> tuple[int, int, int]:
        s = nfa.state()
        a = s
        while i < len(pattern) and pattern[i] not in "|)":
            fs, fa, i = parse_repeat(i)
            nfa.link(a, fs)
            a = fa
        return s, a, i

    def parse_repeat(i: int) -> tuple[int, int, int]:
        atom_start = i
        fs, fa, i = parse_atom(i)
        if i < len(pattern) and pattern[i] in "*+?":
            op = pattern[i]
            i += 1
            s, a = nfa.state(), nfa.state()
            nfa.link(s, fs)
            nfa.link(fa, a)
            if op in "*?":
                nfa.link(s, a)
            if op in "*+":
                nfa.link(fa, fs)
            return s, a, i
        if i < len(pattern) and pattern[i] == "{":
            # bounded repetition {m,n} by atom duplication (re-parse the
            # atom's span once per copy): m mandatory copies chained, then
            # n-m optional ones each epsilon-skippable to the exit. Bounded
            # grammars are what make constrained GENERATION terminate —
            # greedy decode on an unbounded star can legally emit the same
            # byte forever, but a {0,N} run's N+1'th position has only the
            # closing literal legal, so the DFA forces progress.
            end = pattern.find("}", i)
            if end < 0:
                raise GrammarError(f"unterminated {{m,n}} in {pattern!r}")
            spec = pattern[i + 1 : end]
            try:
                if "," in spec:
                    m_s, n_s = spec.split(",", 1)
                    m, n = int(m_s or 0), int(n_s)
                else:
                    m = n = int(spec)
            except ValueError as e:
                raise GrammarError(f"bad repetition {{{spec}}}") from e
            if n < m or m < 0 or n > 512:
                # n == 0 is legal: {0,0} is the epsilon repetition (a
                # maxItems: 1 array emits (,item){0,0} — zero tail items)
                raise GrammarError(f"bad repetition bounds {{{spec}}}")
            atom_src = pattern[atom_start:i]

            def copy_atom() -> tuple[int, int]:
                cs, ca, consumed = parse_atom(atom_start)
                assert consumed == i, (atom_src, consumed, i)
                return cs, ca

            s = nfa.state()
            exit_ = nfa.state()
            a = s
            for _ in range(m):
                cs, ca = copy_atom()
                nfa.link(a, cs)
                a = ca
            for _ in range(n - m):
                nfa.link(a, exit_)  # stopping here is legal
                cs, ca = copy_atom()
                nfa.link(a, cs)
                a = ca
            nfa.link(a, exit_)
            return s, exit_, end + 1
        return fs, fa, i

    def chain_bytes(bs: bytes) -> tuple[int, int]:
        s = nfa.state()
        a = s
        for byte in bs:
            nxt = nfa.state()
            nfa.link(a, nxt, frozenset([byte]))
            a = nxt
        return s, a

    def parse_atom(i: int) -> tuple[int, int, int]:
        ch = pattern[i]
        if ch == "(":
            s, a, i = parse_alt(i + 1)
            if i >= len(pattern) or pattern[i] != ")":
                raise GrammarError(f"unbalanced parens in {pattern!r}")
            return s, a, i + 1
        if ch == "[":
            byteset, i = _parse_class(pattern, i)
            s, a = nfa.state(), nfa.state()
            nfa.link(s, a, byteset)
            return s, a, i
        if ch == ".":
            s, a = nfa.state(), nfa.state()
            nfa.link(s, a, _ANY - frozenset([0x0A]))
            return s, a, i + 1
        if ch == "\\":
            if i + 1 >= len(pattern):
                raise GrammarError(f"trailing backslash in {pattern!r}")
            esc = pattern[i + 1]
            if esc in _ESCAPES:
                s, a = nfa.state(), nfa.state()
                nfa.link(s, a, _ESCAPES[esc])
                return s, a, i + 2
            s, a = chain_bytes(esc.encode("utf-8"))
            return s, a, i + 2
        if ch in "*+?|)":
            raise GrammarError(f"misplaced {ch!r} in {pattern!r}")
        s, a = chain_bytes(ch.encode("utf-8"))
        return s, a, i + 1

    start, accept, i = parse_alt(0)
    if i != len(pattern):
        raise GrammarError(f"unparsed tail {pattern[i:]!r} in {pattern!r}")
    return nfa, start, accept


def _nfa_to_byte_dfa(
    nfa: _Nfa, start: int, accept: int
) -> tuple[np.ndarray, set[int]]:
    """Subset construction → ``byte_next [S, 256]`` int32 (-1 dead) and the
    accepting-state set. State 0 is the start state."""

    def closure(states: frozenset) -> frozenset:
        stack, seen = list(states), set(states)
        while stack:
            s = stack.pop()
            for byteset, to in nfa.edges[s]:
                if byteset is _EPS and to not in seen:
                    seen.add(to)
                    stack.append(to)
        return frozenset(seen)

    start_set = closure(frozenset([start]))
    ids: dict[frozenset, int] = {start_set: 0}
    order = [start_set]
    rows: list[np.ndarray] = []
    for state_set in order:
        row = np.full(256, DEAD, np.int32)
        # group the outgoing byte edges once, then move per byte
        by_byte: dict[int, set[int]] = {}
        for s in state_set:
            for byteset, to in nfa.edges[s]:
                if byteset is _EPS:
                    continue
                for byte in byteset:
                    by_byte.setdefault(byte, set()).add(to)
        for byte, targets in by_byte.items():
            target = closure(frozenset(targets))
            to_id = ids.get(target)
            if to_id is None:
                if len(ids) >= MAX_DFA_STATES:
                    raise GrammarError(
                        f"grammar explodes past {MAX_DFA_STATES} DFA states"
                    )
                to_id = len(ids)
                ids[target] = to_id
                order.append(target)
            row[byte] = to_id
        rows.append(row)
    accepting = {i for ss, i in ids.items() if accept in ss}
    return np.stack(rows), accepting


# ---------------------------------------------------------------------------
# JSON schema (subset) → regex
# ---------------------------------------------------------------------------

# JSON string body: any byte except the quote, the backslash (no escape
# sequences — keeps the DFA byte-local) and the control range JSON forbids
# raw. BOUNDED: every primitive carries a finite repetition so the whole
# grammar is finite — that is what guarantees a constrained generation
# TERMINATES (at the bound, only the closing literal is legal) instead of
# greedy-looping inside an unbounded star until max_new_tokens.
_STRING_CLASS = '[^"\\\\' + "".join(chr(c) for c in range(0x20)) + "]"
_DEFAULT_STRING_MAX = 24
_JSON_INT = r"-?(0|[1-9][0-9]{0,14})"
_JSON_NUMBER = r"-?(0|[1-9][0-9]{0,14})(\.[0-9]{1,6})?"


def _json_string_regex(schema: dict) -> str:
    n = int(schema.get("maxLength", _DEFAULT_STRING_MAX))
    n = max(1, min(n, 256))
    return f'"{_STRING_CLASS}{{0,{n}}}"'


def _regex_escape(text: str) -> str:
    return "".join(f"\\{c}" if c in _SPECIALS else c for c in text)


def schema_to_regex(schema: dict) -> str:
    """Compile a JSON-schema SUBSET to a regex over compact (no-whitespace)
    JSON. Supported: ``object`` with ``properties`` (all emitted, in
    declaration order — the deterministic layout is what makes the grammar
    regular), ``string`` (plus ``enum``/``pattern``), ``integer``,
    ``number``, ``boolean``, ``null``, ``array`` of a supported item type,
    and bare ``enum`` consts. Anything else raises GrammarError — an
    unsupported schema must fail the request loudly, not emit unvalidated
    output."""
    if not isinstance(schema, dict):
        raise GrammarError(f"schema must be an object, got {type(schema).__name__}")
    if "enum" in schema:
        opts = [
            _regex_escape(json.dumps(v, separators=(",", ":")))
            for v in schema["enum"]
        ]
        if not opts:
            raise GrammarError("empty enum")
        return "(" + "|".join(opts) + ")"
    stype = schema.get("type")
    if stype == "string":
        if "pattern" in schema:
            return '"' + str(schema["pattern"]) + '"'
        return _json_string_regex(schema)
    if stype == "integer":
        return _JSON_INT
    if stype == "number":
        return _JSON_NUMBER
    if stype == "boolean":
        return "(true|false)"
    if stype == "null":
        return "null"
    if stype == "array":
        item = schema_to_regex(schema.get("items", {"type": "string"}))
        max_items = max(1, min(int(schema.get("maxItems", 8)), 64))
        return r"\[(" + item + "(," + item + f"){{0,{max_items - 1}}}" + r")?\]"
    if stype == "object":
        props = schema.get("properties", {})
        if not props:
            raise GrammarError("object schema needs at least one property")
        parts = []
        for name, sub in props.items():
            key = _regex_escape(json.dumps(str(name)))
            parts.append(key + ":" + schema_to_regex(sub))
        return r"\{" + ",".join(parts) + r"\}"
    raise GrammarError(
        f"unsupported schema {json.dumps(schema)[:80]!r}; supported types: "
        "object, array, string, integer, number, boolean, null, enum"
    )


# ---------------------------------------------------------------------------
# Token-level DFA
# ---------------------------------------------------------------------------


@dataclass
class TokenDFA:
    """One compiled grammar, token-level. ``next[s, t] >= 0`` ⇔ token t is
    legal in state s (the device mask IS the sign bit); ``complete`` states
    are sink-accepts — the engine stops the request on entry."""

    next: np.ndarray  # [S, V] int32, -1 = illegal
    accepting: frozenset  # accepting DFA states (EOS legal here)
    complete: frozenset  # sink-accept states (host finishes on entry)
    key: str = ""  # canonical response_format (registry cache key)

    @property
    def n_states(self) -> int:
        return self.next.shape[0]

    def advance(self, state: int, token: int) -> int:
        """Host-mirror advance (engine: one per delivered token)."""
        if state in self.complete:
            return state
        if not (0 <= token < self.next.shape[1]):
            return DEAD
        return int(self.next[state, token])

    def is_complete(self, state: int) -> bool:
        return state in self.complete

    def packed(self) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """The packed device product ``(bits, defaults, exc_key,
        exc_next)`` — computed once per compiled grammar and cached on
        the instance (packing is O(S·V), same order as building ``next``
        itself)."""
        cached = getattr(self, "_packed_cache", None)
        if cached is None:
            cached = pack_next_table(self.next)
            self._packed_cache = cached
        return cached

    @property
    def n_exceptions(self) -> int:
        """Exception rows this grammar needs in the pool (capacity check
        against the registry's ``max_exceptions``)."""
        return int(self.packed()[2].shape[0])


def pack_next_table(
    next_table: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Dense ``next [S, V]`` int32 → the packed device representation:

    - ``bits [S, ceil(V/32)]`` uint32 — legality bitmask, LSB-first
      (token ``t`` → bit ``t % 32`` of word ``t // 32``), matching the
      shift/AND expansion in ``sampling._expand_allowed``;
    - ``defaults [S]`` int32 — the state's MODAL successor over its legal
      tokens (0 for all-dead states: padded rows park at state 0);
    - ``exc_key [E]`` int64 / ``exc_next [E]`` int32 — SORTED composite
      keys ``s · V + t`` for every legal token whose successor differs
      from the state default, successor alongside (keys are int64 here;
      the registry casts to int32 after enforcing ``S · V < 2**31``).

    Legal tokens reproduce the dense table EXACTLY (default unless the
    key probe hits an exception). Illegal tokens also resolve to
    default/exception, but they are masked to -inf by the bitmask and
    never sampled, so that value is never delivered."""
    n_states, vocab = next_table.shape
    legal = next_table >= 0
    n_words = (vocab + BITS_PER_WORD - 1) // BITS_PER_WORD
    padded = np.zeros((n_states, n_words * BITS_PER_WORD), dtype=bool)
    padded[:, :vocab] = legal
    weights = np.uint64(1) << np.arange(BITS_PER_WORD, dtype=np.uint64)
    bits = (
        (padded.reshape(n_states, n_words, BITS_PER_WORD).astype(np.uint64)
         * weights).sum(axis=-1)
    ).astype(np.uint32)

    defaults = np.zeros(n_states, np.int32)
    exc_key_parts: list[np.ndarray] = []
    exc_next_parts: list[np.ndarray] = []
    for s in range(n_states):
        row = next_table[s]
        mask = legal[s]
        if not mask.any():
            continue  # unreachable dead state: park at 0, mask all -inf
        counts = np.bincount(row[mask])
        d = int(counts.argmax())
        defaults[s] = d
        toks = np.nonzero(mask & (row != d))[0]
        if toks.size:
            exc_key_parts.append(np.int64(s) * vocab + toks.astype(np.int64))
            exc_next_parts.append(row[toks].astype(np.int32))
    if exc_key_parts:
        # ascending state, ascending token within a state → already sorted
        exc_key = np.concatenate(exc_key_parts)
        exc_next = np.concatenate(exc_next_parts)
    else:
        exc_key = np.zeros(0, np.int64)
        exc_next = np.zeros(0, np.int32)
    return bits, defaults, exc_key, exc_next


def _token_byte_table(
    tokenizer: Any, vocab_size: int
) -> tuple[np.ndarray, np.ndarray]:
    """Per-token UTF-8 byte images, padded: ``bytes_ [V, Lmax]`` +
    ``lengths [V]``. Tokens that decode to nothing (specials, ids past the
    tokenizer's vocab) get length -1 = never legal under any grammar.

    Cached ON the tokenizer object: the table is grammar-INDEPENDENT —
    V decode() calls (seconds at a 256k vocab) must be paid once per
    tokenizer, not once per distinct response_format a client submits."""
    cache = getattr(tokenizer, "_lstpu_token_bytes", None)
    if cache is not None and vocab_size in cache:
        return cache[vocab_size]
    rows: list[bytes] = []
    for t in range(vocab_size):
        try:
            text = tokenizer.decode([t])
        except Exception:  # noqa: BLE001 — undecodable id = unusable token
            text = ""
        rows.append(text.encode("utf-8") if text else b"")
    lmax = max((len(r) for r in rows), default=1) or 1
    bytes_ = np.zeros((vocab_size, lmax), np.int32)
    lengths = np.full(vocab_size, -1, np.int32)
    for t, r in enumerate(rows):
        if not r or "�" in rows[t].decode("utf-8", "replace"):
            continue  # empty or lossy decode: unusable under a byte DFA
        lengths[t] = len(r)
        bytes_[t, : len(r)] = list(r)
    try:
        if cache is None:
            cache = {}
            tokenizer._lstpu_token_bytes = cache
        cache[vocab_size] = (bytes_, lengths)
    except (AttributeError, TypeError):
        pass  # slots-only tokenizer: recompute per grammar, still correct
    return bytes_, lengths


def compile_token_dfa(
    pattern: str,
    tokenizer: Any,
    vocab_size: int,
    eos_token_id: Optional[int] = None,
    key: str = "",
) -> TokenDFA:
    """regex → byte DFA → token DFA over the MODEL vocab (ids past the
    tokenizer's vocab are simply never legal — constrained decoding also
    fences off the padding ids random weights love to argmax into).

    The token table is built vectorized: one [V]-wide numpy advance per
    byte position per start state, not a V×S python loop — a 256k vocab
    compiles in seconds, and the registry caches the result anyway."""
    byte_next, accepting = _nfa_to_byte_dfa(*_regex_to_nfa(pattern))
    n_states = byte_next.shape[0]
    tok_bytes, tok_lens = _token_byte_table(tokenizer, vocab_size)
    lmax = tok_bytes.shape[1]

    # pad the byte table with a dead row so vectorized advance can index
    # state -1 safely (dead stays dead)
    padded = np.vstack([byte_next, np.full((1, 256), DEAD, np.int32)])

    next_table = np.full((n_states, vocab_size), DEAD, np.int32)
    usable = tok_lens > 0
    for s in range(n_states):
        states = np.full(vocab_size, s, np.int32)
        for p in range(lmax):
            active = usable & (tok_lens > p)
            if not active.any():
                break
            states = np.where(
                active, padded[states, tok_bytes[:, p]], states
            )
        states = np.where(usable, states, DEAD)
        next_table[s] = states

    # sink-accept states: accepting with NO outgoing byte transition —
    # generation is COMPLETE there. Self-loop every token so the device
    # row is never all -inf; the engine finishes the request on entry
    # before any self-loop token is delivered.
    complete = {
        s for s in accepting if not (byte_next[s] >= 0).any()
    }
    for s in complete:
        next_table[s, :] = s
    # EOS legal exactly at accepting states (stopping there leaves output
    # that matches the grammar)
    if eos_token_id is not None and 0 <= eos_token_id < vocab_size:
        for s in accepting:
            next_table[s, eos_token_id] = s
    # no-dead-end check: a state with zero legal tokens would hand the
    # sampler an all -inf row (reads as a NaN fault). Unreachable states
    # can be dead; reachable ones cannot.
    reachable = {0}
    frontier = [0]
    while frontier:
        s = frontier.pop()
        for t in set(next_table[s][next_table[s] >= 0].tolist()):
            if t not in reachable:
                reachable.add(t)
                frontier.append(t)
    for s in reachable:
        if not (next_table[s] >= 0).any():
            raise GrammarError(
                f"grammar has a dead end at DFA state {s}: no token in the "
                "vocabulary can continue it (tokenizer/grammar mismatch?)"
            )
    return TokenDFA(
        next=next_table,
        accepting=frozenset(accepting),
        complete=frozenset(complete),
        key=key,
    )


def compile_response_format(
    response_format: dict,
    tokenizer: Any,
    vocab_size: int,
    eos_token_id: Optional[int] = None,
) -> TokenDFA:
    """``response_format`` (the OpenAI-compatible request field) → TokenDFA.
    Supported: ``{"type": "json_schema", "json_schema": {"schema": {...}}}``
    (the nested ``{"schema": ...}`` and flat spellings both work) and
    ``{"type": "regex", "regex": "..."}``."""
    if not isinstance(response_format, dict):
        raise GrammarError("response_format must be an object")
    kind = str(response_format.get("type", ""))
    if kind == "regex":
        pattern = response_format.get("regex")
        if not pattern:
            raise GrammarError("response_format type=regex needs a 'regex' key")
        pattern = str(pattern)
    elif kind == "json_schema":
        schema = response_format.get("json_schema", response_format.get("schema"))
        if isinstance(schema, dict) and "schema" in schema:
            schema = schema["schema"]
        if not isinstance(schema, dict):
            raise GrammarError(
                "response_format type=json_schema needs a schema object"
            )
        pattern = schema_to_regex(schema)
    else:
        raise GrammarError(
            f"unsupported response_format type {kind!r}; "
            "supported: json_schema, regex"
        )
    key = json.dumps(response_format, sort_keys=True, separators=(",", ":"))
    return compile_token_dfa(
        pattern, tokenizer, vocab_size, eos_token_id, key=key
    )


# ---------------------------------------------------------------------------
# Device-resident grammar pool (the registry)
# ---------------------------------------------------------------------------


def grammar_pool_bytes(
    slots: int,
    states: int,
    vocab_size: int,
    exceptions: int = DEFAULT_GRAMMAR_EXCEPTIONS,
) -> int:
    """Plan-term arithmetic (serving/memory.py) for the PACKED pool:
    bits ``[G+1, S, ceil(V/32)]`` uint32 + defaults ``[G+1, S]`` int32 +
    exception key/next ``[G+1, E]`` int32 each. ~28× smaller than the
    dense ``[G+1, S, V]`` int32 table this replaced (~670 MB at gemma's
    256k vocab with the OLD defaults 4×128 — docs §15 has the sizing
    table; 64 slots now fit in ~0.3 GB). ``slots <= 0`` is the shared
    DISABLED contract: constrained decoding off, 0 bytes, and the
    registry refuses construction (the engine never builds one)."""
    if slots <= 0:
        return 0
    rows = slots + 1
    words = (vocab_size + BITS_PER_WORD - 1) // BITS_PER_WORD
    per_row = states * words * 4 + states * 4 + 2 * max(0, exceptions) * 4
    return rows * per_row


@dataclass
class _GrammarState:
    dfa: TokenDFA
    row: Optional[int] = None
    refs: int = 0
    last_used: int = 0


class GrammarRegistry:
    """Compile cache + device residency for token DFAs. Same shape as
    AdapterRegistry: row 0 = unconstrained (all tokens legal, self-loop at
    state 0), rows 1..G hot-swapped LRU, refcounts pin rows active
    requests read. Residency state is ``_lock``-guarded: ``release()``
    runs from the request ``_finalize`` completion hook OFF the engine
    thread, and ``compile()`` runs caller-side on any thread."""

    _GUARDED = {
        "_lock": (
            "pool",
            "_by_key",
            "_row_owner",
            "_free_rows",
            "_tick",
            "compiled_total",
            "swaps_total",
        ),
    }

    def __init__(
        self,
        tokenizer: Any,
        vocab_size: int,
        eos_token_id: Optional[int],
        slots: int = 64,
        max_states: int = 128,
        max_exceptions: int = DEFAULT_GRAMMAR_EXCEPTIONS,
    ) -> None:
        import jax.numpy as jnp

        if slots < 1:
            raise ValueError(
                "grammar-slots <= 0 disables constrained decoding "
                "(grammar_pool_bytes(slots<=0) == 0 is the same "
                "zero/disabled contract); the registry is only built "
                f"with slots >= 1, got slots={slots}"
            )
        if max_states < 2 or max_exceptions < 1:
            raise ValueError(
                f"grammar pool needs >= 2 states and >= 1 exception row; "
                f"got max_states={max_states} "
                f"max_exceptions={max_exceptions}"
            )
        if int(max_states) * int(vocab_size) > _EXC_SENTINEL:
            raise ValueError(
                "grammar-states × vocab_size must stay below 2**31 - 1: "
                "the device transition probe uses int32 composite keys "
                f"(state·V+token); got {max_states} × {vocab_size}"
            )
        self.tokenizer = tokenizer
        self.vocab_size = int(vocab_size)
        self.eos_token_id = eos_token_id
        self.slots = int(slots)
        self.max_states = int(max_states)
        self.max_exceptions = int(max_exceptions)
        self.n_words = (
            self.vocab_size + BITS_PER_WORD - 1
        ) // BITS_PER_WORD
        # row 0: every token legal (all-ones bitmask), self-loop at state
        # 0 (defaults 0, no exceptions) — every base slot rides it. Rows
        # 1..G start all-illegal and park at state 0 until a swap-in.
        bits = np.zeros(
            (self.slots + 1, self.max_states, self.n_words), np.uint32
        )
        bits[0] = np.uint32(0xFFFFFFFF)
        defaults = np.zeros((self.slots + 1, self.max_states), np.int32)
        exc_key = np.full(
            (self.slots + 1, self.max_exceptions), _EXC_SENTINEL, np.int32
        )
        exc_next = np.zeros((self.slots + 1, self.max_exceptions), np.int32)
        self.pool = (
            jnp.asarray(bits),
            jnp.asarray(defaults),
            jnp.asarray(exc_key),
            jnp.asarray(exc_next),
        )
        self.pool_bytes = grammar_pool_bytes(
            self.slots,
            self.max_states,
            self.vocab_size,
            self.max_exceptions,
        )
        self._by_key: dict[str, _GrammarState] = {}
        self._row_owner: dict[int, _GrammarState] = {}
        self._free_rows = list(range(self.slots, 0, -1))
        self._tick = 0
        self._lock = threading.Lock()
        # cumulative stats
        self.compiled_total = 0
        self.swaps_total = 0
        self.on_load_program: Optional[Any] = None

    # -- compile cache (any thread: submit() compiles caller-side) ----------

    def compile(self, response_format: dict) -> TokenDFA:
        key = json.dumps(response_format, sort_keys=True, separators=(",", ":"))
        with self._lock:
            state = self._by_key.get(key)
        if state is not None:
            return state.dfa
        dfa = compile_response_format(
            response_format, self.tokenizer, self.vocab_size, self.eos_token_id
        )
        if dfa.n_states > self.max_states:
            raise GrammarError(
                f"grammar needs {dfa.n_states} DFA states but the pool is "
                f"sized for {self.max_states}; raise grammar-states"
            )
        if dfa.n_exceptions > self.max_exceptions:
            raise GrammarError(
                f"grammar needs {dfa.n_exceptions} transition exceptions "
                f"but the pool is sized for {self.max_exceptions}; raise "
                "grammar-exceptions"
            )
        with self._lock:
            state = self._by_key.get(key)
            if state is None:
                state = _GrammarState(dfa=dfa)
                self._by_key[key] = state
                self.compiled_total += 1
        return state.dfa

    # -- residency (engine thread) -------------------------------------------

    def acquire(self, dfa: TokenDFA) -> int:
        """Pool row for a compiled grammar, swapping it in when absent.
        Refcounted; release() when the request finishes. Lock-guarded:
        release() runs from the _finalize hook off the engine thread, and
        an unguarded refs bump here would race it."""
        with self._lock:
            state = self._by_key.get(dfa.key)
            if state is None:  # compiled outside the cache (tests)
                state = _GrammarState(dfa=dfa)
                self._by_key[dfa.key] = state
            self._tick += 1
            state.last_used = self._tick
            if state.row is None:
                self._swap_in_locked(state)
            state.refs += 1
            return state.row

    def release(self, dfa: TokenDFA) -> None:
        with self._lock:
            state = self._by_key.get(dfa.key)
            if state is None:
                return
            assert state.refs > 0
            state.refs -= 1

    def _swap_in_locked(self, state: _GrammarState) -> None:
        import jax.numpy as jnp

        if not self._free_rows:
            victims = [s for s in self._row_owner.values() if s.refs == 0]
            if not victims:
                raise GrammarError(
                    f"all {self.slots} grammar rows are pinned by active "
                    "requests; raise grammar-slots or retry"
                )
            victim = min(victims, key=lambda s: s.last_used)
            self._free_rows.append(victim.row)
            self._row_owner.pop(victim.row, None)
            victim.row = None
        row = self._free_rows.pop()
        bits, defaults, exc_key, exc_next = state.dfa.packed()
        n = state.dfa.n_states
        n_exc = exc_key.shape[0]
        if n_exc > self.max_exceptions:  # acquire() bypassing compile()
            raise GrammarError(
                f"grammar needs {n_exc} transition exceptions but the "
                f"pool is sized for {self.max_exceptions}; raise "
                "grammar-exceptions"
            )
        pb = np.zeros((self.max_states, self.n_words), np.uint32)
        pb[:n] = bits
        pd = np.zeros(self.max_states, np.int32)
        pd[:n] = defaults
        pk = np.full(self.max_exceptions, _EXC_SENTINEL, np.int32)
        # int32 cast is safe: __init__ enforces max_states·V < 2**31
        pk[:n_exc] = exc_key.astype(np.int32)
        pn = np.zeros(self.max_exceptions, np.int32)
        pn[:n_exc] = exc_next
        if self.on_load_program is not None:
            self.on_load_program()
        self.pool = _grammar_load_row(
            self.pool,
            jnp.asarray(row, jnp.int32),
            jnp.asarray(pb),
            jnp.asarray(pd),
            jnp.asarray(pk),
            jnp.asarray(pn),
        )
        state.row = row
        self._row_owner[row] = state
        self.swaps_total += 1

    def warmup(self) -> None:
        """Compile the row-upload program with an out-of-bounds row (every
        write drops) — no grammar swap under traffic is ever a compile."""
        import jax

        import jax.numpy as jnp

        if self.on_load_program is not None:
            self.on_load_program()
        with self._lock:
            self.pool = _grammar_load_row(
                self.pool,
                jnp.asarray(self.slots + 1, jnp.int32),
                jnp.asarray(
                    np.zeros((self.max_states, self.n_words), np.uint32)
                ),
                jnp.asarray(np.zeros(self.max_states, np.int32)),
                jnp.asarray(
                    np.full(self.max_exceptions, _EXC_SENTINEL, np.int32)
                ),
                jnp.asarray(np.zeros(self.max_exceptions, np.int32)),
            )
            jax.block_until_ready(self.pool)

    @property
    def resident(self) -> int:
        return len(self._row_owner)

    def stats(self) -> dict[str, Any]:
        return {
            "compiled": self.compiled_total,
            "resident": self.resident,
            "slots": self.slots,
            "states": self.max_states,
            "exceptions": self.max_exceptions,
            "swaps-total": self.swaps_total,
            "pool-bytes": self.pool_bytes,
        }


def _grammar_load_row(pool, row, bits, defaults, exc_key, exc_next):
    """One traced-row upload program covering ALL FOUR packed planes in a
    single dispatch, jitted ONCE at module scope (the same shape as
    adapters._load_row) — defining the jit wrapper inside the call would
    retrace and recompile on EVERY swap, which is exactly the mid-traffic
    stall warmup() exists to prevent."""
    return _GRAMMAR_LOAD_JIT(pool, row, bits, defaults, exc_key, exc_next)


def _make_grammar_load_jit():
    import functools as _functools

    import jax

    @_functools.partial(jax.jit, donate_argnames=("p",))
    def _load(p, r, b, d, k, n):
        pb, pd, pk, pn = p
        return (
            pb.at[r].set(b, mode="drop"),
            pd.at[r].set(d, mode="drop"),
            pk.at[r].set(k, mode="drop"),
            pn.at[r].set(n, mode="drop"),
        )

    return _load


_GRAMMAR_LOAD_JIT = _make_grammar_load_jit()


def verify_states(
    dfa: TokenDFA, state: int, drafts: Iterable[int]
) -> list[int]:
    """Per-position DFA states for a speculative verify dispatch: position
    j's state is reached after consuming drafts 0..j-1 from ``state``. An
    ILLEGAL draft's successors carry the last legal state forward — those
    positions can never be consumed (the illegal draft is rejected at j by
    its -inf logit), but their mask rows must stay well-formed (≥1 legal
    token) so the device math never sees an all-masked row."""
    out = [state]
    cur = state
    for d in drafts:
        nxt = dfa.advance(cur, int(d))
        cur = cur if nxt < 0 else nxt
        out.append(cur)
    return out
