"""Durable session tier: crash-safe KV checkpoints on disk (ROADMAP 2b/3b).

The host arena (serving/pagepool.HostPageTier, docs/SERVING.md §16) made
hibernated sessions survive DEVICE-pool pressure — but they still live in
their owner's RAM, so replica death, drain or scale-to-zero destroys every
idle session and the fleet re-prefills the world. This module is the tier
UNDER the arena: checkpoints on disk (or any mounted object store) that any
replica can restore, so a session outlives the process that spilled it —
the serverless cold-start economics both PAPERS.md anchors hinge on
(DeepServe's serverless abstraction, STREAM's multi-tier KV).

Crash-safety is by CONSTRUCTION, not by fsck:

- The data file IS the migration wire. A checkpoint body is the
  ``lstpu-kvmig-v2`` frame stream (serving/wire.py — 8-byte preamble,
  CRC32-preluded begin/page/commit frames) that ``decode_mig_frames``
  already parses and bounds-checks: a torn write fails its frame CRC or
  truncates mid-prelude, both of which read as a DEAD ENTRY, never as
  wrong KV and never as a hang. One codec across RAM, wire and disk also
  means a durable checkpoint can be served STRAIGHT onto the P2P fetch
  wire without re-encoding.
- Writes are temp + fsync + rename, data file BEFORE manifest: the
  manifest is the commit record, so every crash phase (pre-temp,
  mid-frame, pre-rename, post-rename) leaves either a complete entry or
  garbage that ``rehydrate`` skips. A data file without a manifest is an
  aborted checkpoint; a manifest without its data file is a dead entry.
- The manifest carries the SPILL-TIME per-page blake2b checksums
  (``pagepool.page_checksum``, stamped when the page left the device).
  Restore verifies read bytes against those stamps — rot is never
  laundered by a fresh hash over already-rotten bytes.

EVERY failure — torn file, CRC mismatch, checksum mismatch, stale or
missing manifest, slow or full disk — raises ``DurableError`` and marks
the entry dead; the engine's admit path degrades to a local cold prefill
with a ``durable-restore-failed`` flight dump (docs/SERVING.md §23), zero
restarts. The ``disk-torn`` / ``disk-corrupt`` / ``disk-stall`` /
``disk-full`` fault sites (serving/faultinject.py) drill each rung.

No jax imports: the store moves opaque page byte images; leaf splitting
and checksum recomputation happen in the engine where the pool layout
lives. Thread-safety: the engine thread restores while the durable worker
checkpoints and the beacon thread advertises — one lock over the index.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Iterable, Optional

from langstream_tpu.serving import wire

log = logging.getLogger(__name__)

# the manifest commit record, one per checkpoint: schema-tagged so a
# future layout change reads old entries as dead instead of as garbage
MANIFEST_SCHEMA = "lstpu-kvdur-v1"
# the replica hibernation record (one per directory, last writer wins)
HIBERNATE_SCHEMA = "lstpu-kvhib-v1"

DATA_SUFFIX = ".kvckpt"
MANIFEST_SUFFIX = ".json"
HIBERNATE_NAME = "hibernate.json"

# a checkpoint page never legitimately exceeds this (the largest real
# pool page is ~MiBs); a corrupt length prefix must bound allocation
MAX_PAGE_BYTES = 1 << 28


class DurableError(RuntimeError):
    """A durable-tier violation (torn/corrupt/missing checkpoint, stale
    manifest, full or stalled disk). Callers treat it exactly like a
    failed migration: the entry is dead, the request prefills cold —
    it never implies wrong KV and never hangs the engine."""


def _fsync_dir(path: str) -> None:
    """fsync the directory so a rename survives power loss — best-effort
    (object-store mounts may not support directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class DurableStore:
    """Directory-backed checkpoint store for hibernated KV prefixes.

    One checkpoint = ``<digest>.kvckpt`` (the v2 frame stream) +
    ``<digest>.json`` (the manifest commit record). ``checkpoint`` runs on
    the engine's durable worker thread (and synchronously at hibernation);
    ``restore`` runs on the engine thread inside an admission; ``rehydrate``
    runs once at boot and reads MANIFESTS ONLY — resurrection cost is
    proportional to the index, not to the checkpointed bytes.

    ``max_bytes`` (0 = unbounded) is enforced after every checkpoint by
    evicting the least-recently-touched entries — the durable tier is a
    cache over re-prefill, so eviction is always safe, merely slow."""

    def __init__(
        self,
        root: str,
        max_bytes: int = 0,
        injector: Any = None,
    ) -> None:
        self.root = str(root)
        self.max_bytes = max(0, int(max_bytes))
        self._fault = injector
        self._lock = threading.Lock()
        # digest -> manifest dict (parsed, validated); the in-memory index
        self._index: dict[str, dict] = {}
        # counters (read under the lock by stats())
        self.checkpoints_total = 0
        self.checkpoint_bytes_total = 0
        self.checkpoint_failures_total = 0
        self.restores_total = 0
        self.restore_bytes_total = 0
        self.restore_failures_total = 0
        self.dead_entries_total = 0
        self.evictions_total = 0
        os.makedirs(self.root, exist_ok=True)

    # -- paths ------------------------------------------------------------

    def _data_path(self, digest: str) -> str:
        return os.path.join(self.root, f"{digest}{DATA_SUFFIX}")

    def _manifest_path(self, digest: str) -> str:
        return os.path.join(self.root, f"{digest}{MANIFEST_SUFFIX}")

    # -- index ------------------------------------------------------------

    def contains(self, digest: str) -> bool:
        with self._lock:
            return str(digest) in self._index

    def entries(self) -> list[tuple[str, int]]:
        """(digest, prefix length) pairs for the beacon advertisement —
        the durable analogue of ``PrefixPageIndex.advertised``."""
        with self._lock:
            return [
                (d, int(m.get("length", 0)))
                for d, m in self._index.items()
            ]

    def bytes_on_disk(self) -> int:
        with self._lock:
            return sum(int(m.get("bytes", 0)) for m in self._index.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def _mark_dead(self, digest: str, why: str) -> None:
        """Drop a bad entry from the index AND the disk — a dead entry
        must never be re-advertised or re-tried on the next admission."""
        with self._lock:
            self._index.pop(digest, None)
            self.dead_entries_total += 1
        for path in (self._manifest_path(digest), self._data_path(digest)):
            try:
                os.unlink(path)
            except OSError:
                pass
        log.warning("durable entry %s marked dead (%s)", digest, why)

    def invalidate(self, digest: str, why: str) -> None:
        """Public kill switch for an entry the CALLER proved bad (e.g. a
        page failing its spill-time checksum after the split) — same
        dead-entry semantics as an internally detected failure."""
        with self._lock:
            self.restore_failures_total += 1
        self._mark_dead(digest, why)

    # -- rehydrate (boot) --------------------------------------------------

    def rehydrate(self) -> int:
        """Scan the directory and rebuild the index from manifests — the
        resurrection path (docs/SERVING.md §23). Manifests only: data
        bytes are verified lazily at restore time. Every malformed,
        orphaned or size-mismatched entry counts dead and is skipped —
        a dirty directory NEVER fails a boot. Returns live entries."""
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            log.exception("durable rehydrate: cannot list %s", self.root)
            return 0
        live = 0
        for name in names:
            if not name.endswith(MANIFEST_SUFFIX) or name == HIBERNATE_NAME:
                continue
            digest = name[: -len(MANIFEST_SUFFIX)]
            try:
                with open(os.path.join(self.root, name)) as f:
                    manifest = json.load(f)
                self._validate_manifest(manifest, digest)
                data = self._data_path(digest)
                size = os.stat(data).st_size
                if size != int(manifest["bytes"]):
                    raise DurableError(
                        f"data file is {size} bytes, manifest says "
                        f"{manifest['bytes']}"
                    )
            except FileNotFoundError:
                self._mark_dead(digest, "manifest without data file")
                continue
            except (OSError, ValueError, KeyError, DurableError) as e:
                self._mark_dead(digest, f"bad manifest: {e}")
                continue
            with self._lock:
                self._index[digest] = manifest
            live += 1
        # data files without a manifest are aborted checkpoints: reclaim
        for name in names:
            if not name.endswith(DATA_SUFFIX):
                continue
            digest = name[: -len(DATA_SUFFIX)]
            if not self.contains(digest):
                try:
                    os.unlink(os.path.join(self.root, name))
                except OSError:
                    pass
        if live:
            log.info(
                "durable tier rehydrated %d session prefix(es) from %s",
                live, self.root,
            )
        return live

    @staticmethod
    def _validate_manifest(manifest: Any, digest: str) -> None:
        if not isinstance(manifest, dict):
            raise DurableError("manifest is not a record")
        if manifest.get("schema") != MANIFEST_SCHEMA:
            raise DurableError(
                f"unknown manifest schema {manifest.get('schema')!r}"
            )
        if manifest.get("digest") != digest:
            raise DurableError(
                f"manifest digest {manifest.get('digest')!r} does not "
                f"match its filename"
            )
        pages = manifest.get("pages")
        sums = manifest.get("checksums")
        if not isinstance(pages, int) or pages <= 0:
            raise DurableError("manifest has no page count")
        if not isinstance(sums, list) or len(sums) != pages:
            raise DurableError(
                f"manifest carries {len(sums) if isinstance(sums, list) else 0}"
                f" checksum(s) for {pages} page(s)"
            )
        for k in ("length", "bytes", "page_size", "bytes_per_page"):
            if not isinstance(manifest.get(k), int) or manifest[k] <= 0:
                raise DurableError(f"manifest field {k!r} missing or bad")

    # -- checkpoint (write) ------------------------------------------------

    def checkpoint(
        self,
        digest: str,
        length: int,
        tokens: Iterable[int],
        pages_raw: list[bytes],
        checksums: list[str],
        page_size: int,
        bytes_per_page: int,
    ) -> int:
        """Write one crash-safe checkpoint; returns bytes written. The
        ``checksums`` are the SPILL-TIME stamps (hex) — this method never
        re-hashes page bytes. Raises ``DurableError`` on any failure
        (counted); a failed checkpoint leaves no manifest, so the entry
        simply does not exist — the session stays restorable from its
        owner until a later attempt succeeds."""
        digest = str(digest)
        if len(pages_raw) != len(checksums) or not pages_raw:
            raise DurableError(
                f"checkpoint {digest}: {len(pages_raw)} page(s) vs "
                f"{len(checksums)} checksum(s)"
            )
        t0 = time.perf_counter()
        fault = self._fault
        try:
            if fault is not None and fault.fires("disk-full"):
                raise DurableError(
                    f"durable volume full ({self.root}) [injected: "
                    "disk-full]"
                )
            if fault is not None and fault.fires("disk-stall"):
                fault.stall("disk-stall")
            frames: list[dict] = [{
                "seq": 0, "kind": "begin", "length": int(length),
                "digest": digest, "pages": len(pages_raw),
                "page_size": int(page_size),
                "bytes_per_page": int(bytes_per_page),
                "tier": "durable",
                "prompt_tokens": [int(t) for t in tokens],
            }]
            for i, (raw, sum_hex) in enumerate(zip(pages_raw, checksums)):
                frames.append({
                    "seq": i + 1, "kind": "page", "i": i,
                    "raw": bytes(raw), "checksum": str(sum_hex),
                })
            frames.append({
                "seq": len(pages_raw) + 1, "kind": "commit",
                "pages_sent": len(pages_raw), "state": {},
            })
            body = wire.encode_mig_stream(frames)
            data_path = self._data_path(digest)
            tmp = data_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, data_path)
            # fault drills AFTER the write: the on-disk artifact is what a
            # real torn write / bit rot leaves, and the restore path must
            # read it as a dead entry — the manifest stays valid on purpose
            if fault is not None and fault.fires("disk-torn"):
                with open(data_path, "r+b") as f:
                    f.truncate(max(len(wire.KVMIG2_PREAMBLE) + 4,
                                   int(len(body) * 0.6)))
            if fault is not None and fault.fires("disk-corrupt"):
                with open(data_path, "r+b") as f:
                    f.seek(len(body) - max(2, len(body) // 3))
                    b = f.read(1)
                    f.seek(-1, os.SEEK_CUR)
                    f.write(bytes([b[0] ^ 0xFF]))
            manifest = {
                "schema": MANIFEST_SCHEMA,
                "digest": digest,
                "length": int(length),
                "pages": len(pages_raw),
                "page_size": int(page_size),
                "bytes_per_page": int(bytes_per_page),
                "bytes": len(body),
                "checksums": [str(s) for s in checksums],
                "created": round(time.time(), 3),
            }
            mpath = self._manifest_path(digest)
            mtmp = mpath + ".tmp"
            with open(mtmp, "w") as f:
                json.dump(manifest, f, separators=(",", ":"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(mtmp, mpath)
            _fsync_dir(self.root)
        except DurableError:
            with self._lock:
                self.checkpoint_failures_total += 1
            raise
        except Exception as e:  # noqa: BLE001 — every disk failure counts
            with self._lock:
                self.checkpoint_failures_total += 1
            raise DurableError(
                f"checkpoint {digest} failed after "
                f"{(time.perf_counter() - t0) * 1e3:.1f} ms: {e}"
            ) from e
        with self._lock:
            self._index[digest] = manifest
            self.checkpoints_total += 1
            self.checkpoint_bytes_total += len(body)
        self._evict_to_cap()
        return len(body)

    def _evict_to_cap(self) -> None:
        if not self.max_bytes:
            return
        while True:
            with self._lock:
                total = sum(
                    int(m.get("bytes", 0)) for m in self._index.values()
                )
                if total <= self.max_bytes or not self._index:
                    return
                victim = min(
                    self._index,
                    key=lambda d: self._index[d].get("created", 0.0),
                )
                self._index.pop(victim, None)
                self.evictions_total += 1
            for path in (
                self._manifest_path(victim), self._data_path(victim)
            ):
                try:
                    os.unlink(path)
                except OSError:
                    pass
            log.info("durable tier evicted %s (cap %d bytes)",
                     victim, self.max_bytes)

    # -- restore (read) ----------------------------------------------------

    def restore(
        self, digest: str, timeout_s: Optional[float] = None
    ) -> dict:
        """Read + verify one checkpoint. Returns ``{"length", "tokens",
        "pages" (raw byte images), "checksums", "page_size",
        "bytes_per_page"}``. EVERY failure — missing entry, torn frame,
        CRC mismatch, stale manifest, deadline — marks the entry dead and
        raises ``DurableError``; per-page blake2b verification against the
        manifest stamps is the CALLER's job (it owns the leaf layout)."""
        digest = str(digest)
        with self._lock:
            manifest = self._index.get(digest)
        if manifest is None:
            with self._lock:
                self.restore_failures_total += 1
            raise DurableError(f"no durable entry for {digest}")
        deadline = (
            time.monotonic() + float(timeout_s) if timeout_s else None
        )
        fault = self._fault
        try:
            if fault is not None and fault.fires("disk-stall"):
                fault.stall("disk-stall")
            if deadline is not None and time.monotonic() > deadline:
                raise DurableError(
                    f"restore {digest} missed its {timeout_s}s deadline "
                    "(stalled volume)"
                )
            max_payload = min(
                MAX_PAGE_BYTES, max(1, int(manifest["bytes_per_page"])) * 2
            )
            pages: list[bytes] = []
            begin: Optional[dict] = None
            committed = False
            with open(self._data_path(digest), "rb") as f:
                preamble = f.read(len(wire.KVMIG2_PREAMBLE))
                if preamble != wire.KVMIG2_PREAMBLE:
                    raise DurableError(
                        f"bad checkpoint preamble {preamble!r}"
                    )
                for frame in wire.decode_mig_frames(f.read, max_payload):
                    if frame["kind"] == "begin":
                        begin = frame
                    elif frame["kind"] == "page":
                        i = int(frame["i"])
                        if i != len(pages):
                            raise DurableError(
                                f"page {i} out of order "
                                f"(expected {len(pages)})"
                            )
                        sums = manifest["checksums"]
                        if frame["checksum"] != sums[i]:
                            raise DurableError(
                                f"page {i} frame stamp does not match the "
                                "manifest (stale manifest or foreign data)"
                            )
                        pages.append(frame["raw"])
                    elif frame["kind"] == "commit":
                        committed = True
            if begin is None or not committed:
                raise DurableError(
                    "checkpoint stream has no begin/commit frame "
                    "(torn write)"
                )
            if begin.get("digest") != digest:
                raise DurableError(
                    f"checkpoint begins with digest "
                    f"{begin.get('digest')!r}, wanted {digest}"
                )
            if len(pages) != int(manifest["pages"]):
                raise DurableError(
                    f"checkpoint carries {len(pages)} page(s), manifest "
                    f"says {manifest['pages']}"
                )
            if deadline is not None and time.monotonic() > deadline:
                raise DurableError(
                    f"restore {digest} missed its {timeout_s}s deadline"
                )
        except DurableError as e:
            self._mark_dead(digest, str(e))
            with self._lock:
                self.restore_failures_total += 1
            raise
        except (OSError, wire.WireError, ValueError, KeyError) as e:
            self._mark_dead(digest, str(e))
            with self._lock:
                self.restore_failures_total += 1
            raise DurableError(
                f"restore {digest} failed: {e}"
            ) from e
        nbytes = sum(len(p) for p in pages)
        with self._lock:
            self.restores_total += 1
            self.restore_bytes_total += nbytes
            # touch for LRU: restored-recently is the worst eviction victim
            manifest["created"] = round(time.time(), 3)
        return {
            "length": int(manifest["length"]),
            "tokens": list(begin.get("prompt_tokens") or []),
            "pages": pages,
            "checksums": list(manifest["checksums"]),
            "page_size": int(manifest["page_size"]),
            "bytes_per_page": int(manifest["bytes_per_page"]),
        }

    # -- replica hibernation ----------------------------------------------

    def write_hibernation(
        self,
        replica_id: str,
        digests: Iterable[str],
        compile_cache_dir: Optional[str] = None,
    ) -> str:
        """The replica-level hibernation record: which replica went down
        on purpose, what it checkpointed, and where its compile cache
        lives — the resurrection drill's evidence that a clean exit (not
        a crash) produced this directory. Same temp+fsync+rename
        discipline as every other write here."""
        doc = {
            "schema": HIBERNATE_SCHEMA,
            "replica": str(replica_id),
            "at": round(time.time(), 3),
            "digests": sorted(str(d) for d in digests),
            "compile_cache_dir": compile_cache_dir,
        }
        path = os.path.join(self.root, HIBERNATE_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.root)
        return path

    def read_hibernation(self) -> Optional[dict]:
        try:
            with open(os.path.join(self.root, HIBERNATE_NAME)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(doc, dict) or doc.get("schema") != HIBERNATE_SCHEMA:
            return None
        return doc

    # -- stats -------------------------------------------------------------

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "durable-entries": len(self._index),
                "durable-bytes-on-disk": sum(
                    int(m.get("bytes", 0)) for m in self._index.values()
                ),
                "durable-checkpoints-total": self.checkpoints_total,
                "durable-checkpoint-bytes-total": self.checkpoint_bytes_total,
                "durable-checkpoint-failures-total":
                    self.checkpoint_failures_total,
                "durable-restores-total": self.restores_total,
                "durable-restore-bytes-total": self.restore_bytes_total,
                "durable-restore-failures-total": self.restore_failures_total,
                "durable-dead-entries-total": self.dead_entries_total,
                "durable-evictions-total": self.evictions_total,
            }

    @staticmethod
    def empty_stats() -> dict[str, int]:
        """The stats() keys, all zero — engines with the tier off still
        publish the block (exporters set gauges unconditionally)."""
        return {
            "durable-entries": 0,
            "durable-bytes-on-disk": 0,
            "durable-checkpoints-total": 0,
            "durable-checkpoint-bytes-total": 0,
            "durable-checkpoint-failures-total": 0,
            "durable-restores-total": 0,
            "durable-restore-bytes-total": 0,
            "durable-restore-failures-total": 0,
            "durable-dead-entries-total": 0,
            "durable-evictions-total": 0,
        }
