"""Binary fleet wire v2 (``lstpu-kvmig-v2`` / ``lstpu-frames-v2``).

The v1 wire ships every replica-to-replica byte as NDJSON with base64
page payloads — a +33% encoding tax plus a per-line JSON parse on the
hot path (~0.5 GiB of pure overhead on a 32k-token int8 prefix
migration, ROADMAP 2c). v2 splits the wire into STREAM's two planes
(arxiv 2606.13968): control frames stay small structured records
(fixed prelude + CRC32, headers only where a record genuinely varies),
data-plane payloads ship as raw leaf bytes at native width — int8
pools move int8, checksums unchanged.

Frame layout (docs/SERVING.md §21), all integers little-endian:

    prelude   ``<HBBIIII`` = magic u16 | kind u8 | flags u8 | seq u32 |
              header_len u32 | payload_len u32 | crc32 u32
              (CRC32 over header ++ payload)
    header    kind-specific record (page: ``<I16s`` index + blake2b-16
              checksum; begin/commit/end/error: a small JSON record —
              once per TRANSFER, never per page/token)
    payload   raw bytes (page: concatenated ``jax.tree.leaves`` blocks
              at native dtype width; begin/tokens: packed ``<i`` int32)

Each stream/body opens with an 8-byte preamble (``LSTPUKV2`` /
``LSTPUFR2``) so a receiver can sniff the codec — a v1 NDJSON body
always starts with ``{``, never with these. Both declared lengths are
bounds-checked BEFORE any allocation: a corrupt or hostile length
prefix raises ``WireError`` (the §10-satellite hardening — the receiver
never allocates unbounded host memory from a wire-supplied length), and
a short read raises too — a truncated stream is a dead hop, never a
hang (the transport's socket timeout bounds every read underneath).

The codec translates to/from the SAME dict frame shapes the v1 modules
use (serving/migrate.py, serving/fleet.py), so checksum discipline,
seq validation and the §17/§18 failure ladders are one code path across
both protocols; only the bytes on the wire differ. Version negotiation
rides the ``caps`` beacon field (``kvmig2`` / ``frames2`` / ``p2p``) —
v1 NDJSON remains the automatic fallback for legacy peers.
"""

from __future__ import annotations

import base64
import json
import struct
import threading
import zlib
from typing import Any, Callable, Iterator, Optional

MIG_SCHEMA_V2 = "lstpu-kvmig-v2"
FRAME_SCHEMA_V2 = "lstpu-frames-v2"

# 8-byte stream preambles, written once per stream/body before the first
# frame — the receiver's codec sniff (v1 NDJSON starts with b"{")
KVMIG2_PREAMBLE = b"LSTPUKV2"
FRAMES2_PREAMBLE = b"LSTPUFR2"

# per-frame prelude: magic u16 | kind u8 | flags u8 | seq u32 |
# header_len u32 | payload_len u32 | crc32 u32 (over header ++ payload)
PRELUDE = struct.Struct("<HBBIIII")
KVMIG2_MAGIC = 0x4B32  # "K2"
FRAMES2_MAGIC = 0x4632  # "F2"

# control headers are small fixed records or one compact JSON dict per
# TRANSFER — anything bigger is a corrupt or hostile length prefix
MAX_HEADER_BYTES = 1 << 16
# token-stream payloads are packed int32 token ids; one frame never
# legitimately carries more than this (the engine chunks far smaller)
FRAMES2_MAX_PAYLOAD = 1 << 20

# lstpu-kvmig-v2 frame kinds
MIG_BEGIN, MIG_PAGE, MIG_COMMIT = 1, 2, 3
# lstpu-frames-v2 frame kinds
FR_TOKENS, FR_HEARTBEAT, FR_END, FR_ERROR = 1, 2, 3, 4

# tokens-frame flag bit 0: header carries the host-mirrored DFA state
# (``<i``) for constrained-stream resume (§18)
FLAG_DFA_STATE = 0x01

_PAGE_HEADER = struct.Struct("<I16s")  # page index + blake2b-16 checksum


class WireError(RuntimeError):
    """A v2 binary wire violation (truncated prelude, CRC mismatch,
    oversized declared length, unknown magic/kind). Receivers treat it
    exactly like corrupt NDJSON: the hop/transfer is dead — callers map
    it to ReplicaError (stream) or MigrationError (migration) and fall
    back; it never implies lost KV and never hangs a reader."""


# ---------------------------------------------------------------------------
# Wire byte accounting (the fleet_wire_bytes_total{proto} counters):
# counted at the SENDING side only — one count per byte fleet-wide, and
# the in-process test ring still sees both directions.
# ---------------------------------------------------------------------------

_COUNT_LOCK = threading.Lock()
_WIRE_BYTES: dict[str, int] = {"v1": 0, "v2": 0}


def count_wire_bytes(proto: str, n: int) -> None:
    if proto not in _WIRE_BYTES:
        return
    with _COUNT_LOCK:
        _WIRE_BYTES[proto] += int(n)


def wire_stats() -> dict[str, int]:
    with _COUNT_LOCK:
        return dict(_WIRE_BYTES)


def reset_wire_stats() -> None:
    with _COUNT_LOCK:
        for k in _WIRE_BYTES:
            _WIRE_BYTES[k] = 0


# ---------------------------------------------------------------------------
# Core frame read/write
# ---------------------------------------------------------------------------


def _frame(magic: int, kind: int, flags: int, seq: int,
           header: bytes, payload: bytes) -> bytes:
    crc = zlib.crc32(payload, zlib.crc32(header))
    return (
        PRELUDE.pack(magic, kind, flags, seq, len(header), len(payload), crc)
        + header
        + payload
    )


def read_exact(read: Callable[[int], bytes], n: int) -> bytes:
    """Read exactly ``n`` bytes from ``read`` (a ``resp.read``-style
    callable that may return short). A premature EOF is a WireError — a
    truncated frame must read as a dead hop, never block forever (the
    transport's socket timeout bounds each underlying read)."""
    buf = b""
    while len(buf) < n:
        chunk = read(n - len(buf))
        if not chunk:
            raise WireError(
                f"truncated wire frame (wanted {n} bytes, got {len(buf)})"
            )
        buf += chunk
    return buf


def read_frame(
    read: Callable[[int], bytes],
    magic: int,
    max_payload: int,
    max_header: int = MAX_HEADER_BYTES,
) -> Optional[tuple[int, int, int, bytes, bytes]]:
    """Read one framed record: ``(kind, flags, seq, header, payload)``,
    or None at a clean end-of-stream (EOF exactly on a frame boundary).
    Both declared lengths are checked against their bounds BEFORE any
    read/allocation; the CRC covers header ++ payload."""
    head = b""
    while len(head) < PRELUDE.size:
        chunk = read(PRELUDE.size - len(head))
        if not chunk:
            if not head:
                return None
            raise WireError(
                f"truncated frame prelude ({len(head)} of "
                f"{PRELUDE.size} bytes)"
            )
        head += chunk
    got_magic, kind, flags, seq, hlen, plen, crc = PRELUDE.unpack(head)
    if got_magic != magic:
        raise WireError(
            f"bad frame magic 0x{got_magic:04x} (want 0x{magic:04x})"
        )
    if hlen > max_header:
        raise WireError(
            f"frame seq {seq} declares a {hlen}-byte header "
            f"(bound {max_header})"
        )
    if plen > max_payload:
        raise WireError(
            f"frame seq {seq} declares a {plen}-byte payload "
            f"(bound {max_payload})"
        )
    header = read_exact(read, hlen)
    payload = read_exact(read, plen)
    if zlib.crc32(payload, zlib.crc32(header)) != crc:
        raise WireError(f"frame seq {seq} failed its CRC32")
    return kind, flags, seq, header, payload


def _pack_tokens(tokens) -> bytes:
    toks = [int(t) for t in tokens]
    return struct.pack(f"<{len(toks)}i", *toks)


def _unpack_tokens(payload: bytes, what: str) -> list[int]:
    if len(payload) % 4:
        raise WireError(
            f"{what} payload ({len(payload)} bytes) is not int32-aligned"
        )
    return list(struct.unpack(f"<{len(payload) // 4}i", payload))


def _json_header(header: bytes, what: str) -> dict:
    try:
        doc = json.loads(header.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise WireError(f"{what} header undecodable ({e})") from e
    if not isinstance(doc, dict):
        raise WireError(f"{what} header is not a record")
    return doc


# ---------------------------------------------------------------------------
# lstpu-kvmig-v2: the migration/page-fetch wire
# ---------------------------------------------------------------------------


def encode_mig_frame(frame: dict) -> bytes:
    """One v1-shaped migration frame dict → its v2 binary encoding. Page
    payloads come from the frame's ``raw`` bytes (the native-width export
    path) or, for compatibility, by decoding its base64 ``data`` blocks."""
    kind = frame.get("kind")
    seq = int(frame.get("seq", 0))
    if kind == "begin":
        meta = {
            k: frame[k]
            for k in (
                "length", "digest", "pages", "page_size",
                "bytes_per_page", "tier",
            )
            if k in frame
        }
        header = json.dumps(meta, separators=(",", ":")).encode("utf-8")
        return _frame(
            KVMIG2_MAGIC, MIG_BEGIN, 0, seq, header,
            _pack_tokens(frame.get("prompt_tokens") or []),
        )
    if kind == "page":
        checksum = bytes.fromhex(str(frame.get("checksum") or ""))
        if len(checksum) != 16:
            raise WireError(
                f"page {frame.get('i')} checksum is {len(checksum)} bytes "
                "(want 16)"
            )
        header = _PAGE_HEADER.pack(int(frame.get("i", 0)), checksum)
        raw = frame.get("raw")
        if raw is None:
            raw = b"".join(
                base64.b64decode(b) for b in (frame.get("data") or [])
            )
        return _frame(KVMIG2_MAGIC, MIG_PAGE, 0, seq, header, bytes(raw))
    if kind == "commit":
        header = json.dumps(
            {
                "pages_sent": int(frame.get("pages_sent", 0)),
                "state": dict(frame.get("state") or {}),
            },
            separators=(",", ":"),
        ).encode("utf-8")
        return _frame(KVMIG2_MAGIC, MIG_COMMIT, 0, seq, header, b"")
    raise WireError(f"unknown migration frame kind {kind!r}")


def encode_mig_stream(frames) -> bytes:
    """A whole migration body — preamble + every frame — as one byte
    string. The durable tier (serving/durable.py) writes THIS to disk:
    the wire codec IS the checkpoint format, so a checkpointed session
    can be decoded by ``decode_mig_frames`` wherever it lands (local
    restore, P2P fetch, resurrection on a foreign replica) and every
    frame's CRC32 prelude doubles as torn-write detection."""
    return KVMIG2_PREAMBLE + b"".join(encode_mig_frame(f) for f in frames)


def decode_mig_frames(
    read: Callable[[int], bytes], max_payload: int,
) -> Iterator[dict]:
    """Decode a v2 migration body (AFTER its preamble) into the v1-shaped
    frame dicts ``bind_frames`` consumes — page payloads come out as one
    contiguous ``raw`` bytes field, split by the receiver pool's leaf
    layout at bind time. Stops after the commit frame; an EOF before it
    is simply the iterator ending (bind_frames' no-commit path calls that
    a cut wire)."""
    while True:
        rec = read_frame(read, KVMIG2_MAGIC, max_payload)
        if rec is None:
            return
        kind, _flags, seq, header, payload = rec
        if kind == MIG_BEGIN:
            meta = _json_header(header, "begin")
            yield {
                "v": MIG_SCHEMA_V2, "seq": seq, "kind": "begin",
                "prompt_tokens": _unpack_tokens(payload, "begin token"),
                **meta,
            }
        elif kind == MIG_PAGE:
            if len(header) != _PAGE_HEADER.size:
                raise WireError(
                    f"page frame seq {seq} header is {len(header)} bytes "
                    f"(want {_PAGE_HEADER.size})"
                )
            i, checksum = _PAGE_HEADER.unpack(header)
            yield {
                "seq": seq, "kind": "page", "i": int(i),
                "raw": payload, "checksum": checksum.hex(),
            }
        elif kind == MIG_COMMIT:
            meta = _json_header(header, "commit")
            yield {
                "seq": seq, "kind": "commit",
                "pages_sent": int(meta.get("pages_sent", 0)),
                "state": dict(meta.get("state") or {}),
            }
            return
        else:
            raise WireError(f"unknown kvmig2 frame kind {kind}")


# ---------------------------------------------------------------------------
# lstpu-frames-v2: the token-stream wire
# ---------------------------------------------------------------------------


def encode_stream_frame(frame: dict) -> bytes:
    """One §17 stream frame dict → its v2 binary encoding. Token chunks
    drop to a fixed packed layout (prelude + packed int32 ids, the DFA
    state as a 4-byte header when carried); the terminal end/error record
    keeps its JSON header — once per stream, off the hot path."""
    kind = frame.get("kind")
    seq = int(frame.get("seq", 0))
    if kind == "tokens":
        payload = _pack_tokens(frame.get("tokens") or [])
        dfa = frame.get("dfa_state")
        if dfa is None:
            return _frame(FRAMES2_MAGIC, FR_TOKENS, 0, seq, b"", payload)
        return _frame(
            FRAMES2_MAGIC, FR_TOKENS, FLAG_DFA_STATE, seq,
            struct.pack("<i", int(dfa)), payload,
        )
    if kind == "heartbeat":
        return _frame(FRAMES2_MAGIC, FR_HEARTBEAT, 0, seq, b"", b"")
    if kind in ("end", "error"):
        meta = {
            k: v for k, v in frame.items() if k not in ("seq", "kind", "v")
        }
        header = json.dumps(meta, separators=(",", ":")).encode("utf-8")
        fk = FR_END if kind == "end" else FR_ERROR
        return _frame(FRAMES2_MAGIC, fk, 0, seq, header, b"")
    raise WireError(f"unknown stream frame kind {kind!r}")


def decode_stream_frames(read: Callable[[int], bytes]) -> Iterator[dict]:
    """Decode a v2 token stream (AFTER its preamble) into the §17 frame
    dicts. Stops after the terminal end/error frame; an EOF before one is
    the iterator simply ending — the consumer's no-terminal-frame check
    calls that a dead hop, same as v1."""
    while True:
        rec = read_frame(read, FRAMES2_MAGIC, FRAMES2_MAX_PAYLOAD)
        if rec is None:
            return
        kind, flags, seq, header, payload = rec
        if kind == FR_TOKENS:
            frame: dict[str, Any] = {
                "seq": seq, "kind": "tokens",
                "tokens": _unpack_tokens(payload, "tokens"),
            }
            if flags & FLAG_DFA_STATE:
                if len(header) != 4:
                    raise WireError(
                        f"tokens frame seq {seq} DFA header is "
                        f"{len(header)} bytes (want 4)"
                    )
                frame["dfa_state"] = struct.unpack("<i", header)[0]
            yield frame
        elif kind == FR_HEARTBEAT:
            yield {"seq": seq, "kind": "heartbeat"}
        elif kind in (FR_END, FR_ERROR):
            meta = _json_header(header, "end" if kind == FR_END else "error")
            yield {
                "seq": seq,
                "kind": "end" if kind == FR_END else "error",
                **meta,
            }
            return
        else:
            raise WireError(f"unknown frames2 frame kind {kind}")
